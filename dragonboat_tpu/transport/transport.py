"""Transport orchestrator: per-target async send queues with batching and
circuit breakers.

cf. internal/transport/transport.go:188-557 — each remote NodeHost address
gets a lazily created queue + worker; the worker drains the queue into
MessageBatches (bounded bytes per batch), reconnecting through the pluggable
IRaftRPC. Send failures trip a per-target breaker and fan out Unreachable
notifications to every (cluster, node) resolving to that address.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, List, Optional

from ..core.rate import RateLimiter
from ..raftio import IMessageHandler, IRaftRPC
from ..settings import soft
from ..types import Message, MessageBatch, MessageType
from .nodes import Nodes

BIN_VER = 1


class _Breaker:
    """Minimal circuit breaker (cf. netutil/circuitbreaker usage
    transport.go:299-311): opens after consecutive failures, half-opens
    after a cooldown."""

    def __init__(self, threshold: int = 1, cooldown: float = 1.0) -> None:
        self._threshold = threshold
        self._cooldown = cooldown
        self._fails = 0
        self._opened_at = 0.0
        self._mu = threading.Lock()

    def ready(self) -> bool:
        with self._mu:
            if self._fails < self._threshold:
                return True
            return time.monotonic() - self._opened_at >= self._cooldown

    def success(self) -> None:
        with self._mu:
            self._fails = 0

    def fail(self) -> None:
        with self._mu:
            self._fails += 1
            if self._fails >= self._threshold:
                self._opened_at = time.monotonic()


class _SendQueue:
    """Per-target outbound queue: count-bounded by the queue itself and
    byte-bounded by a RateLimiter when NodeHostConfig.max_send_queue_size
    is set (cf. transport.go:170-185 sendQueueRateLimited — an unbounded
    byte backlog toward one dead peer would otherwise hold entry payloads
    alive indefinitely)."""

    def __init__(self, maxlen: int, max_bytes: int = 0) -> None:
        self.q: "queue.Queue[Optional[Message]]" = queue.Queue(maxlen)
        self.thread: Optional[threading.Thread] = None
        self.rl = RateLimiter(max_bytes)
        # RateLimiter is not thread-safe; producer (engine threads) and
        # consumer (queue worker) both touch it
        self._rl_mu = threading.Lock()

    def try_put(self, m: Message) -> bool:
        # account BEFORE enqueueing: the consumer may dequeue and decrease
        # the instant put_nowait returns, and a decrease-before-increase
        # pair would clamp at 0 then leak the increase forever
        sz = _msg_size(m)
        with self._rl_mu:
            if self.rl.enabled and self.rl.rate_limited():
                return False
            self.rl.increase(sz)
        try:
            self.q.put_nowait(m)
        except queue.Full:
            with self._rl_mu:
                self.rl.decrease(sz)
            return False
        return True

    def taken(self, m: Message) -> None:
        with self._rl_mu:
            self.rl.decrease(_msg_size(m))


class Transport:
    """cf. internal/transport/transport.go Transport."""

    def __init__(
        self,
        source_address: str,
        deployment_id: int,
        rpc_factory: Callable[..., IRaftRPC],
        resolver: Optional[Nodes] = None,
        send_queue_length: int = 0,
        max_send_queue_bytes: int = 0,
    ) -> None:
        self.source_address = source_address
        self.deployment_id = deployment_id
        self.nodes = resolver or Nodes()
        self._handler: Optional[IMessageHandler] = None
        self._queues: Dict[str, _SendQueue] = {}
        self._breakers: Dict[str, _Breaker] = {}
        self._mu = threading.Lock()
        self._stopped = threading.Event()
        self._qlen = send_queue_length or 1024
        self._qbytes = max_send_queue_bytes
        self._metrics = {
            "sent": 0,
            "send_failures": 0,
            "received": 0,
            "connect_attempts": 0,
            "connect_failures": 0,
        }
        self.rpc: IRaftRPC = rpc_factory(
            request_handler=self._handle_request,
            chunk_handler=self._handle_chunk,
        )
        # snapshot chunk sink installed by the snapshot subsystem
        self._chunk_sink: Optional[Callable] = None
        # monkey-test hooks (cf. transport.go:281-289)
        self._pre_send_batch_hook: Optional[Callable] = None

    # -- lifecycle -------------------------------------------------------------
    def start(self) -> None:
        self.rpc.start()

    def stop(self) -> None:
        self._stopped.set()
        with self._mu:
            qs = list(self._queues.values())
            self._queues.clear()
        for sq in qs:
            try:
                sq.q.put_nowait(None)
            except queue.Full:
                pass
        for sq in qs:
            if sq.thread is not None:
                sq.thread.join(timeout=2)
        self.rpc.stop()

    def set_message_handler(self, handler: IMessageHandler) -> None:
        self._handler = handler

    def set_chunk_sink(self, sink: Callable) -> None:
        self._chunk_sink = sink

    def set_pre_send_batch_hook(self, hook: Optional[Callable]) -> None:
        self._pre_send_batch_hook = hook

    def metrics(self) -> dict:
        return dict(self._metrics)

    # -- receive path ----------------------------------------------------------
    def _handle_request(self, batch: MessageBatch) -> None:
        if self._handler is None:
            return
        if self.deployment_id and batch.deployment_id and (
            batch.deployment_id != self.deployment_id
        ):
            return  # cross-deployment traffic dropped (transport.go:327-340)
        if batch.source_address:
            for m in batch.requests:
                if m.from_:
                    self.nodes.add_remote_address(
                        m.cluster_id, m.from_, batch.source_address
                    )
        self._metrics["received"] += len(batch.requests)
        self._handler.handle_message_batch(batch)

    def _handle_chunk(self, chunk) -> bool:
        if self._chunk_sink is None:
            return False
        return self._chunk_sink(chunk)

    # -- send path ---------------------------------------------------------------
    def send(self, m: Message) -> bool:
        """Queue a message for async delivery (cf. asyncSend
        transport.go:400-451). Returns False when dropped."""
        addr = self.nodes.resolve(m.cluster_id, m.to)
        if addr is None:
            self._notify_unreachable_one(m.cluster_id, m.to)
            return False
        return self.send_to_address(addr, m)

    def send_many(self, msgs) -> int:
        """Queue many messages in one pass: resolve and group by target
        address first, then amortize the breaker check and queue lookup
        over each target's whole batch (the engine's columnar fan-out
        emits one such batch per step instead of per-message send()
        calls). Returns how many messages were queued."""
        if not msgs:
            return 0
        by_addr: Dict[str, List[Message]] = {}
        for m in msgs:
            addr = self.nodes.resolve(m.cluster_id, m.to)
            if addr is None:
                self._notify_unreachable_one(m.cluster_id, m.to)
                continue
            by_addr.setdefault(addr, []).append(m)
        sent = 0
        if self._stopped.is_set():
            return 0
        for addr, ms in by_addr.items():
            if not self._get_breaker(addr).ready():
                continue
            sq = self._get_queue(addr)
            for m in ms:
                if sq.try_put(m):
                    sent += 1
        return sent

    def send_to_address(self, addr: str, m: Message) -> bool:
        if self._stopped.is_set():
            return False
        breaker = self._get_breaker(addr)
        if not breaker.ready():
            return False
        sq = self._get_queue(addr)
        return sq.try_put(m)

    def _get_breaker(self, addr: str) -> _Breaker:
        with self._mu:
            b = self._breakers.get(addr)
            if b is None:
                b = self._breakers[addr] = _Breaker()
            return b

    def _get_queue(self, addr: str) -> _SendQueue:
        with self._mu:
            sq = self._queues.get(addr)
            if sq is None:
                sq = self._queues[addr] = _SendQueue(self._qlen, self._qbytes)
                sq.thread = threading.Thread(
                    target=self._process_queue,
                    args=(addr, sq),
                    name=f"transport-{addr}",
                    daemon=True,
                )
                sq.thread.start()
            return sq

    def _process_queue(self, addr: str, sq: _SendQueue) -> None:
        """Per-target worker: connect lazily, drain queue into batches
        (cf. connectAndProcess/processQueue transport.go:453-557)."""
        conn = None
        breaker = self._get_breaker(addr)
        try:
            while not self._stopped.is_set():
                try:
                    m = sq.q.get(timeout=0.5)
                except queue.Empty:
                    continue
                if m is None:
                    return
                sq.taken(m)
                requests = [m]
                size = _msg_size(m)
                while size < soft.max_message_batch_size:
                    try:
                        m2 = sq.q.get_nowait()
                    except queue.Empty:
                        break
                    if m2 is None:
                        return
                    sq.taken(m2)
                    requests.append(m2)
                    size += _msg_size(m2)
                # the message that crossed the byte cap ships in a second
                # batch so no single wire write exceeds the cap
                # (cf. transport.go:533-541 twoBatch)
                if size >= soft.max_message_batch_size and len(requests) > 1:
                    splits = [requests[:-1], requests[-1:]]
                else:
                    splits = [requests]
                for reqs in splits:
                    batch = MessageBatch(
                        requests=reqs,
                        deployment_id=self.deployment_id,
                        source_address=self.source_address,
                        bin_ver=BIN_VER,
                    )
                    if self._pre_send_batch_hook is not None:
                        if not self._pre_send_batch_hook(batch):
                            continue  # dropped by chaos hook
                    try:
                        if conn is None:
                            self._metrics["connect_attempts"] += 1
                            conn = self.rpc.get_connection(addr)
                        conn.send_message_batch(batch)
                        breaker.success()
                        self._metrics["sent"] += len(batch.requests)
                    except Exception:
                        self._metrics["send_failures"] += len(batch.requests)
                        self._metrics["connect_failures"] += 1
                        if conn is not None:
                            try:
                                conn.close()
                            except Exception:
                                pass
                            conn = None
                        breaker.fail()
                        self._notify_unreachable(addr)
                        # drop queued traffic for the cooldown window
                        time.sleep(0.05)
        finally:
            if conn is not None:
                try:
                    conn.close()
                except Exception:
                    pass

    # -- failure fanout ---------------------------------------------------------
    def _notify_unreachable(self, addr: str) -> None:
        """cf. transport.go:371-386 + nodehost.go:2034-2055."""
        if self._handler is None:
            return
        for cid, nid in self.nodes.reverse_resolve(addr):
            self._handler.handle_unreachable(cid, nid)

    def _notify_unreachable_one(self, cluster_id: int, node_id: int) -> None:
        if self._handler is not None:
            self._handler.handle_unreachable(cluster_id, node_id)


def _msg_size(m: Message) -> int:
    return 64 + sum(len(e.cmd) + 48 for e in m.entries)


__all__ = ["Transport", "BIN_VER"]
