"""Transport orchestrator: per-target async send queues with batching and
circuit breakers.

cf. internal/transport/transport.go:188-557 — each remote NodeHost address
gets a lazily created queue + worker; the worker drains the queue into
MessageBatches (bounded bytes per batch), reconnecting through the pluggable
IRaftRPC. Send failures trip a per-target breaker and fan out Unreachable
notifications to every (cluster, node) resolving to that address.

Resilience hardening on top of the reference shape:

  * `_Breaker` backs off exponentially with jitter and half-opens with a
    single in-flight probe (cf. netutil/circuitbreaker usage
    transport.go:299-311) instead of the fixed 1s cooldown — a flapping
    peer costs O(log) reconnect storms, and jitter decorrelates many
    senders hammering the same recovered target.
  * `_SendQueue` is class-prioritized: control-plane traffic (heartbeats,
    votes, TimeoutNow) is never queued behind — or pushed out by — bulk
    replication under backpressure. When the queue is full, an arriving
    urgent message evicts the oldest bulk message; urgent traffic is also
    exempt from the byte rate limiter (it is tiny and liveness-critical:
    a follower that cannot hear heartbeats behind a bulk backlog calls a
    needless election).
  * `metrics()` exposes breaker/queue state so chaos runs can assert the
    above (e.g. "no heartbeat-class message was ever dropped from a full
    queue").
"""
from __future__ import annotations

import threading
import time
import random
import zlib
from collections import deque
from typing import Callable, Dict, List, Optional

from ..core.rate import RateLimiter
from ..raftio import IMessageHandler, IRaftRPC
from ..settings import soft
from ..trace import flight_recorder
from ..types import Message, MessageBatch, MessageType
from .nodes import Nodes

BIN_VER = 1

# control-plane message classes that keep a cluster live; everything else
# (Replicate, InstallSnapshot, ...) is bulk and yields to them
URGENT_TYPES = frozenset(
    {
        MessageType.HEARTBEAT,
        MessageType.HEARTBEAT_RESP,
        MessageType.REQUEST_VOTE,
        MessageType.REQUEST_VOTE_RESP,
        MessageType.REQUEST_PREVOTE,
        MessageType.REQUEST_PREVOTE_RESP,
        MessageType.TIMEOUT_NOW,
    }
)


class _Breaker:
    """Circuit breaker with exponential backoff, jittered cooldowns and a
    half-open single-probe state.

    States: CLOSED (traffic flows; consecutive failures >= threshold trip
    it) and OPEN. While OPEN and cooling, enqueue and probe are both
    refused. Once the cooldown elapses the breaker is effectively
    half-open: traffic may enqueue again and the queue worker is granted
    ONE in-flight probe send; the probe's outcome either closes the
    breaker (success) or re-opens it with a doubled, jittered cooldown.
    """

    CLOSED, OPEN = 0, 1

    def __init__(
        self,
        threshold: int = 1,
        base_cooldown: float = 0.5,
        max_cooldown: float = 15.0,
        jitter: float = 0.25,
        rng: Optional[random.Random] = None,
        clock: Callable[[], float] = time.monotonic,
        name: str = "",
    ) -> None:
        self._name = name  # target address, for flight-recorder events
        self._threshold = threshold
        self._base = base_cooldown
        self._max = max_cooldown
        self._jitter = jitter
        self._rng = rng or random.Random()
        self._clock = clock
        self._mu = threading.Lock()
        self._state = self.CLOSED
        self._fails = 0
        self._nominal = base_cooldown  # pre-jitter cooldown, doubles per reopen
        self._cooldown = 0.0
        self._opened_at = 0.0
        self._probe_inflight = False
        # counters for metrics()/tests
        self.opens = 0
        self.probes = 0
        self.probe_failures = 0

    def _jittered(self, nominal: float) -> float:
        j = self._jitter
        return nominal * (1.0 + j * (2.0 * self._rng.random() - 1.0))

    def _cooled_locked(self) -> bool:
        return self._clock() - self._opened_at >= self._cooldown

    # -- producer side -----------------------------------------------------
    def allow_enqueue(self) -> bool:
        with self._mu:
            return self._state == self.CLOSED or self._cooled_locked()

    # legacy name used by older call sites/tests
    ready = allow_enqueue

    # -- worker (wire-write) side ------------------------------------------
    def allow_probe(self) -> bool:
        """CLOSED: always. OPEN: one probe once the cooldown elapsed."""
        with self._mu:
            if self._state == self.CLOSED:
                return True
            if not self._cooled_locked() or self._probe_inflight:
                return False
            self._probe_inflight = True
            self.probes += 1
            return True

    def success(self) -> None:
        with self._mu:
            reclosed = self._state == self.OPEN
            self._state = self.CLOSED
            self._fails = 0
            self._nominal = self._base
            self._probe_inflight = False
        if reclosed:
            flight_recorder().record("breaker_closed", addr=self._name)

    def fail(self) -> None:
        with self._mu:
            tripped = False
            if self._state == self.CLOSED:
                self._fails += 1
                if self._fails < self._threshold:
                    return
                self._state = self.OPEN
                self.opens += 1
                self._nominal = self._base
                tripped = True
            else:
                # a failed half-open probe (or a straggler failure while
                # open): back off exponentially, re-arm the cooldown
                if self._probe_inflight:
                    self.probe_failures += 1
                self._nominal = min(self._max, self._nominal * 2.0)
            self._probe_inflight = False
            self._cooldown = self._jittered(self._nominal)
            self._opened_at = self._clock()
            cooldown = self._cooldown
        if tripped:
            flight_recorder().record(
                "breaker_open", addr=self._name, cooldown_s=round(cooldown, 4)
            )

    # -- introspection -----------------------------------------------------
    def is_open(self) -> bool:
        with self._mu:
            return self._state == self.OPEN

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "state": "open" if self._state == self.OPEN else "closed",
                "consecutive_failures": self._fails,
                "cooldown_s": self._cooldown,
                "nominal_cooldown_s": self._nominal,
                "opens": self.opens,
                "probes": self.probes,
                "probe_failures": self.probe_failures,
            }


class _SendQueue:
    """Per-target outbound queue, class-prioritized and byte-bounded.

    Two deques under one condition variable: urgent control-plane traffic
    (URGENT_TYPES) and bulk. Consumers always drain urgent first. The
    count bound covers both classes; byte accounting via RateLimiter
    applies to bulk only (cf. transport.go:170-185 sendQueueRateLimited —
    an unbounded byte backlog toward one dead peer would otherwise hold
    entry payloads alive indefinitely; urgent messages carry no payload).
    Under a full queue an urgent arrival evicts the OLDEST bulk message —
    replication recovers by retransmission, a lost heartbeat costs an
    election."""

    __slots__ = (
        "_maxlen",
        "_urgent",
        "_bulk",
        "_cv",
        "_closed",
        "name",
        "rl",
        "thread",
        "evicted_bulk",
        "dropped_bulk",
        "dropped_urgent",
    )

    def __init__(self, maxlen: int, max_bytes: int = 0, name: str = "") -> None:
        self.name = name  # target address, for flight-recorder events
        self._maxlen = maxlen
        self._urgent: deque = deque()
        self._bulk: deque = deque()
        self._cv = threading.Condition()
        self._closed = False
        self.rl = RateLimiter(max_bytes)
        self.thread: Optional[threading.Thread] = None
        self.evicted_bulk = 0  # bulk pushed out to admit urgent
        self.dropped_bulk = 0  # bulk refused (full queue / byte limit)
        self.dropped_urgent = 0  # urgent refused (queue full of urgent)

    def _admit_locked(self, m: Message) -> bool:
        urgent = m.type in URGENT_TYPES
        if not urgent and self.rl.enabled and self.rl.rate_limited():
            self.dropped_bulk += 1
            return False
        if len(self._urgent) + len(self._bulk) >= self._maxlen:
            if urgent and self._bulk:
                ev = self._bulk.popleft()
                self.rl.decrease(_msg_size(ev))
                self.evicted_bulk += 1
                # sampled breadcrumb: first eviction + every 64th, so a
                # sustained backpressure storm costs O(storm/64) events
                if (self.evicted_bulk - 1) % 64 == 0:
                    flight_recorder().record(  # hot-path: ok (1-in-64)
                        "sendq_evicted_bulk", addr=self.name,
                        total=self.evicted_bulk,
                    )
            elif urgent:
                self.dropped_urgent += 1
                # always recorded: a dropped heartbeat/vote is the event a
                # postmortem is looking for (it should ~never happen —
                # the queue must fill with urgent traffic alone first)
                flight_recorder().record(  # hot-path: ok (anomaly-only)
                    "sendq_dropped_urgent", addr=self.name,
                    total=self.dropped_urgent,
                )
                return False
            else:
                self.dropped_bulk += 1
                return False
        if urgent:
            self._urgent.append(m)  # never charged to the byte budget
        else:
            self.rl.increase(_msg_size(m))
            self._bulk.append(m)
        return True

    def try_put(self, m: Message) -> bool:
        with self._cv:
            if self._closed:
                return False
            ok = self._admit_locked(m)
            if ok:
                self._cv.notify()
            return ok

    def put_many(self, msgs: List[Message]) -> int:
        """Admit a whole target batch under ONE lock acquisition + ONE
        consumer wake (the engine's columnar fan-out emits one such batch
        per destination per step)."""
        with self._cv:
            if self._closed:
                return 0
            n = 0
            for m in msgs:
                if self._admit_locked(m):
                    n += 1
            if n:
                self._cv.notify()
            return n

    def _pop_locked(self) -> Optional[Message]:
        if self._urgent:
            return self._urgent.popleft()  # urgent was never rl-charged
        if self._bulk:
            m = self._bulk.popleft()
            self.rl.decrease(_msg_size(m))
            return m
        return None

    def get(self, timeout: float) -> Optional[Message]:
        """Urgent-first pop; None on timeout or close."""
        with self._cv:
            if not self._urgent and not self._bulk and not self._closed:
                self._cv.wait(timeout)
            return self._pop_locked()

    def get_nowait(self) -> Optional[Message]:
        with self._cv:
            return self._pop_locked()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    @property
    def closed(self) -> bool:
        return self._closed

    def depths(self) -> tuple:
        with self._cv:
            return len(self._urgent), len(self._bulk)


class Transport:
    """cf. internal/transport/transport.go Transport."""

    def __init__(
        self,
        source_address: str,
        deployment_id: int,
        rpc_factory: Callable[..., IRaftRPC],
        resolver: Optional[Nodes] = None,
        send_queue_length: int = 0,
        max_send_queue_bytes: int = 0,
    ) -> None:
        self.source_address = source_address
        self.deployment_id = deployment_id
        self.nodes = resolver or Nodes()
        self._handler: Optional[IMessageHandler] = None
        self._queues: Dict[str, _SendQueue] = {}
        self._breakers: Dict[str, _Breaker] = {}
        self._mu = threading.Lock()
        self._stopped = threading.Event()
        self._qlen = send_queue_length or 1024
        self._qbytes = max_send_queue_bytes
        self._metrics = {
            "sent": 0,
            "send_failures": 0,
            "received": 0,
            "connect_attempts": 0,
            "connect_failures": 0,
            "dropped_while_open": 0,
        }
        self.rpc: IRaftRPC = rpc_factory(
            request_handler=self._handle_request,
            chunk_handler=self._handle_chunk,
        )
        # snapshot chunk sink installed by the snapshot subsystem
        self._chunk_sink: Optional[Callable] = None
        # monkey-test hooks (cf. transport.go:281-289); the hook may also
        # MUTATE batch.requests in place (FaultPlane drop/duplicate/
        # reorder run per-message inside the batch)
        self._pre_send_batch_hook: Optional[Callable] = None

    # -- lifecycle -------------------------------------------------------------
    def start(self) -> None:
        self.rpc.start()

    def stop(self) -> None:
        self._stopped.set()
        with self._mu:
            qs = list(self._queues.values())
            self._queues.clear()
        for sq in qs:
            sq.close()
        for sq in qs:
            if sq.thread is not None:
                sq.thread.join(timeout=2)
        self.rpc.stop()

    def set_message_handler(self, handler: IMessageHandler) -> None:
        self._handler = handler

    def set_chunk_sink(self, sink: Callable) -> None:
        self._chunk_sink = sink

    def set_pre_send_batch_hook(self, hook: Optional[Callable]) -> None:
        self._pre_send_batch_hook = hook

    def metrics(self) -> dict:
        """Flat numeric snapshot: wire counters plus aggregate breaker and
        queue state (per-address detail via breaker_states())."""
        out = dict(self._metrics)
        with self._mu:
            breakers = list(self._breakers.values())
            queues = list(self._queues.values())
        out["breakers_open"] = sum(1 for b in breakers if b.is_open())
        out["breaker_opens"] = sum(b.opens for b in breakers)
        out["breaker_probes"] = sum(b.probes for b in breakers)
        out["breaker_probe_failures"] = sum(
            b.probe_failures for b in breakers
        )
        qu = qb = 0
        ev = db = du = 0
        for sq in queues:
            u, b = sq.depths()
            qu += u
            qb += b
            ev += sq.evicted_bulk
            db += sq.dropped_bulk
            du += sq.dropped_urgent
        out["queued_urgent"] = qu
        out["queued_bulk"] = qb
        out["queue_evicted_bulk"] = ev
        out["queue_dropped_bulk"] = db
        out["queue_dropped_urgent"] = du
        return out

    def breaker_states(self) -> Dict[str, dict]:
        with self._mu:
            breakers = list(self._breakers.items())
        return {addr: b.snapshot() for addr, b in breakers}

    # -- receive path ----------------------------------------------------------
    def _handle_request(self, batch: MessageBatch) -> None:
        if self._handler is None:
            return
        if self.deployment_id and batch.deployment_id and (
            batch.deployment_id != self.deployment_id
        ):
            return  # cross-deployment traffic dropped (transport.go:327-340)
        if batch.source_address:
            for m in batch.requests:
                if m.from_:
                    self.nodes.add_remote_address(
                        m.cluster_id, m.from_, batch.source_address
                    )
        self._metrics["received"] += len(batch.requests)
        self._handler.handle_message_batch(batch)

    def _handle_chunk(self, chunk) -> bool:
        if self._chunk_sink is None:
            return False
        return self._chunk_sink(chunk)

    # -- send path ---------------------------------------------------------------
    def send(self, m: Message) -> bool:
        """Queue a message for async delivery (cf. asyncSend
        transport.go:400-451). Returns False when dropped."""
        addr = self.nodes.resolve(m.cluster_id, m.to)
        if addr is None:
            self._notify_unreachable_one(m.cluster_id, m.to)
            return False
        return self.send_to_address(addr, m)

    def send_many(self, msgs) -> int:
        """Queue many messages in one pass: resolve and group by target
        address first, then amortize the breaker check, queue lookup AND
        the queue lock over each target's whole batch (the engine's
        columnar fan-out emits one such batch per step instead of
        per-message send() calls). Returns how many messages were
        queued."""
        if not msgs:
            return 0
        by_addr: Dict[str, List[Message]] = {}
        for m in msgs:
            addr = self.nodes.resolve(m.cluster_id, m.to)
            if addr is None:
                self._notify_unreachable_one(m.cluster_id, m.to)
                continue
            by_addr.setdefault(addr, []).append(m)
        sent = 0
        if self._stopped.is_set():
            return 0
        for addr, ms in by_addr.items():
            if not self._get_breaker(addr).allow_enqueue():
                continue
            sent += self._get_queue(addr).put_many(ms)
        return sent

    def send_to_address(self, addr: str, m: Message) -> bool:
        if self._stopped.is_set():
            return False
        if not self._get_breaker(addr).allow_enqueue():
            return False
        return self._get_queue(addr).try_put(m)

    def _get_breaker(self, addr: str) -> _Breaker:
        with self._mu:
            b = self._breakers.get(addr)
            if b is None:
                # deterministic per-address jitter stream so chaos runs
                # replay with identical breaker timing
                b = self._breakers[addr] = _Breaker(
                    rng=random.Random(zlib.crc32(addr.encode())), name=addr
                )
            return b

    def _get_queue(self, addr: str) -> _SendQueue:
        with self._mu:
            sq = self._queues.get(addr)
            if sq is None:
                sq = self._queues[addr] = _SendQueue(
                    self._qlen, self._qbytes, name=addr
                )
                sq.thread = threading.Thread(
                    target=self._process_queue,
                    args=(addr, sq),
                    name=f"transport-{addr}",
                    daemon=True,
                )
                sq.thread.start()
            return sq

    def _process_queue(self, addr: str, sq: _SendQueue) -> None:
        """Per-target worker: connect lazily, drain queue into batches
        (cf. connectAndProcess/processQueue transport.go:453-557)."""
        conn = None
        breaker = self._get_breaker(addr)
        try:
            while not self._stopped.is_set():
                m = sq.get(timeout=0.5)
                if m is None:
                    if sq.closed:
                        return
                    continue
                requests = [m]
                size = _msg_size(m)
                while size < soft.max_message_batch_size:
                    m2 = sq.get_nowait()
                    if m2 is None:
                        break
                    requests.append(m2)
                    size += _msg_size(m2)
                # the message that crossed the byte cap ships in a second
                # batch so no single wire write exceeds the cap
                # (cf. transport.go:533-541 twoBatch)
                if size >= soft.max_message_batch_size and len(requests) > 1:
                    splits = [requests[:-1], requests[-1:]]
                else:
                    splits = [requests]
                for reqs in splits:
                    batch = MessageBatch(
                        requests=reqs,
                        deployment_id=self.deployment_id,
                        source_address=self.source_address,
                        bin_ver=BIN_VER,
                    )
                    if self._pre_send_batch_hook is not None:
                        if not self._pre_send_batch_hook(batch):
                            continue  # dropped by chaos hook
                        if not batch.requests:
                            continue  # chaos hook drained the batch
                    if not breaker.allow_probe():
                        # open + cooling: shed the queued traffic instead
                        # of hammering a dead peer (the reference drops
                        # queued traffic for the cooldown window too)
                        self._metrics["dropped_while_open"] += len(
                            batch.requests
                        )
                        continue
                    try:
                        if conn is None:
                            self._metrics["connect_attempts"] += 1
                            conn = self.rpc.get_connection(addr)
                        conn.send_message_batch(batch)
                        breaker.success()
                        self._metrics["sent"] += len(batch.requests)
                    except Exception:
                        self._metrics["send_failures"] += len(batch.requests)
                        self._metrics["connect_failures"] += 1
                        if conn is not None:
                            try:
                                conn.close()
                            except Exception:
                                pass
                            conn = None
                        breaker.fail()
                        self._notify_unreachable(addr)
                        # brief pause so a hard-down peer does not spin
                        # this worker; the breaker cooldown does the real
                        # shedding
                        time.sleep(0.05)
        finally:
            if conn is not None:
                try:
                    conn.close()
                except Exception:
                    pass

    # -- failure fanout ---------------------------------------------------------
    def _notify_unreachable(self, addr: str) -> None:
        """cf. transport.go:371-386 + nodehost.go:2034-2055."""
        if self._handler is None:
            return
        for cid, nid in self.nodes.reverse_resolve(addr):
            self._handler.handle_unreachable(cid, nid)

    def _notify_unreachable_one(self, cluster_id: int, node_id: int) -> None:
        if self._handler is not None:
            self._handler.handle_unreachable(cluster_id, node_id)


def _msg_size(m: Message) -> int:
    return 64 + sum(len(e.cmd) + 48 for e in m.entries)


__all__ = ["Transport", "BIN_VER", "URGENT_TYPES"]
