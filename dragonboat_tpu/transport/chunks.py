"""Inbound snapshot chunk reassembly with offset-resumable streams.

cf. internal/transport/chunks.go:67-347 — tracks in-flight snapshot
streams, writes chunks into a .receiving temp dir, validates the assembled
file, atomically finalizes it into the node's snapshot directory, and
converts the completed stream into an InstallSnapshot message delivered
through the normal receive path.

Resume protocol (no referent in the reference, which restarts aborted
streams from scratch): after every persisted chunk the tracker records a
progress file (`stream-progress.json`, atomic replace) next to the data.
When a RETRY of the same stream begins — the sender always restarts at
chunk 0; raft's snapshot-status feedback drives the retry — chunks the
progress record already covers are verified and SKIPPED without touching
disk, and writing resumes at the recorded offset (the in-progress file is
first truncated to the recorded durable size, so a torn tail from a
mid-write crash can never duplicate bytes). A receiver host crash
(NodeHost.crash) therefore costs at most one chunk of rewritten data, and
the `.receiving` dir survives process death because it lives under the
durable snapshot root.
"""
from __future__ import annotations

import json
import os
import shutil
import threading
from typing import Dict, Optional, Tuple

from ..rsm.snapshotio import validate_snapshot_file
from ..trace import flight_recorder
from ..types import Message, MessageBatch, MessageType, Snapshot, SnapshotChunk
from ..settings import soft

_PROGRESS_FILE = "stream-progress.json"


class _Track:
    __slots__ = (
        "first", "next_chunk", "f", "tmp_dir", "final_dir", "files", "tick",
        "skip_until",
    )

    def __init__(self, first: SnapshotChunk, tmp_dir: str, final_dir: str) -> None:
        self.first = first
        self.next_chunk = 1
        self.tmp_dir = tmp_dir
        self.final_dir = final_dir
        self.f = None
        self.files = []  # (file_info, local_path)
        self.tick = 0
        # resume fence: chunk ids below this are already durable from a
        # previous attempt of the SAME stream — verified and skipped
        self.skip_until = 0


class Chunks:
    """cf. Chunks internal/transport/chunks.go:67-98."""

    def __init__(self, nodehost) -> None:
        self._nh = nodehost
        self._mu = threading.Lock()
        self._tracked: Dict[Tuple[int, int, int], _Track] = {}
        self._tick = 0
        # stream-plane counters (read by tests/verdicts; ints under _mu)
        self._resumed_streams = 0
        self._skipped_chunks = 0
        self._aborted_streams = 0
        self._completed_streams = 0
        # install streams that began while their cluster was marked
        # mid live-migration on this host (NodeHost.mark_migrating, set
        # by serving/placement.py on both ends of a member swap): the
        # counter that lets the bench/longhaul ledgers tell migration
        # install traffic from ordinary crash-rejoin catch-up
        self._migration_streams = 0

    def _key(self, c: SnapshotChunk) -> Tuple[int, int, int]:
        return (c.cluster_id, c.node_id, c.from_)

    def stats(self) -> dict:
        with self._mu:
            return {
                "resumed_streams": self._resumed_streams,
                "skipped_chunks": self._skipped_chunks,
                "aborted_streams": self._aborted_streams,
                "completed_streams": self._completed_streams,
                "migration_streams": self._migration_streams,
            }

    # ------------------------------------------------------------------ entry
    def add_chunk(self, c: SnapshotChunk) -> bool:
        """Returns False to reject the stream (cf. addChunk
        chunks.go:227-282)."""
        with self._mu:
            key = self._key(c)
            t = self._tracked.get(key)
            if c.chunk_id == 0:
                if t is not None:
                    self._drop_locked(key, reason="restarted")
                t = self._begin_locked(c)
                if t is None:
                    return False
                # migration tagging: is_migrating takes NodeHost._nodes_mu
                # INSIDE Chunks._mu — hierarchy-legal (rank 36 -> 38) and
                # the probe is one set lookup
                is_mig = getattr(self._nh, "is_migrating", None)
                if is_mig is not None and is_mig(c.cluster_id):
                    self._migration_streams += 1
            elif t is None or c.chunk_id != t.next_chunk:
                if t is not None:
                    self._drop_locked(key, reason="out_of_order")
                return False
            else:
                t.next_chunk += 1
            if c.chunk_id < t.skip_until:
                # already durable from the previous attempt of this
                # stream: bookkeeping only, no disk write
                self._skipped_chunks += 1
                self._note_file_complete_locked(t, c)
            else:
                try:
                    self._save_chunk_locked(t, c)
                    self._write_progress_locked(t, c)
                except OSError:
                    self._drop_locked(key, reason="io_error")
                    return False
            if c.chunk_id == c.chunk_count - 1:
                ok = self._finalize_locked(key, t, c)
                return ok
            return True

    # ------------------------------------------------------------------ paths
    def _node_snapshot_dir(self, cluster_id: int, node_id: int) -> str:
        return os.path.join(
            self._nh.snapshot_dir_root(),
            f"snapshot-part-{cluster_id:020d}-{node_id:020d}",
        )

    def _begin_locked(self, c: SnapshotChunk) -> Optional[_Track]:
        base = self._node_snapshot_dir(c.cluster_id, c.node_id)
        final_dir = os.path.join(base, f"snapshot-{c.index:016X}")
        tmp_dir = final_dir + ".receiving"
        if os.path.exists(final_dir):
            # A finalized image already exists: its InstallSnapshot handoff
            # was lost (the receiver was partitioned or mid-restart at
            # finalize time). Rejecting the retry would poison EVERY
            # subsequent stream of this index — the observed chaos wedge
            # (hundreds of failed re-streams, zero recoveries). Re-deliver
            # from the on-disk image; external-file metadata was persisted
            # next to it at finalize time. The image is NEVER deleted here:
            # it may be the node's only durable copy of an installed
            # snapshot.
            self._redeliver_locked(c, final_dir)
            return None
        # reclaim older abandoned partials for this node: a stream at a
        # higher index makes them unreachable (the sender only ever
        # streams its newest image), and keeping them would leak disk —
        # the fixed-width hex name compares lexically == numerically
        try:
            this_part = f"snapshot-{c.index:016X}.receiving"
            for name in os.listdir(base):
                if name.endswith(".receiving") and name < this_part:
                    shutil.rmtree(os.path.join(base, name), ignore_errors=True)
        except OSError:
            pass
        t = self._try_resume_locked(c, tmp_dir, final_dir)
        if t is not None:
            return t
        if os.path.exists(tmp_dir):
            # stale/incompatible partial from a different stream shape
            shutil.rmtree(tmp_dir, ignore_errors=True)
        os.makedirs(tmp_dir, exist_ok=True)
        t = _Track(c, tmp_dir, final_dir)
        t.tick = self._tick
        self._tracked[self._key(c)] = t
        return t

    def _try_resume_locked(self, c: SnapshotChunk, tmp_dir, final_dir) -> Optional[_Track]:
        """Adopt a surviving `.receiving` dir of the SAME stream: verify
        the recorded progress, truncate the in-progress file to the
        durable size, and fence already-persisted chunks off the write
        path. Returns None when no compatible progress exists (the caller
        starts clean)."""
        prog = self._read_progress(tmp_dir)
        if (
            prog is None
            or prog.get("index") != c.index
            or prog.get("term") != c.term
            or prog.get("chunk_count") != c.chunk_count
        ):
            return None
        nxt = int(prog.get("next_chunk", 0))
        if nxt <= 0:
            return None
        fname = prog.get("file")
        if fname:
            fpath = os.path.join(tmp_dir, fname)
            size = int(prog.get("size", 0))
            try:
                have = os.path.getsize(fpath)
            except OSError:
                return None
            if have < size:
                return None  # progress outran data (should not happen)
            if have > size:
                # torn tail from a mid-write crash: roll the file back to
                # the last chunk the progress record covers
                with open(fpath, "ab") as f:
                    f.truncate(size)
        t = _Track(c, tmp_dir, final_dir)
        t.tick = self._tick
        t.skip_until = nxt
        self._tracked[self._key(c)] = t
        self._resumed_streams += 1
        flight_recorder().record(
            "snapshot_stream_resumed", cluster=c.cluster_id,
            node=c.node_id, index=c.index, offset_chunks=nxt,
            offset_bytes=int(prog.get("size", 0)),
        )
        return t

    def _progress_path(self, tmp_dir: str) -> str:
        return os.path.join(tmp_dir, _PROGRESS_FILE)

    def _read_progress(self, tmp_dir: str) -> Optional[dict]:
        try:
            with open(self._progress_path(tmp_dir)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _write_progress_locked(self, t: _Track, c: SnapshotChunk) -> None:
        """Record the durable resume point AFTER the chunk's bytes are on
        disk (write-then-record: the record can only ever lag the data, so
        resume never skips bytes that were lost)."""
        if c.has_file_info:
            name = f"external-file-{c.file_info.file_id}"
        else:
            name = f"snapshot-{c.index:016X}.gbsnap"
        path = os.path.join(t.tmp_dir, name)
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        rec = {
            "index": c.index,
            "term": c.term,
            "chunk_count": c.chunk_count,
            "next_chunk": c.chunk_id + 1,
            "file": name if not c.witness else "",
            "size": size if not c.witness else 0,
        }
        tmp = self._progress_path(t.tmp_dir) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, self._progress_path(t.tmp_dir))

    def _redeliver_locked(self, c: SnapshotChunk, final_dir: str) -> None:
        """Hand an already-received snapshot image to the node again (the
        stream that produced it finished, but the receiving raft never saw
        the InstallSnapshot). The stale-snapshot ACK path in the engine
        covers the 'already recovered' case."""
        fname = f"snapshot-{c.index:016X}.gbsnap"
        final_path = os.path.join(final_dir, fname)
        ss = Snapshot(
            filepath=final_path,
            file_size=(
                os.path.getsize(final_path)
                if not c.witness and os.path.exists(final_path)
                else 0
            ),
            index=c.index,
            term=c.term,
            membership=c.membership,
            files=self._load_stream_files(final_dir),
            cluster_id=c.cluster_id,
            on_disk_index=c.on_disk_index,
            witness=c.witness,
        )
        m = Message(
            type=MessageType.INSTALL_SNAPSHOT,
            cluster_id=c.cluster_id,
            to=c.node_id,
            from_=c.from_,
            snapshot=ss,
        )
        self._nh.handle_message_batch(MessageBatch(requests=[m]))
        self._nh.handle_snapshot(c.cluster_id, c.node_id, c.from_)

    def _note_file_complete_locked(self, t: _Track, c: SnapshotChunk) -> None:
        """External-file bookkeeping shared by the write and skip paths:
        the metadata rides the chunk stream, so a skipped (already
        durable) chunk must still contribute its file record."""
        if c.has_file_info and c.file_chunk_id == c.file_chunk_count - 1:
            name = f"external-file-{c.file_info.file_id}"
            t.files.append((c.file_info, os.path.join(t.final_dir, name)))

    def _save_chunk_locked(self, t: _Track, c: SnapshotChunk) -> None:
        if c.witness:
            return
        if c.has_file_info:
            name = f"external-file-{c.file_info.file_id}"
        else:
            name = f"snapshot-{c.index:016X}.gbsnap"
        path = os.path.join(t.tmp_dir, name)
        mode = "wb" if c.file_chunk_id == 0 else "ab"
        with open(path, mode) as f:
            f.write(c.data)
        self._note_file_complete_locked(t, c)

    def _finalize_locked(self, key, t: _Track, c: SnapshotChunk) -> bool:
        first = t.first
        fname = f"snapshot-{first.index:016X}.gbsnap"
        fpath = os.path.join(t.tmp_dir, fname)
        if not first.witness:
            if not validate_snapshot_file(fpath):
                # the assembled image is corrupt: the partial is
                # WORTHLESS — purge it, or the retry would resume past
                # every chunk (no rewrites), re-validate the same bytes
                # and wedge this snapshot index forever
                self._drop_locked(key, reason="validation", purge=True)
                return False
        del self._tracked[key]
        self._completed_streams += 1
        # the progress record must not travel into the finalized image dir
        try:
            os.remove(self._progress_path(t.tmp_dir))
        except OSError:
            pass
        if os.path.exists(t.final_dir):
            shutil.rmtree(t.tmp_dir, ignore_errors=True)
            return True
        # persist external-file metadata next to the image: a lost
        # InstallSnapshot handoff is re-delivered from disk later, and the
        # stream is the only carrier of this metadata
        if t.files:
            meta = [
                {
                    "name": os.path.basename(lp),
                    "file_id": fi.file_id,
                    "metadata": fi.metadata.hex() if fi.metadata else "",
                }
                for fi, lp in t.files
            ]
            with open(
                os.path.join(t.tmp_dir, "stream-files.json"), "w"
            ) as mf:
                json.dump(meta, mf)
        os.replace(t.tmp_dir, t.final_dir)
        final_path = os.path.join(t.final_dir, fname)
        from ..types import SnapshotFile as WireFile

        wire_files = [
            WireFile(
                filepath=lp,
                file_size=os.path.getsize(lp),
                file_id=fi.file_id,
                metadata=fi.metadata,
            )
            for fi, lp in t.files
        ]
        ss = Snapshot(
            filepath=final_path,
            file_size=os.path.getsize(final_path) if not first.witness else 0,
            index=first.index,
            term=first.term,
            membership=first.membership,
            files=wire_files,
            cluster_id=first.cluster_id,
            on_disk_index=first.on_disk_index,
            witness=first.witness,
        )
        m = Message(
            type=MessageType.INSTALL_SNAPSHOT,
            cluster_id=first.cluster_id,
            to=first.node_id,
            from_=first.from_,
            snapshot=ss,
        )
        self._nh.handle_message_batch(MessageBatch(requests=[m]))
        self._nh.handle_snapshot(first.cluster_id, first.node_id, first.from_)
        return True

    def _load_stream_files(self, final_dir: str):
        """External-file records persisted at finalize (for re-delivery)."""
        path = os.path.join(final_dir, "stream-files.json")
        if not os.path.exists(path):
            return []
        from ..types import SnapshotFile as WireFile

        try:
            with open(path) as f:
                meta = json.load(f)
            out = []
            for rec in meta:
                lp = os.path.join(final_dir, rec["name"])
                out.append(
                    WireFile(
                        filepath=lp,
                        file_size=(
                            os.path.getsize(lp) if os.path.exists(lp) else 0
                        ),
                        file_id=rec["file_id"],
                        metadata=bytes.fromhex(rec["metadata"]),
                    )
                )
            return out
        except Exception:
            return []

    def _drop_locked(self, key, reason: str = "", purge: bool = False) -> None:
        t = self._tracked.pop(key, None)
        if t is not None:
            # the partial data + progress record normally STAY on disk:
            # they are exactly what the next attempt of this stream
            # resumes from. Only the in-memory tracking is abandoned.
            # `purge` (validation failure) removes them — corrupt bytes
            # must be re-transferred, not resumed past.
            if purge:
                shutil.rmtree(t.tmp_dir, ignore_errors=True)
            if reason == "restarted":
                # not an abort: the sender's RETRY of this same stream
                # arrived (the normal resume path) — no counter bump and
                # no client fail-fast window
                return
            self._aborted_streams += 1
            flight_recorder().record(
                "snapshot_stream_aborted", cluster=t.first.cluster_id,
                node=t.first.node_id, index=t.first.index,
                reason=reason or "dropped",
            )
            notify = getattr(self._nh, "_on_snapshot_stream_aborted", None)
            if notify is not None:
                # lock-free downstream (plain attribute stamps on the
                # node): safe to invoke under _mu
                notify(
                    t.first.cluster_id, t.first.node_id, t.first.from_,
                    reason or "dropped",
                )

    # --------------------------------------------------------------------- gc
    # resumable partials whose stream is never retried (member removed,
    # sender permanently gone) expire after this wall-clock age — bounds
    # the disk a dead stream can hold to one image per (cluster, node)
    # for a bounded time
    RESUME_TTL_S = 1800.0

    def tick(self) -> None:
        """Periodic timeout sweep (cf. chunks.go:112-139)."""
        with self._mu:
            self._tick += 1
            dead = [
                k
                for k, t in self._tracked.items()
                if self._tick - t.tick > soft.snapshot_chunk_timeout_tick
            ]
            for k in dead:
                self._drop_locked(k, reason="timeout")
            sweep_due = self._tick % soft.snapshot_chunk_timeout_tick == 0
            tracked_dirs = (
                {t.tmp_dir for t in self._tracked.values()}
                if sweep_due
                else None
            )
        if sweep_due:
            # the walk/rmtree I/O runs OUTSIDE _mu: holding the tracker
            # lock across a directory sweep would stall inbound chunk
            # delivery — the cadence stall this plane exists to avoid.
            # Swept dirs are by definition untracked; a stream that
            # begins concurrently recreates its dir on the next chunk.
            self._sweep_stale_partials(tracked_dirs)

    def _sweep_stale_partials(self, tracked_dirs) -> None:
        """Age out resumable `.receiving` partials no live stream is
        feeding: process_orphans spares progress-carrying partials (they
        are resume state) and _begin's reclaim only fires when a NEWER
        stream targets the same node, so a stream that is simply never
        retried would otherwise hold a snapshot image of disk forever."""
        import time as _time

        try:
            root = self._nh.snapshot_dir_root()
            now = _time.time()
            for part in os.listdir(root):
                pdir = os.path.join(root, part)
                if not part.startswith("snapshot-part-"):
                    continue
                try:
                    names = os.listdir(pdir)
                except OSError:
                    continue
                for name in names:
                    if not name.endswith(".receiving"):
                        continue
                    path = os.path.join(pdir, name)
                    if path in tracked_dirs:
                        continue  # live stream: its own timeout governs
                    try:
                        age = now - os.path.getmtime(
                            self._progress_path(path)
                        )
                    except OSError:
                        continue  # no progress record: orphan sweep owns it
                    if age > self.RESUME_TTL_S:
                        shutil.rmtree(path, ignore_errors=True)
        except OSError:
            pass


__all__ = ["Chunks"]
