"""Inbound snapshot chunk reassembly.

cf. internal/transport/chunks.go:67-347 — tracks in-flight snapshot
streams, writes chunks into a .receiving temp dir, validates the assembled
file, atomically finalizes it into the node's snapshot directory, and
converts the completed stream into an InstallSnapshot message delivered
through the normal receive path.
"""
from __future__ import annotations

import os
import shutil
import threading
from typing import Dict, Optional, Tuple

from ..rsm.snapshotio import validate_snapshot_file
from ..types import Message, MessageBatch, MessageType, Snapshot, SnapshotChunk
from ..settings import soft


class _Track:
    __slots__ = ("first", "next_chunk", "f", "tmp_dir", "final_dir", "files", "tick")

    def __init__(self, first: SnapshotChunk, tmp_dir: str, final_dir: str) -> None:
        self.first = first
        self.next_chunk = 1
        self.tmp_dir = tmp_dir
        self.final_dir = final_dir
        self.f = None
        self.files = []  # (file_info, local_path)
        self.tick = 0


class Chunks:
    """cf. Chunks internal/transport/chunks.go:67-98."""

    def __init__(self, nodehost) -> None:
        self._nh = nodehost
        self._mu = threading.Lock()
        self._tracked: Dict[Tuple[int, int, int], _Track] = {}
        self._tick = 0

    def _key(self, c: SnapshotChunk) -> Tuple[int, int, int]:
        return (c.cluster_id, c.node_id, c.from_)

    # ------------------------------------------------------------------ entry
    def add_chunk(self, c: SnapshotChunk) -> bool:
        """Returns False to reject the stream (cf. addChunk
        chunks.go:227-282)."""
        with self._mu:
            key = self._key(c)
            t = self._tracked.get(key)
            if c.chunk_id == 0:
                if t is not None:
                    self._drop(key)
                t = self._begin(c)
                if t is None:
                    return False
            elif t is None or c.chunk_id != t.next_chunk:
                if t is not None:
                    self._drop(key)
                return False
            else:
                t.next_chunk += 1
            try:
                self._save_chunk(t, c)
            except OSError:
                self._drop(key)
                return False
            if c.chunk_id == c.chunk_count - 1:
                ok = self._finalize(key, t, c)
                return ok
            return True

    # ------------------------------------------------------------------ paths
    def _node_snapshot_dir(self, cluster_id: int, node_id: int) -> str:
        return os.path.join(
            self._nh.snapshot_dir_root(),
            f"snapshot-part-{cluster_id:020d}-{node_id:020d}",
        )

    def _begin(self, c: SnapshotChunk) -> Optional[_Track]:
        base = self._node_snapshot_dir(c.cluster_id, c.node_id)
        final_dir = os.path.join(base, f"snapshot-{c.index:016X}")
        tmp_dir = final_dir + ".receiving"
        if os.path.exists(final_dir):
            # A finalized image already exists: its InstallSnapshot handoff
            # was lost (the receiver was partitioned or mid-restart at
            # finalize time). Rejecting the retry would poison EVERY
            # subsequent stream of this index — the observed chaos wedge
            # (hundreds of failed re-streams, zero recoveries). Re-deliver
            # from the on-disk image; external-file metadata was persisted
            # next to it at finalize time. The image is NEVER deleted here:
            # it may be the node's only durable copy of an installed
            # snapshot.
            self._redeliver(c, final_dir)
            return None
        os.makedirs(tmp_dir, exist_ok=True)
        t = _Track(c, tmp_dir, final_dir)
        t.tick = self._tick
        self._tracked[self._key(c)] = t
        return t

    def _redeliver(self, c: SnapshotChunk, final_dir: str) -> None:
        """Hand an already-received snapshot image to the node again (the
        stream that produced it finished, but the receiving raft never saw
        the InstallSnapshot). The stale-snapshot ACK path in the engine
        covers the 'already recovered' case."""
        fname = f"snapshot-{c.index:016X}.gbsnap"
        final_path = os.path.join(final_dir, fname)
        ss = Snapshot(
            filepath=final_path,
            file_size=(
                os.path.getsize(final_path)
                if not c.witness and os.path.exists(final_path)
                else 0
            ),
            index=c.index,
            term=c.term,
            membership=c.membership,
            files=self._load_stream_files(final_dir),
            cluster_id=c.cluster_id,
            on_disk_index=c.on_disk_index,
            witness=c.witness,
        )
        m = Message(
            type=MessageType.INSTALL_SNAPSHOT,
            cluster_id=c.cluster_id,
            to=c.node_id,
            from_=c.from_,
            snapshot=ss,
        )
        self._nh.handle_message_batch(MessageBatch(requests=[m]))
        self._nh.handle_snapshot(c.cluster_id, c.node_id, c.from_)

    def _save_chunk(self, t: _Track, c: SnapshotChunk) -> None:
        if c.witness:
            return
        if c.has_file_info:
            name = f"external-file-{c.file_info.file_id}"
        else:
            name = f"snapshot-{c.index:016X}.gbsnap"
        path = os.path.join(t.tmp_dir, name)
        mode = "wb" if c.file_chunk_id == 0 else "ab"
        with open(path, mode) as f:
            f.write(c.data)
        if c.has_file_info and c.file_chunk_id == c.file_chunk_count - 1:
            t.files.append((c.file_info, os.path.join(t.final_dir, name)))

    def _finalize(self, key, t: _Track, c: SnapshotChunk) -> bool:
        first = t.first
        fname = f"snapshot-{first.index:016X}.gbsnap"
        fpath = os.path.join(t.tmp_dir, fname)
        if not first.witness:
            if not validate_snapshot_file(fpath):
                self._drop(key)
                return False
        del self._tracked[key]
        if os.path.exists(t.final_dir):
            shutil.rmtree(t.tmp_dir, ignore_errors=True)
            return True
        # persist external-file metadata next to the image: a lost
        # InstallSnapshot handoff is re-delivered from disk later, and the
        # stream is the only carrier of this metadata
        if t.files:
            import json

            meta = [
                {
                    "name": os.path.basename(lp),
                    "file_id": fi.file_id,
                    "metadata": fi.metadata.hex() if fi.metadata else "",
                }
                for fi, lp in t.files
            ]
            with open(
                os.path.join(t.tmp_dir, "stream-files.json"), "w"
            ) as mf:
                json.dump(meta, mf)
        os.replace(t.tmp_dir, t.final_dir)
        final_path = os.path.join(t.final_dir, fname)
        from ..types import SnapshotFile as WireFile

        wire_files = [
            WireFile(
                filepath=lp,
                file_size=os.path.getsize(lp),
                file_id=fi.file_id,
                metadata=fi.metadata,
            )
            for fi, lp in t.files
        ]
        ss = Snapshot(
            filepath=final_path,
            file_size=os.path.getsize(final_path) if not first.witness else 0,
            index=first.index,
            term=first.term,
            membership=first.membership,
            files=wire_files,
            cluster_id=first.cluster_id,
            on_disk_index=first.on_disk_index,
            witness=first.witness,
        )
        m = Message(
            type=MessageType.INSTALL_SNAPSHOT,
            cluster_id=first.cluster_id,
            to=first.node_id,
            from_=first.from_,
            snapshot=ss,
        )
        self._nh.handle_message_batch(MessageBatch(requests=[m]))
        self._nh.handle_snapshot(first.cluster_id, first.node_id, first.from_)
        return True

    def _load_stream_files(self, final_dir: str):
        """External-file records persisted at finalize (for re-delivery)."""
        path = os.path.join(final_dir, "stream-files.json")
        if not os.path.exists(path):
            return []
        import json

        from ..types import SnapshotFile as WireFile

        try:
            with open(path) as f:
                meta = json.load(f)
            out = []
            for rec in meta:
                lp = os.path.join(final_dir, rec["name"])
                out.append(
                    WireFile(
                        filepath=lp,
                        file_size=(
                            os.path.getsize(lp) if os.path.exists(lp) else 0
                        ),
                        file_id=rec["file_id"],
                        metadata=bytes.fromhex(rec["metadata"]),
                    )
                )
            return out
        except Exception:
            return []

    def _drop(self, key) -> None:
        t = self._tracked.pop(key, None)
        if t is not None:
            shutil.rmtree(t.tmp_dir, ignore_errors=True)

    # --------------------------------------------------------------------- gc
    def tick(self) -> None:
        """Periodic timeout sweep (cf. chunks.go:112-139)."""
        with self._mu:
            self._tick += 1
            dead = [
                k
                for k, t in self._tracked.items()
                if self._tick - t.tick > soft.snapshot_chunk_timeout_tick
            ]
            for k in dead:
                self._drop(k)


__all__ = ["Chunks"]
