"""TCP transport module: the default IRaftRPC.

Custom framed protocol mirroring the reference's design
(cf. internal/transport/tcp.go:57-244): magic number + fixed header
{method, payload size, crc32 of payload, crc32 of header} + payload
(encoded MessageBatch or SnapshotChunk). Mutual TLS optional. A poison
frame announces graceful connection shutdown.
"""
from __future__ import annotations

import socket
import ssl
import struct
import threading
import zlib
from typing import Callable, Optional

from .. import codec
from ..raftio import (
    IConnection,
    IRaftRPC,
    ISnapshotConnection,
)
from ..types import MessageBatch, SnapshotChunk

MAGIC = b"DBTP"
# method(u16) payload_len(u64) payload_crc(u32) header_crc(u32)
_HDR = struct.Struct("<HQII")
REQUEST_HEADER_SIZE = 4 + _HDR.size

RAFT_TYPE = 100
SNAPSHOT_TYPE = 200
POISON_TYPE = 65535

# 4s per-frame IO deadlines in the reference (tcp.go magicNumberDuration +
# headerDuration); generous fixed socket timeouts here
DEFAULT_TIMEOUT = 10.0
SNAPSHOT_TIMEOUT = 30.0


class FrameError(Exception):
    pass


def _write_frame(sock: socket.socket, method: int, payload: bytes) -> None:
    hdr = _HDR.pack(method, len(payload), zlib.crc32(payload), 0)
    hcrc = zlib.crc32(hdr[: _HDR.size - 4])
    hdr = hdr[: _HDR.size - 4] + struct.pack("<I", hcrc)
    sock.sendall(MAGIC + hdr + payload)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise FrameError("connection closed")
        buf.extend(chunk)
    return bytes(buf)


def _read_frame(sock: socket.socket, max_size: int = 1 << 30):
    magic = _read_exact(sock, 4)
    if magic != MAGIC:
        raise FrameError(f"bad magic {magic!r}")
    raw = _read_exact(sock, _HDR.size)
    method, plen, pcrc, hcrc = _HDR.unpack(raw)
    if zlib.crc32(raw[: _HDR.size - 4]) != hcrc:
        raise FrameError("header crc mismatch")
    if method == POISON_TYPE:
        return method, b""
    if plen > max_size:
        raise FrameError(f"oversized frame {plen}")
    payload = _read_exact(sock, plen)
    if zlib.crc32(payload) != pcrc:
        raise FrameError("payload crc mismatch")
    return method, payload


class TCPConnection(IConnection):
    """cf. internal/transport/tcp.go:347-363."""

    def __init__(self, sock: socket.socket) -> None:
        self._sock = sock

    def close(self) -> None:
        try:
            _write_frame(self._sock, POISON_TYPE, b"")
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def send_message_batch(self, batch: MessageBatch) -> None:
        payload = codec.encode_message_batch(batch)
        _write_frame(self._sock, RAFT_TYPE, payload)


class TCPSnapshotConnection(ISnapshotConnection):
    """cf. internal/transport/tcp.go:365-402."""

    def __init__(self, sock: socket.socket) -> None:
        self._sock = sock

    def close(self) -> None:
        try:
            _write_frame(self._sock, POISON_TYPE, b"")
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def send_chunk(self, chunk: SnapshotChunk) -> None:
        payload = codec.encode_chunk(chunk)
        _write_frame(self._sock, SNAPSHOT_TYPE, payload)


class TCPTransport(IRaftRPC):
    """Listener + connection factory (cf. TCPTransport tcp.go:405-553)."""

    def __init__(
        self,
        listen_address: str,
        request_handler: Callable[[MessageBatch], None],
        chunk_handler: Callable[[SnapshotChunk], bool],
        tls_config: Optional[dict] = None,
    ) -> None:
        self._listen_address = listen_address
        self._request_handler = request_handler
        self._chunk_handler = chunk_handler
        self._tls = tls_config
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()
        self._conn_threads = []

    def name(self) -> str:
        return "go-tcp-transport-equivalent"

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        host, _, port = self._listen_address.rpartition(":")
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host or "0.0.0.0", int(port)))
        s.listen(128)
        s.settimeout(0.2)
        self._listener = s
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="tcp-accept", daemon=True
        )
        self._accept_thread.start()

    def stop(self) -> None:
        self._stopped.set()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2)
        if self._listener is not None:
            self._listener.close()

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            if self._tls:
                try:
                    ctx = _server_ssl_context(self._tls)
                    conn = ctx.wrap_socket(conn, server_side=True)
                except ssl.SSLError:
                    conn.close()
                    continue
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()
            self._conn_threads = [
                x for x in self._conn_threads if x.is_alive()
            ]
            self._conn_threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        conn.settimeout(DEFAULT_TIMEOUT * 6)
        try:
            with conn:
                while not self._stopped.is_set():
                    method, payload = _read_frame(conn)
                    if method == POISON_TYPE:
                        return
                    if method == RAFT_TYPE:
                        batch, _ = codec.decode_message_batch(payload)
                        self._request_handler(batch)
                    elif method == SNAPSHOT_TYPE:
                        chunk, _ = codec.decode_chunk(payload)
                        if not self._chunk_handler(chunk):
                            return
                    else:
                        raise FrameError(f"unknown method {method}")
        except (FrameError, OSError, socket.timeout):
            return

    # -- dialing ---------------------------------------------------------------
    def _dial(self, target: str, timeout: float) -> socket.socket:
        host, _, port = target.rpartition(":")
        sock = socket.create_connection((host, int(port)), timeout=timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
        if self._tls:
            ctx = _client_ssl_context(self._tls)
            sock = ctx.wrap_socket(sock, server_hostname=host)
        return sock

    def get_connection(self, target: str) -> TCPConnection:
        return TCPConnection(self._dial(target, DEFAULT_TIMEOUT))

    def get_snapshot_connection(self, target: str) -> TCPSnapshotConnection:
        return TCPSnapshotConnection(self._dial(target, SNAPSHOT_TIMEOUT))


def _server_ssl_context(tls: dict) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(tls["cert_file"], tls["key_file"])
    ctx.load_verify_locations(tls["ca_file"])
    ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def _client_ssl_context(tls: dict) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.load_cert_chain(tls["cert_file"], tls["key_file"])
    ctx.load_verify_locations(tls["ca_file"])
    ctx.check_hostname = False
    return ctx


def tcp_factory(listen_address: str, tls_config: Optional[dict] = None):
    """Factory adapter for Transport(rpc_factory=...)."""

    def make(request_handler, chunk_handler):
        return TCPTransport(
            listen_address, request_handler, chunk_handler, tls_config
        )

    return make


__all__ = [
    "TCPTransport",
    "tcp_factory",
    "TCPConnection",
    "TCPSnapshotConnection",
    "FrameError",
    "MAGIC",
    "REQUEST_HEADER_SIZE",
]
