"""Outbound snapshot streaming: split a snapshot file into chunks.

cf. internal/transport/snapshot.go:55-110 + 282-291 — an InstallSnapshot
message is materialized as a sequence of SnapshotChunks (2MB default):
chunk 0 carries the membership + metadata, the last chunk completes the
file; external files follow the main payload, each tagged with
file_chunk_id/file_info.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, List, Optional

from ..settings import soft
from ..types import Message, Snapshot, SnapshotChunk


def split_snapshot_message(m: Message, chunk_size: int = 0) -> List[SnapshotChunk]:
    """Plan the chunk sequence for a snapshot message (data filled lazily at
    send time, cf. snapshot.go:282-291)."""
    ss = m.snapshot
    chunk_size = chunk_size or soft.sent_snapshot_chunk_size
    chunks: List[SnapshotChunk] = []
    main_chunks = max(1, -(-max(ss.file_size, 1) // chunk_size))
    total = main_chunks + sum(
        max(1, -(-max(f.file_size, 1) // chunk_size)) for f in ss.files
    )
    cid = 0
    for i in range(main_chunks):
        chunks.append(
            SnapshotChunk(
                cluster_id=m.cluster_id,
                node_id=m.to,
                from_=m.from_,
                chunk_id=cid,
                chunk_count=total,
                index=ss.index,
                term=ss.term,
                filepath=ss.filepath,
                file_size=ss.file_size,
                file_chunk_id=i,
                file_chunk_count=main_chunks,
                membership=ss.membership if cid == 0 else None,
                on_disk_index=ss.on_disk_index,
                witness=ss.witness,
            )
        )
        cid += 1
    for f in ss.files:
        f_chunks = max(1, -(-max(f.file_size, 1) // chunk_size))
        for i in range(f_chunks):
            chunks.append(
                SnapshotChunk(
                    cluster_id=m.cluster_id,
                    node_id=m.to,
                    from_=m.from_,
                    chunk_id=cid,
                    chunk_count=total,
                    index=ss.index,
                    term=ss.term,
                    filepath=f.filepath,
                    file_size=f.file_size,
                    file_chunk_id=i,
                    file_chunk_count=f_chunks,
                    has_file_info=True,
                    file_info=f,
                    on_disk_index=ss.on_disk_index,
                    witness=ss.witness,
                )
            )
            cid += 1
    return chunks


def load_chunk_data(chunk: SnapshotChunk, chunk_size: int = 0) -> SnapshotChunk:
    chunk_size = chunk_size or soft.sent_snapshot_chunk_size
    offset = chunk.file_chunk_id * chunk_size
    with open(chunk.filepath, "rb") as f:
        f.seek(offset)
        chunk.data = f.read(chunk_size)
    chunk.chunk_size = len(chunk.data)
    return chunk


class RateLimiter:
    """Token-bucket byte throttle for snapshot streams (cf. the reference's
    SnapshotBytesPerSecond knobs, config.go:299-306). acquire(n) sleeps the
    calling thread until n bytes of budget exist; rate 0 = unlimited."""

    def __init__(self, bytes_per_second: int, burst: Optional[int] = None):
        self.rate = bytes_per_second
        self._burst = burst or max(bytes_per_second, 1)
        self._tokens = float(self._burst)
        self._last = time.monotonic()
        self._mu = threading.Lock()

    def acquire(self, n: int) -> None:
        if self.rate <= 0 or n <= 0:
            return
        # debt model: take the bytes immediately and sleep off any deficit,
        # so an acquisition larger than the burst cannot spin forever
        with self._mu:
            now = time.monotonic()
            self._tokens = min(
                self._burst, self._tokens + (now - self._last) * self.rate
            )
            self._last = now
            self._tokens -= n
            wait = -self._tokens / self.rate if self._tokens < 0 else 0.0
        if wait > 0:
            time.sleep(wait)


class SnapshotLane:
    """One in-flight outbound snapshot stream (cf. lane.go:40-237); runs on
    its own thread, reports success/failure back to the leader's raft.
    Admission (total + per-target lane caps) is the caller's job — a lane
    that starts always owns a slot; release() runs exactly once when the
    stream ends."""

    def __init__(
        self,
        transport,
        target_addr: str,
        m: Message,
        on_done: Callable[[int, int, bool], None],
        release: Optional[Callable[[], None]] = None,
        rate_limiter: Optional[RateLimiter] = None,
    ) -> None:
        self._transport = transport
        self._target = target_addr
        self._m = m
        self._on_done = on_done
        self._release = release
        self._rate = rate_limiter
        self.thread = threading.Thread(
            target=self._run, name="snapshot-lane", daemon=True
        )

    def start(self) -> None:
        self.thread.start()

    def _run(self) -> None:
        failed = False
        conn = None
        try:
            conn = self._transport.rpc.get_snapshot_connection(self._target)
            for chunk in split_snapshot_message(self._m):
                if not self._m.snapshot.witness:
                    chunk = load_chunk_data(chunk)
                if self._rate is not None:
                    self._rate.acquire(chunk.chunk_size)
                conn.send_chunk(chunk)
        except Exception:
            failed = True
        finally:
            if conn is not None:
                try:
                    conn.close()
                except Exception:
                    pass
            if self._release is not None:
                self._release()
            # failure feeds SnapshotStatus back into the sender's raft;
            # success waits for the receiver's SnapshotReceived ack
            if failed:
                self._on_done(self._m.cluster_id, self._m.to, True)


__all__ = [
    "split_snapshot_message",
    "load_chunk_data",
    "RateLimiter",
    "SnapshotLane",
]
