"""Recompilation-hazard detection for the jitted step path.

The whole architecture rests on ONE compiled executable advancing every
lane (`make_step_fn` is lru_cached per KernelConfig). Three ways that
silently breaks, each a rule here:

  * Python control flow on a traced value inside kernel code — under
    `jax.jit` an `if`/`while` on a tracer either raises at trace time or,
    when the value sneaks in as a weak type, forks the trace per call.
  * Concretizing a traced value (`int()/float()/bool()/np.asarray()`)
    inside kernel code — forces a trace-time constant, so the compiled
    step is only valid for that value and every new value retraces.
  * Creating jit wrappers inside the step loop's hot functions — each
    `jax.jit(...)` call is a fresh cache, so per-step creation compiles
    forever (the blessed pattern is the lru_cached factory:
    `make_step_fn` / `_make_activate_fn`).

Tracedness is declared in targets (`traced_modules` / `traced_functions`)
and propagated through simple assignments. Static escapes — `.shape`,
`.dtype`, `.ndim`, `len()` — do NOT taint: those are Python values at
trace time and branching on them is exactly how shape-specialized kernels
are supposed to be written.
"""
from __future__ import annotations

import ast
from typing import Iterable, Set

from .engine import Finding, FunctionInfo, Rule

_STATIC_ATTRS = ("shape", "dtype", "ndim", "size")
_CONCRETIZERS = ("int", "float", "bool")
_JIT_FACTORIES = ("make_step_fn", "_make_activate_fn")


def _static_escaped_names(expr: ast.AST) -> Set[int]:
    """ids of Name nodes that only feed static accessors (x.shape, len(x))
    — referencing a traced array through them is trace-stable."""
    escaped: Set[int] = set()

    def mark(node):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name):
                escaped.add(id(sub))

    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            mark(node.value)
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "len"
        ):
            for a in node.args:
                mark(a)
    return escaped


def _traced_refs(expr: ast.AST, traced: Set[str]) -> bool:
    """Does `expr` reference a traced name outside a static escape?

    `x is y` / `x is not y` never reads a traced VALUE — identity of the
    tracer objects is a Python-level property, stable per call site (the
    `_merge.sel` fast path relies on it) — so pure identity comparisons
    are exempt."""
    if isinstance(expr, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops
    ):
        return False
    escaped = _static_escaped_names(expr)
    for node in ast.walk(expr):
        if (
            isinstance(node, ast.Name)
            and node.id in traced
            and id(node) not in escaped
        ):
            return True
    return False


def _traced_name_set(fn: FunctionInfo, targets) -> Set[str]:
    """Seed with non-static parameters, then propagate through simple
    assignments to a FIXPOINT: ast.walk order is not source order (an
    assignment inside a loop body is visited after later top-level
    statements), so one pass would miss taint flowing out of nested
    blocks. The pass count is bounded by the assignment-chain depth."""
    args = fn.node.args
    params = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    n_defaults = len(args.defaults)
    defaulted = set(
        a.arg for a in (args.posonlyargs + args.args)[-n_defaults:]
    ) if n_defaults else set()
    defaulted |= {a.arg for a in args.kwonlyargs}
    traced = {
        p
        for p in params
        if p not in targets.static_param_names
        and p not in defaulted
        and p != "self"
    }
    while True:
        before = len(traced)
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign) and _traced_refs(
                node.value, traced
            ):
                for t in node.targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name):
                            traced.add(sub.id)
            elif isinstance(node, ast.AugAssign) and isinstance(
                node.target, ast.Name
            ):
                if _traced_refs(node.value, traced):
                    traced.add(node.target.id)
        if len(traced) == before:
            return traced


class PythonBranchOnTraced(Rule):
    id = "retrace/python-branch-on-traced"
    doc = (
        "Python if/while (or iteration) on a value derived from a traced "
        "array inside jitted kernel code — trace-time error or a fresh "
        "trace per call; use jnp.where/lax.cond masks"
    )
    motivation = (
        "the kernel advances all lanes divergence-free by construction "
        "(ops/kernel.py); one Python branch on device data breaks the "
        "single-executable contract"
    )

    def check_function(self, fn: FunctionInfo, targets) -> Iterable[Finding]:
        if not targets.is_traced(fn.key()):
            return
        traced = _traced_name_set(fn, targets)
        if not traced:
            return
        for node in ast.walk(fn.node):
            if isinstance(node, (ast.If, ast.While)):
                if _traced_refs(node.test, traced):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    yield self.finding(
                        fn,
                        node,
                        f"Python `{kind}` on a traced value (mask with "
                        f"jnp.where / lax.cond instead)",
                    )
            elif isinstance(node, ast.For):
                if _traced_refs(node.iter, traced):
                    yield self.finding(
                        fn,
                        node,
                        "Python iteration over a traced value (use "
                        "lax.scan / vectorized ops)",
                    )
            elif isinstance(node, ast.IfExp):
                if _traced_refs(node.test, traced):
                    yield self.finding(
                        fn,
                        node,
                        "conditional expression on a traced value (use "
                        "jnp.where)",
                    )


class ConcretizeTraced(Rule):
    id = "retrace/concretize-traced"
    doc = (
        "int()/float()/bool()/np.asarray() on a traced value inside "
        "jitted kernel code — bakes a trace-time constant, so every new "
        "value recompiles"
    )
    motivation = (
        "a float static arg / concretized scalar gives the jit cache a "
        "per-call signature: the compile-once contract degrades to "
        "compile-per-value with no test failing"
    )

    def check_function(self, fn: FunctionInfo, targets) -> Iterable[Finding]:
        if not targets.is_traced(fn.key()):
            return
        traced = _traced_name_set(fn, targets)
        if not traced:
            return
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = ""
            if isinstance(f, ast.Name):
                name = f.id
            elif isinstance(f, ast.Attribute) and isinstance(
                f.value, ast.Name
            ):
                if f.value.id in ("np", "numpy") and f.attr in (
                    "asarray",
                    "array",
                ):
                    name = f"np.{f.attr}"
            if not name:
                continue
            if name in _CONCRETIZERS or name.startswith("np."):
                if node.args and _traced_refs(node.args[0], traced):
                    yield self.finding(
                        fn,
                        node,
                        f"{name}() concretizes a traced value (trace-time "
                        f"constant -> retrace per value)",
                    )


class JitInHotFunction(Rule):
    id = "retrace/jit-in-hot"
    doc = (
        "jax.jit()/jit-factory call inside a step-loop hot function — a "
        "fresh wrapper (and XLA compile) per step; build wrappers once in "
        "the lru_cached factories"
    )
    motivation = (
        "eagerly-created scatter chains at bring-up dominated wall clock "
        "until _make_activate_fn bucketed + cached them; the step loop "
        "must never create jit wrappers"
    )

    def check_function(self, fn: FunctionInfo, targets) -> Iterable[Finding]:
        if fn.key() not in targets.hot_functions:
            return
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "jit":
                yield self.finding(
                    fn, node, "jax.jit() wrapper created on the hot path"
                )
            elif isinstance(f, ast.Name) and f.id in ("jit",) + tuple(
                _JIT_FACTORIES
            ):
                # the factories are lru_cached, but calling them per step
                # still pays a config-hash + risks a compile on any miss
                yield self.finding(
                    fn,
                    node,
                    f"{f.id}() called on the hot path — resolve the "
                    f"compiled fn once at setup",
                )


class DictIterInTraced(Rule):
    id = "retrace/dict-iter-in-traced"
    doc = (
        "iterating .items()/.keys()/.values() of a non-literal dict "
        "inside jitted kernel code — trace structure depends on dict "
        "insertion order (a reordered caller silently recompiles)"
    )
    motivation = (
        "dict-ordering-dependent closures are the classic invisible "
        "trace-signature variance: same values, different order, new "
        "executable"
    )

    def check_function(self, fn: FunctionInfo, targets) -> Iterable[Finding]:
        if not targets.is_traced(fn.key()):
            return
        # a dict ASSIGNED inside this function has program-text-determined
        # insertion order — deterministic per trace. The hazard is order
        # chosen by someone else: parameters and closure captures.
        local_names = set()
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name):
                            local_names.add(sub.id)
        for node in ast.walk(fn.node):
            iters = []
            if isinstance(node, ast.For):
                iters.append(node.iter)
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                iters.extend(g.iter for g in node.generators)
            for it in iters:
                if (
                    isinstance(it, ast.Call)
                    and isinstance(it.func, ast.Attribute)
                    and it.func.attr in ("items", "keys", "values")
                    and isinstance(it.func.value, ast.Name)
                    and it.func.value.id not in local_names
                ):
                    yield self.finding(
                        fn,
                        node,
                        f"iteration over {it.func.value.id}."
                        f"{it.func.attr}() — trace shape depends on dict "
                        f"ordering; iterate a sorted/declared key list",
                    )


RULES = [
    PythonBranchOnTraced(),
    ConcretizeTraced(),
    JitInHotFunction(),
    DictIterInTraced(),
]

__all__ = [
    "RULES",
    "ConcretizeTraced",
    "DictIterInTraced",
    "JitInHotFunction",
    "PythonBranchOnTraced",
]
