"""Declarative analysis targets: WHICH code each rule family watches.

This file is the contract between the codebase's performance/concurrency
architecture and the rule engine:

  * the engine step loop's hot functions (pack -> dispatch -> fetch ->
    decode/fan-out -> save) where per-message Python, device syncs and
    unguarded telemetry are regressions (PR 1's columnar fan-out closed a
    340x kernel-vs-e2e gap; these lists keep it closed);
  * the jit-traced kernel code where Python control flow on traced values
    and per-call trace-signature variance silently recompile;
  * the declared LOCK HIERARCHY of the host runtime and the shared state
    each lock guards (the two PR 3 data races — snapshot index/data skew
    and the logdb compaction-vs-append lost update — were both
    "documented-shared-state written outside its lock" bugs).

Paths are package-relative ("engine/vector.py"); functions are qualnames
("VectorEngine._decode", nested defs as "make_step_fn.apply").
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

VECTOR = "engine/vector.py"
NODE = "engine/node.py"
EXEC = "engine/execengine.py"
NODEHOST = "nodehost.py"
TRANSPORT = "transport/transport.py"
LOGDB = "storage/logdb.py"
KV = "storage/kv.py"
TRACE = "trace.py"
PROFILE = "profile.py"
MANAGED = "rsm/managed.py"
KERNEL = "ops/kernel.py"
STATE = "ops/state.py"
SERVING_ADMISSION = "serving/admission.py"
SERVING_BACKPRESSURE = "serving/backpressure.py"
SERVING_FRONT = "serving/front.py"
SERVING_SESSIONS = "serving/sessions.py"
SERVING_PLACEMENT = "serving/placement.py"
CHUNKS = "transport/chunks.py"
FAULTS = "faults.py"

FnKey = Tuple[str, str]  # (relpath, qualname)


@dataclass
class LockSpec:
    """One declared lock: its rank in the acquisition hierarchy (SMALLER =
    must be taken FIRST / outermost) and a one-line role description."""

    cls: str  # owning class name
    attr: str  # attribute name on instances of cls
    rank: int
    doc: str = ""


@dataclass
class Targets:
    """The full target configuration handed to every rule."""

    # ---- hot-path families (PR 1 columnar fan-out) -----------------------
    hot_functions: Set[FnKey] = field(default_factory=set)
    hot_lock_functions: Set[FnKey] = field(default_factory=set)
    hot_telemetry_functions: Set[FnKey] = field(default_factory=set)
    hot_trace_functions: Set[FnKey] = field(default_factory=set)

    # ---- device-sync family ---------------------------------------------
    # the ONE blessed device->host transfer seam on the step path
    blessed_device_get: Set[FnKey] = field(default_factory=set)
    # dotted prefixes that name device-resident values in hot functions
    device_roots: Set[str] = field(default_factory=set)

    # ---- recompilation-hazard family ------------------------------------
    # modules whose top-level functions all run under jit (minus exempt)
    traced_modules: Set[str] = field(default_factory=set)
    traced_exempt: Set[str] = field(default_factory=set)  # root qualnames
    traced_functions: Set[FnKey] = field(default_factory=set)  # extras
    # parameter names that are static under jit everywhere they appear
    static_param_names: Set[str] = field(default_factory=set)

    # ---- lock-discipline family -----------------------------------------
    locks: List[LockSpec] = field(default_factory=list)
    # variable-name -> class hints for non-self lock expressions (sh._mu)
    lock_var_hints: Dict[str, str] = field(default_factory=dict)
    # relpath -> {class -> {field -> guarding lock attr}}
    guarded_state: Dict[str, Dict[str, Dict[str, str]]] = field(
        default_factory=dict
    )
    # method-name suffix asserting the caller already holds the lock
    locked_suffix: str = "_locked"

    # ---- interprocedural families (ISSUE 20) ----------------------------
    # (cls, attr) of locks on the engine step / per-node protocol path: a
    # blocking call (fsync, .result(), sleep, queue wait) TRANSITIVELY
    # reachable while one is held stalls the step loop for every lane
    # (locks/blocking-under-hot-lock)
    hot_locks: Set[Tuple[str, str]] = field(default_factory=set)
    # rule ids / families whose allow() pragmas are exempt from
    # pragma/unused — rules gated off by configuration (empty
    # device_roots, a family not enabled in this deployment) legitimately
    # suppress zero findings
    unused_pragma_allowlist: Set[str] = field(default_factory=set)

    # -- queries -----------------------------------------------------------
    def is_hot(self, key: FnKey) -> bool:
        return key in self.hot_functions

    def is_hot_lock(self, key: FnKey) -> bool:
        return key in self.hot_lock_functions or key in self.hot_functions

    def is_traced(self, key: FnKey) -> bool:
        relpath, qualname = key
        if key in self.traced_functions:
            return True
        return (
            relpath in self.traced_modules
            and qualname.split(".")[0] not in self.traced_exempt
        )

    def is_hot_lock_spec(self, spec: Optional["LockSpec"]) -> bool:
        return spec is not None and (spec.cls, spec.attr) in self.hot_locks

    def lock_rank(self, cls: Optional[str], attr: str, module=None):
        """Resolve (class, attr) -> LockSpec; subclass names resolve
        through the module's base map when one is provided."""
        for spec in self.locks:
            if spec.attr != attr:
                continue
            if cls is None or spec.cls == cls:
                return spec
            if module is not None and module.is_subclass_of(cls, spec.cls):
                return spec
        return None

    def all_function_targets(self):
        """(relpath, qualname, why) for config-drift detection."""
        for name in (
            "hot_functions",
            "hot_lock_functions",
            "hot_telemetry_functions",
            "hot_trace_functions",
            "blessed_device_get",
            "traced_functions",
        ):
            for relpath, qualname in sorted(getattr(self, name)):
                yield relpath, qualname, name


def _default_targets() -> Targets:
    # the step hot path: every function here runs once per engine step on
    # the loop thread (pack -> dispatch -> fetch -> decode/fan-out -> save)
    hot = {
        (VECTOR, "VectorEngine._run_once"),
        (VECTOR, "VectorEngine._pack"),
        (VECTOR, "VectorEngine._pack_wire"),
        (VECTOR, "VectorEngine._stage_row"),
        (VECTOR, "VectorEngine._flush_staged_rows"),
        (VECTOR, "VectorEngine._fetch_output"),
        (VECTOR, "VectorEngine._fetch_super"),
        (VECTOR, "VectorEngine._decode"),
        # the decode phase bodies (split out of _decode so the K-step
        # super-step path orchestrates the same code) and the multi-step
        # super-step machinery — all run once per engine step / inner step
        (VECTOR, "VectorEngine._decode_super"),
        (VECTOR, "VectorEngine._decode_place"),
        (VECTOR, "VectorEngine._refresh_mirrors"),
        (VECTOR, "VectorEngine._decode_send_rep"),
        (VECTOR, "VectorEngine._commit_saves"),
        (VECTOR, "VectorEngine._decode_send_post"),
        (VECTOR, "VectorEngine._decode_apply"),
        (VECTOR, "VectorEngine._decode_reads"),
        (VECTOR, "VectorEngine._routed_rep_plan"),
        (VECTOR, "VectorEngine._place_routed_reps"),
        (VECTOR, "VectorEngine._mask_routed"),
        (VECTOR, "VectorEngine._dispatch_sends"),
        (VECTOR, "VectorEngine._save_updates"),
        (VECTOR, "VectorEngine.try_local_deliver_many"),
        (VECTOR, "gather_replicate_sends"),
        (VECTOR, "gather_post_sends"),
        (VECTOR, "gather_resp_sends"),
        (VECTOR, "build_save_updates"),
    }
    # the transport send hot path: one lock/breaker-check per TARGET
    # BATCH, never per message
    hot_lock = {
        (TRANSPORT, "Transport.send_many"),
        (TRANSPORT, "_SendQueue.put_many"),
    }
    hot_telemetry = set(hot) | set(hot_lock) | {
        (TRANSPORT, "_SendQueue._admit_locked"),
        # the step-phase profiler's stamping seams (PR 6 attribution
        # plane): Sample.record + the phase-plane fan-out run once per
        # stage per step — they must stay inside the `if self.sampling`
        # gate or every step pays histogram/recorder work
        (TRACE, "Profiler.end"),
        (TRACE, "Profiler.add"),
        (PROFILE, "PhasePlane.on_phase"),
    }
    # request entry points that mint trace ids + the decode/send phases
    # that propagate them: unsampled requests stay allocation/event-free
    hot_trace = {
        (NODE, "Node.propose"),
        (NODE, "Node.propose_batch"),
        (NODE, "Node.propose_batch_async"),
        (NODE, "Node.apply_raft_update"),
        (VECTOR, "gather_replicate_sends"),
        (VECTOR, "gather_resp_sends"),
        (VECTOR, "VectorEngine._pack_wire"),
        (VECTOR, "VectorEngine._decode"),
        # the quorum_commit stamp moved into the split-out apply phase
        (VECTOR, "VectorEngine._decode_apply"),
        (TRANSPORT, "Transport.send_many"),
    }
    # the declared lock hierarchy, outermost first. Acquisition must go
    # DOWN this table; taking an equal-or-outer lock while holding an
    # inner one is an ordering violation.
    locks = [
        LockSpec(
            "ManagedStateMachine", "_mu", 10,
            "SM serialization (exclusive()): update+applied-advance and "
            "snapshot index+data each form one critical section (PR 3 "
            "snapshot skew fix)",
        ),
        LockSpec(
            "_Shard", "_wmu", 20,
            "logdb shard writer lock: append vs compaction boundary-batch "
            "rewrite (PR 3 lost-update fix)",
        ),
        LockSpec(
            "_Shard", "_mu", 30,
            "logdb shard cache lock (state/max-index/last-batch caches)",
        ),
        LockSpec(
            "Chunks", "_mu", 36,
            "inbound snapshot-stream tracker (resume fences, per-stream "
            "progress, stream counters); held across finalize's "
            "InstallSnapshot handoff and the abort notify, both of which "
            "take NodeHost._nodes_mu inside it",
        ),
        LockSpec(
            "PlacementPlane", "_mu", 35,
            "placement plan/active-migration table + migration ledger; "
            "outer of NodeHost._nodes_mu (the load fold and every "
            "migration step call into the host's request API, which "
            "takes _nodes_mu inside)",
        ),
        LockSpec(
            "SessionManager", "_mu", 37,
            "session pool + lifecycle counters; outer of "
            "NodeHost._nodes_mu for the same reason (checkout never "
            "holds it across a propose, but the rank keeps any future "
            "nesting legal in one direction only)",
        ),
        LockSpec(
            "NodeHost", "_nodes_mu", 38,
            "node registry + launch-spec table (the restart plane: "
            "stop/crash/restart_cluster all transition through it); held "
            "briefly on every inbound batch and API lookup, released "
            "before any engine or node lock is taken",
        ),
        LockSpec(
            "Transport", "_mu", 40,
            "transport registry lock (queue/breaker maps)",
        ),
        LockSpec(
            "Node", "_mu", 41,
            "per-node protocol lock (step vs API surface); API paths take "
            "it before marking the engine dirty",
        ),
        LockSpec(
            "VectorEngine", "_lanes_mu", 42, "engine lane registry",
        ),
        LockSpec(
            "VectorEngine", "_dirty_mu", 44,
            "engine dirty-set / pending-tick state",
        ),
        LockSpec(
            "VectorEngine", "_snap_status_mu", 44,
            "engine snapshot-completion set",
        ),
        LockSpec(
            "ServingFront", "_mu", 45,
            "serving-front tenant queue table (admitted-but-unsubmitted "
            "bulk ops); released before propose_batch is called, never "
            "held across engine or node locks",
        ),
        LockSpec(
            "AdmissionController", "_mu", 46,
            "admission tenant registry + admit/shed ledger",
        ),
        LockSpec(
            "SaturationMonitor", "_mu", 47,
            "cached saturation score + last signal sample",
        ),
        LockSpec(
            "_SendQueue", "_cv", 50,
            "send-queue condition (urgent/bulk deques + admission counters)",
        ),
        LockSpec(
            "_Breaker", "_mu", 50, "circuit-breaker state",
        ),
        LockSpec(
            "TokenBucket", "_mu", 55,
            "token-bucket balance/refill-time pair (leaf: one take() is "
            "one atomic refill+spend)",
        ),
        LockSpec(
            "_BarrierStats", "_mu", 60,
            "WAL barrier-pressure gauge (leaf: taken inside the fsync "
            "seam with shard locks already held)",
        ),
        LockSpec(
            "MmapRing", "_mu", 60,
            "flight-ring slot seal (leaf: taken with no other lock held)",
        ),
        LockSpec(
            "PhasePlane", "_mu", 60,
            "phase-histogram table (leaf: dict probe only; the Histogram "
            "observation itself happens outside it)",
        ),
        LockSpec(
            "SyncAudit", "_mu", 60,
            "device-sync site-attribution table (leaf)",
        ),
        LockSpec(
            "ClockPlane", "_mu", 60,
            "per-host clock-fault table (ISSUE 17: skew/drift/jump "
            "anchors); leaf — clock_fn closures read it from every tick "
            "worker with no other lock held, mutations come from the "
            "chaos scheduler thread",
        ),
        LockSpec(
            "CompileWatch", "_mu", 60,
            "compile-event counters + registered-function table (leaf)",
        ),
        LockSpec(
            "DeviceCensus", "_mu", 60,
            "HBM census plane table (leaf: written once at engine init, "
            "read by the 1/s export paths)",
        ),
        LockSpec(
            "HistorySampler", "_mu", 60,
            "telemetry-history ring handle (leaf: the sampler thread "
            "copies the ref out under it and writes the ring outside; "
            "the sample itself only reads zero-sync stat exports, never "
            "another lock)",
        ),
    ]
    guarded_state = {
        TRANSPORT: {
            "_SendQueue": {
                "_urgent": "_cv",
                "_bulk": "_cv",
                "_closed": "_cv",
                "evicted_bulk": "_cv",
                "dropped_bulk": "_cv",
                "dropped_urgent": "_cv",
            },
            "_Breaker": {
                "_state": "_mu",
                "_fails": "_mu",
                "_nominal": "_mu",
                "_cooldown": "_mu",
                "_opened_at": "_mu",
                "_probe_inflight": "_mu",
                "opens": "_mu",
                "probes": "_mu",
                "probe_failures": "_mu",
            },
        },
        LOGDB: {
            "_Shard": {
                "_state_cache": "_mu",
                "_max_index_cache": "_mu",
                "_batch_cache": "_mu",
            },
        },
        TRACE: {
            "MmapRing": {"_seq": "_mu", "_mm": "_mu"},
        },
        PROFILE: {
            "PhasePlane": {"_hists": "_mu"},
            "SyncAudit": {"_out": "_mu"},
            "CompileWatch": {"_fns": "_mu"},
            "DeviceCensus": {"_planes": "_mu"},
            # the history sampler's ring handle swaps on stop(); the
            # plain-int sample/error counters are sampler-thread-only
            "HistorySampler": {"_ring": "_mu"},
        },
        MANAGED: {
            "ManagedStateMachine": {"_destroyed": "_mu"},
        },
        VECTOR: {
            "VectorEngine": {
                "_dirty": "_dirty_mu",
                "_gc_set": "_dirty_mu",
                "_pending_ticks": "_dirty_mu",
                "_snap_status": "_snap_status_mu",
                "_lanes": "_lanes_mu",
                # the restart plane's lane recycling (ISSUE 7): the free
                # list, g->lane table and message route are read by the
                # loop/delivery hot paths and mutated by add/remove/
                # _deactivate — a write outside _lanes_mu is exactly the
                # double-free / stale-route class of restart bug
                "_free": "_lanes_mu",
                "_lane_by_g": "_lanes_mu",
                "_route": "_lanes_mu",
                # the clock-fault plane (ISSUE 17): per-host suspect
                # deadlines are written by tick-worker threads reporting
                # anomalies and drained by the loop thread — a write
                # outside _dirty_mu is a lost-revocation (stale lease
                # read) class of bug. The lease mirrors themselves
                # (_m_lease_ok, _lease_local, _lease_fb) are loop-thread
                # only, like every other _m_* mirror.
                "_clock_suspect": "_dirty_mu",
            },
        },
        # the clock-fault plane (ISSUE 17): each host's [anchor_real,
        # anchor_fault, rate] triple is read by that host's tick worker
        # on every tick and rewritten by the chaos scheduler — a write
        # outside _mu tears the re-anchor continuity rule and turns a
        # drift change into a spurious step jump
        FAULTS: {
            "ClockPlane": {"_hosts": "_mu"},
        },
        NODEHOST: {
            "NodeHost": {
                "_nodes": "_nodes_mu",
                "_launch_specs": "_nodes_mu",
                # live-migration tag set (serving/placement.py): read by
                # the inbound chunk tracker on every stream begin
                "_migrating": "_nodes_mu",
            },
        },
        # the serving overload plane (ISSUE 8): admit/shed decisions and
        # the saturation cache are read on every client request from many
        # threads — a write outside the declared lock is exactly the
        # lost-increment / torn-decision class of admission bug
        KV: {
            "_BarrierStats": {
                "ewma_s": "_mu",
                "last_s": "_mu",
                "last_wave_s": "_mu",
                "inflight": "_mu",
                "barriers": "_mu",
            },
        },
        SERVING_ADMISSION: {
            "AdmissionController": {"_tenants": "_mu"},
            "TokenBucket": {"tokens": "_mu", "_t": "_mu"},
        },
        SERVING_BACKPRESSURE: {
            "SaturationMonitor": {
                "_cached": "_mu",
                "_cached_at": "_mu",
                "_last_signals": "_mu",
            },
        },
        SERVING_FRONT: {
            "ServingFront": {"_queues": "_mu"},
        },
        # the millions-of-users plane (ISSUE 14): the session pools and
        # the migration ledger are mutated from client threads, the
        # placement pacer and teardown — a write outside the declared
        # lock is a lost-session / double-migration class of bug
        SERVING_SESSIONS: {
            "SessionManager": {
                "_pools": "_mu",
                "_counters": "_mu",
                "_dead": "_mu",
            },
        },
        SERVING_PLACEMENT: {
            "PlacementPlane": {
                "_active": "_mu",
                "_counters": "_mu",
                "_last_lanes": "_mu",
                "_abort": "_mu",
            },
        },
        # the streamed-install plane (ISSUE 13): the stream tracker and
        # its resume/abort counters are mutated from transport delivery
        # threads and the tick sweeper — a write outside _mu is exactly
        # the torn-progress / double-count class of resume bug
        CHUNKS: {
            "Chunks": {
                "_tracked": "_mu",
                "_tick": "_mu",
                "_resumed_streams": "_mu",
                "_skipped_chunks": "_mu",
                "_aborted_streams": "_mu",
                "_completed_streams": "_mu",
                "_migration_streams": "_mu",
            },
        },
    }
    return Targets(
        hot_functions=hot,
        hot_lock_functions=hot_lock,
        hot_telemetry_functions=hot_telemetry,
        hot_trace_functions=hot_trace,
        blessed_device_get={
            (VECTOR, "VectorEngine._fetch_output"),
            # the multi-step engine's once-per-K-steps consolidated
            # transfer (mirrors profile.SyncAudit.BLESSED)
            (VECTOR, "VectorEngine._fetch_super"),
        },
        device_roots={"self._state"},
        traced_modules={KERNEL},
        traced_exempt={
            "make_step_fn",
            "make_multi_step_fn",
            # the sharded twin: shard_map + jit factory (same contract)
            "make_sharded_multi_step_fn",
            # host-side backend/env probe deciding Pallas ring vs XLA
            # all-gather — runs at trace time, not inside the kernel
            "_pallas_route_active",
        },
        traced_functions={(VECTOR, "_make_activate_fn.apply")},
        # `steps` is the super-step scan length: a compile-time constant
        # baked into the executable by make_multi_step_fn (a traced K
        # would rebuild the scan per value — the retrace family's
        # recompile-hazard meta-test covers exactly this). The sharded
        # factory additionally bakes the mesh and the cross-shard axis
        # (axis_name/n_shards): all compile-time topology, never traced.
        static_param_names={
            "cfg", "donate", "steps", "mesh", "axis_name", "n_shards",
        },
        locks=locks,
        lock_var_hints={
            "node": "Node",
            "sh": "_Shard",
            "sq": "_SendQueue",
            "breaker": "_Breaker",
        },
        guarded_state=guarded_state,
        # blocking work must never be reachable under these: the engine
        # lane/dirty/snap registries gate the step loop itself, and
        # Node._mu gates every protocol step and API call on that node.
        # (_SendQueue._cv is deliberately NOT here: waiting on the send
        # condition IS its job, and the sender thread owns that latency.)
        hot_locks={
            ("VectorEngine", "_lanes_mu"),
            ("VectorEngine", "_dirty_mu"),
            ("VectorEngine", "_snap_status_mu"),
            ("Node", "_mu"),
        },
    )


DEFAULT_TARGETS = _default_targets()

__all__ = [
    "DEFAULT_TARGETS",
    "FnKey",
    "LockSpec",
    "Targets",
    "CHUNKS",
    "FAULTS",
    "KERNEL",
    "KV",
    "LOGDB",
    "MANAGED",
    "NODE",
    "NODEHOST",
    "PROFILE",
    "SERVING_ADMISSION",
    "SERVING_BACKPRESSURE",
    "SERVING_FRONT",
    "SERVING_PLACEMENT",
    "SERVING_SESSIONS",
    "STATE",
    "TRACE",
    "TRANSPORT",
    "VECTOR",
]
