"""Device-sync detection: implicit device->host transfers on the hot path.

The engine's step loop is architected around exactly ONE device->host
transfer per step (`VectorEngine._fetch_output`: a single consolidated
`jax.device_get` of the whole StepOutput). Everything after it works on
host numpy mirrors. Any OTHER transfer in a hot function — an explicit
`jax.device_get`, a `.block_until_ready()`, an `np.asarray(...)` /
`float()/int()/bool()` coercion of a device value, or scalar indexing of
a device array inside a loop — blocks the async dispatch pipeline and
silently reintroduces the per-step sync the columnar refactor removed.
No test fails; the BENCH numbers just quietly decay.

Device values are recognized by dotted-prefix roots declared in
`targets.device_roots` (the engine's device state lives under
`self._state`); the heuristic is deliberately narrow — a false negative
costs a missed review comment, a false positive costs everyone a pragma.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from .engine import Finding, FunctionInfo, Rule

_SYNC_ATTR_CALLS = ("block_until_ready",)
_COERCIONS = ("int", "float", "bool")


def dotted_parts(expr: ast.AST) -> Optional[List[str]]:
    """`self._state.term[g]` -> ["self", "_state", "term"]; None when the
    expression is not a name/attribute/subscript chain."""
    parts: List[str] = []
    node = expr
    while True:
        if isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        elif isinstance(node, ast.Name):
            parts.append(node.id)
            return list(reversed(parts))
        else:
            return None


def _rooted_in(expr: ast.AST, roots) -> bool:
    parts = dotted_parts(expr)
    if parts is None:
        return False
    dotted = ".".join(parts)
    return any(dotted == r or dotted.startswith(r + ".") for r in roots)


def _mentions_device_root(expr: ast.AST, roots) -> bool:
    """Any sub-expression rooted in a declared device root."""
    for node in ast.walk(expr):
        if isinstance(node, (ast.Attribute, ast.Name, ast.Subscript)):
            if _rooted_in(node, roots):
                return True
    return False


class DeviceGetOutsideSeam(Rule):
    id = "device-sync/device-get"
    doc = (
        "jax.device_get()/.block_until_ready() in a hot function outside "
        "the blessed _fetch_output seam — a second per-step transfer "
        "stalls the async dispatch pipeline"
    )
    motivation = (
        "PR 1: the step loop pays exactly one consolidated device->host "
        "fetch; extra syncs erase the columnar win without failing a test"
    )

    def check_function(self, fn: FunctionInfo, targets) -> Iterable[Finding]:
        if fn.key() not in targets.hot_functions:
            return
        if fn.key() in targets.blessed_device_get:
            return
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute):
                if f.attr == "device_get":
                    yield self.finding(
                        fn,
                        node,
                        "device_get outside the blessed _fetch_output seam",
                    )
                elif f.attr in _SYNC_ATTR_CALLS:
                    yield self.finding(
                        fn, node, f".{f.attr}() forces a device sync"
                    )
            elif isinstance(f, ast.Name) and f.id == "device_get":
                yield self.finding(
                    fn,
                    node,
                    "device_get outside the blessed _fetch_output seam",
                )


class HostCoercionOfDeviceValue(Rule):
    id = "device-sync/scalar-read"
    doc = (
        "float()/int()/bool()/.item() applied to a device value "
        "(targets.device_roots) in a hot function — each coercion is one "
        "blocking device->host transfer"
    )
    motivation = (
        "PR 1: scalar reads of device arrays were the per-message host "
        "work the whole-column gathers removed"
    )

    def check_function(self, fn: FunctionInfo, targets) -> Iterable[Finding]:
        if fn.key() not in targets.hot_functions:
            return
        roots = targets.device_roots
        if not roots:
            return
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (
                isinstance(f, ast.Name)
                and f.id in _COERCIONS
                and node.args
                and _mentions_device_root(node.args[0], roots)
            ):
                yield self.finding(
                    fn,
                    node,
                    f"{f.id}() on a device value is an implicit "
                    f"device->host sync",
                )
            elif (
                isinstance(f, ast.Attribute)
                and f.attr == "item"
                and _mentions_device_root(f.value, roots)
            ):
                yield self.finding(
                    fn,
                    node,
                    ".item() on a device value is an implicit "
                    "device->host sync",
                )


class AsarrayOnDeviceValue(Rule):
    id = "device-sync/host-array"
    doc = (
        "np.asarray()/np.array() of a device value in a hot function — a "
        "whole-plane implicit transfer outside the consolidated fetch"
    )
    motivation = (
        "PR 1: plane fetches belong in _fetch_output where they ship as "
        "ONE batched transfer; ad-hoc np.asarray pulls add per-dispatch "
        "overhead and block the pipeline"
    )

    def check_function(self, fn: FunctionInfo, targets) -> Iterable[Finding]:
        if fn.key() not in targets.hot_functions:
            return
        roots = targets.device_roots
        if not roots:
            return
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in ("asarray", "array")
                and isinstance(f.value, ast.Name)
                and f.value.id in ("np", "numpy")
                and node.args
                and _mentions_device_root(node.args[0], roots)
            ):
                yield self.finding(
                    fn,
                    node,
                    f"np.{f.attr}() on a device value is an implicit "
                    f"whole-plane transfer",
                )


class DeviceScalarIndexInLoop(Rule):
    id = "device-sync/index-in-loop"
    doc = (
        "scalar indexing of a device array inside a for/while body of a "
        "hot function — O(iterations) device round-trips"
    )
    motivation = (
        "PR 1: per-lane device reads in the fan-out loops were the "
        "measured hot spot the numpy mirrors replaced"
    )

    def check_function(self, fn: FunctionInfo, targets) -> Iterable[Finding]:
        if fn.key() not in targets.hot_functions:
            return
        roots = targets.device_roots
        if not roots:
            return
        for _loop, sub in self.loop_body_nodes(fn.node):
            if isinstance(sub, ast.Subscript) and _rooted_in(
                sub.value, roots
            ):
                yield self.finding(
                    fn,
                    sub,
                    "device-array indexing inside a hot loop (gather the "
                    "column once outside the loop)",
                )


RULES = [
    DeviceGetOutsideSeam(),
    HostCoercionOfDeviceValue(),
    AsarrayOnDeviceValue(),
    DeviceScalarIndexInLoop(),
]

__all__ = [
    "RULES",
    "AsarrayOnDeviceValue",
    "DeviceGetOutsideSeam",
    "DeviceScalarIndexInLoop",
    "HostCoercionOfDeviceValue",
    "dotted_parts",
]
