"""Lock-discipline checking for the host runtime.

The lock hierarchy is DECLARED in targets.py (LockSpec table: rank,
owner class, role); these rules enforce it lexically:

  locks/order         — a `with` acquiring an equal-or-outer-ranked lock
                        while an inner-ranked one is held: the textbook
                        deadlock shape (two threads, opposite orders).
  locks/guarded-state — a write to declared guarded shared state outside
                        its lock. Both PR 3 races (snapshot index/data
                        skew, logdb compaction-vs-append lost update) were
                        exactly this: documented-shared-state mutated on a
                        path that skipped the documented lock.

Conventions honored:
  * methods named `*_locked` assert the caller holds the lock (the
    in-tree convention: `_admit_locked`, `_pop_locked`, ...);
  * `__init__` is exempt (no concurrent access before publication);
  * nested `def`s do not inherit the enclosing `with` — they run later,
    possibly without the lock (each is checked separately).

Limits (documented, not hidden): THESE rules are lexical and
per-function; lock objects are recognized by `<root>.<attr>` shape with
class resolution via `self`/declared variable hints. The interprocedural
counterparts live in rules_xlocks.py on top of the static call graph
(callgraph.py): locks taken by callees, the `*_locked` caller-holds
convention, and blocking calls under engine-hot locks are checked there.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from .engine import Finding, FunctionInfo, Rule
from .rules_device import dotted_parts

# mutating method names on containers/deques/sets/dicts
_MUTATORS = frozenset(
    {
        "append",
        "appendleft",
        "extend",
        "extendleft",
        "pop",
        "popleft",
        "popitem",
        "clear",
        "add",
        "discard",
        "remove",
        "update",
        "difference_update",
        "intersection_update",
        "setdefault",
        "insert",
        "rotate",
        "write",  # mmap/file-like guarded handles
        "flush",
        "close",
    }
)


def _lock_ref(expr: ast.AST) -> Optional[Tuple[str, str]]:
    """`self._mu` / `sh._wmu` / `self._sq._cv` -> (dotted root, attr);
    None otherwise."""
    parts = dotted_parts(expr)
    if parts is None or len(parts) < 2:
        return None
    return ".".join(parts[:-1]), parts[-1]


def _resolve_spec(fn: FunctionInfo, targets, root: str, attr: str):
    if root == "self":
        return targets.lock_rank(fn.class_name, attr, fn.module)
    cls = targets.lock_var_hints.get(root)
    if cls is not None:
        return targets.lock_rank(cls, attr, fn.module)
    # unambiguous attr (exactly one spec with that name) still resolves
    matches = [s for s in targets.locks if s.attr == attr]
    return matches[0] if len(matches) == 1 else None


def _walk_with_stack(fn_node, on_with=None, on_node=None):
    """Walk a function body tracking lexically-held `with` items; nested
    function defs are NOT entered (their bodies run later, lock-free)."""

    held: List[Tuple[ast.With, ast.AST]] = []  # (with stmt, context expr)

    def visit(node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # checked as its own FunctionInfo
        if isinstance(node, (ast.With, ast.AsyncWith)):
            if on_with is not None:
                on_with(node, held)
            for item in node.items:
                held.append((node, item.context_expr))
            for c in node.body:
                visit(c)
            del held[len(held) - len(node.items):]
            return
        if on_node is not None:
            on_node(node, held)
        for c in ast.iter_child_nodes(node):
            visit(c)

    for c in fn_node.body:
        visit(c)


class LockOrder(Rule):
    id = "locks/order"
    doc = (
        "nested `with` acquiring a lock ranked at-or-above one already "
        "held (declared hierarchy: analysis/targets.py LockSpec table) — "
        "the opposite-order deadlock shape"
    )
    motivation = (
        "the PR 2/3 concurrency bugs all lived at lock boundaries; the "
        "hierarchy table makes the intended order checkable instead of "
        "tribal"
    )

    def check_function(self, fn: FunctionInfo, targets) -> Iterable[Finding]:
        out: List[Finding] = []

        def on_with(node, held):
            specs_held = []
            for _w, expr in held:
                ref = _lock_ref(expr)
                if ref is None:
                    continue
                spec = _resolve_spec(fn, targets, *ref)
                if spec is not None:
                    specs_held.append((spec, ref))
            if not specs_held:
                return
            for item in node.items:
                ref = _lock_ref(item.context_expr)
                if ref is None:
                    continue
                spec = _resolve_spec(fn, targets, *ref)
                if spec is None:
                    continue
                for h, href in specs_held:
                    if href == ref:
                        continue  # the same lock EXPRESSION (reentrancy
                        # is a different bug class; keep the signal clean)
                    if spec.rank <= h.rank:
                        # h is spec with a DIFFERENT root is the
                        # two-instance AB/BA shape (self._mu then
                        # node._mu on another instance of the same
                        # class): undefined instance order, so it flags
                        detail = (
                            "two instances of the same lock with no "
                            "defined instance order"
                            if h is spec
                            else "declared order is the reverse"
                        )
                        out.append(
                            self.finding(
                                fn,
                                node,
                                f"acquires {spec.cls}.{spec.attr} "
                                f"(rank {spec.rank}) while holding "
                                f"{h.cls}.{h.attr} (rank {h.rank}) — "
                                f"{detail}",
                            )
                        )

        _walk_with_stack(fn.node, on_with=on_with)
        return out


class GuardedStateWrite(Rule):
    id = "locks/guarded-state"
    doc = (
        "write/mutation of declared guarded shared state "
        "(targets.guarded_state) outside a lexical `with` on its "
        "declared lock (methods named *_locked assert the caller holds "
        "it; __init__ is exempt)"
    )
    motivation = (
        "PR 3 found two shipped races of exactly this shape: snapshot "
        "index/data skew and the logdb compaction-vs-append lost update"
    )

    def check_function(self, fn: FunctionInfo, targets) -> Iterable[Finding]:
        module_map = targets.guarded_state.get(fn.module.relpath)
        if not module_map:
            return []
        if fn.name == "__init__" or fn.name.endswith(targets.locked_suffix):
            return []
        out: List[Finding] = []

        def guard_for(root: str, field_name: str) -> Optional[str]:
            """The lock attr guarding <root>.<field>, or None."""
            if root == "self":
                for cls, fields in module_map.items():
                    if field_name in fields and fn.module.is_subclass_of(
                        fn.class_name, cls
                    ):
                        return fields[field_name]
                return None
            for fields in module_map.values():
                if field_name in fields:
                    return fields[field_name]
            return None

        def held_locks(held):
            refs = set()
            for _w, expr in held:
                ref = _lock_ref(expr)
                if ref is not None:
                    refs.add(ref)
            return refs

        def attr_write_target(node) -> List[Tuple[str, str, ast.AST]]:
            """(root, field, node) for each guarded-shape write target."""
            targets_ = []
            if isinstance(node, ast.Assign):
                tgts = node.targets
            elif isinstance(node, (ast.AugAssign,)):
                tgts = [node.target]
            elif isinstance(node, ast.Delete):
                tgts = node.targets
            else:
                return targets_
            for t in tgts:
                base = t
                if isinstance(base, ast.Subscript):
                    base = base.value  # self._lanes[key] = ...
                parts = dotted_parts(base)
                if parts is not None and len(parts) == 2:
                    targets_.append((parts[0], parts[1], t))
            return targets_

        def on_node(node, held):
            # 1. assignments / deletions
            for root, field_name, t in attr_write_target(node):
                lock = guard_for(root, field_name)
                if lock is None:
                    continue
                if (root, lock) not in held_locks(held):
                    out.append(
                        self.finding(
                            fn,
                            node,
                            f"writes {root}.{field_name} outside "
                            f"`with {root}.{lock}` (declared guard)",
                        )
                    )
            # 2. mutating method calls: self._bulk.append(...)
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS
            ):
                parts = dotted_parts(node.func.value)
                if parts is None or len(parts) != 2:
                    return
                root, field_name = parts
                lock = guard_for(root, field_name)
                if lock is None:
                    return
                if (root, lock) not in held_locks(held):
                    out.append(
                        self.finding(
                            fn,
                            node,
                            f"mutates {root}.{field_name}."
                            f"{node.func.attr}() outside "
                            f"`with {root}.{lock}` (declared guard)",
                        )
                    )

        _walk_with_stack(fn.node, on_node=on_node)
        return out


RULES = [LockOrder(), GuardedStateWrite()]

__all__ = ["RULES", "GuardedStateWrite", "LockOrder"]
