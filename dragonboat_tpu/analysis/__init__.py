"""`dragonboat_tpu.analysis`: the static-analysis subsystem.

A pure-AST rule engine (no imports of the checked code, no jax in the
process) plus three analyzer families for the two failure classes that
keep biting this architecture:

  * silent hot-path regressions — device syncs and recompilation hazards
    on the compiled JAX step loop (`device-sync/*`, `retrace/*`, plus the
    four hot-path families migrated from tests/test_hot_path_lint.py:
    `columnar/*`, `locks/lock-in-hot-loop`, `telemetry/unguarded`,
    `trace/unguarded-stamp`);
  * host-side lock-discipline races — a declared lock hierarchy and
    guarded-state map checked lexically (`locks/order`,
    `locks/guarded-state`).

Entry points:

    python -m dragonboat_tpu.tools.check [--json] [paths...]
    from dragonboat_tpu.analysis import build_analyzer
    findings = build_analyzer().run()

Suppression: `# lint: allow(<rule-or-family>) <reason>` on the flagged
line (or alone on the line above). See engine.py for pragma semantics.
"""
from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

from .engine import (
    Analyzer,
    CrossRule,
    Finding,
    FunctionInfo,
    LEGACY_MARK,
    Rule,
    SourceModule,
    unsuppressed,
)
from .targets import DEFAULT_TARGETS, LockSpec, Targets
from . import (
    rules_device,
    rules_hotpath,
    rules_locks,
    rules_retrace,
    rules_xlocks,
    rules_xretrace,
    rules_xsync,
)

#: bumped when the rule set / semantics change in a way that invalidates
#: stored baselines ("1.x" = the PR 5 lexical engine; "2.x" = the
#: interprocedural call-graph pass). Recorded in --json output and the
#: longhaul preflight header so a run report pins WHICH gate it passed.
RULES_VERSION = "2.0"

#: every registered rule, in family order (hotpath -> device -> retrace
#: -> locks -> interprocedural); tools.check --list-rules renders this
ALL_RULES: List[Rule] = (
    list(rules_hotpath.RULES)
    + list(rules_device.RULES)
    + list(rules_retrace.RULES)
    + list(rules_locks.RULES)
    + list(rules_xlocks.RULES)
    + list(rules_xretrace.RULES)
    + list(rules_xsync.RULES)
)

FAMILIES = sorted({r.id.split("/", 1)[0] for r in ALL_RULES})


def rules_for_families(families: Iterable[str]) -> List[Rule]:
    fams = set(families)
    return [r for r in ALL_RULES if r.id.split("/", 1)[0] in fams]


def build_analyzer(
    families: Optional[Sequence[str]] = None,
    targets: Targets = DEFAULT_TARGETS,
    root: str = "",
) -> Analyzer:
    """The standard analyzer over the dragonboat_tpu package root; narrow
    to specific rule families with `families=("columnar", "locks")`."""
    rules = ALL_RULES if families is None else rules_for_families(families)
    # pragma/unused is only meaningful when every rule ran: a family-
    # restricted run would call every other family's pragmas dead
    return Analyzer(rules, targets, root=root, unused_pragmas=families is None)


def run_default(paths: Optional[Sequence[str]] = None) -> List[Finding]:
    return build_analyzer().run(paths)


__all__ = [
    "ALL_RULES",
    "Analyzer",
    "CrossRule",
    "DEFAULT_TARGETS",
    "FAMILIES",
    "Finding",
    "FunctionInfo",
    "LEGACY_MARK",
    "LockSpec",
    "RULES_VERSION",
    "Rule",
    "SourceModule",
    "Targets",
    "build_analyzer",
    "rules_for_families",
    "run_default",
    "unsuppressed",
]
