"""Interprocedural lock discipline over the static call graph.

The lexical rules (rules_locks.py) see one function at a time; these see
the program. Three checks, all driven by callgraph.Program:

  locks/cross-function-order    — the held-lock set propagates through
      call edges: a function holding rank-N that (transitively) calls
      into an acquisition of an equal-or-outer rank is the same deadlock
      shape as a nested `with`, just split across frames — exactly where
      the lexical rule's blind spot was.
  locks/locked-callee-unheld    — VERIFY the `*_locked` caller-holds
      convention instead of trusting the suffix: a call to a `*_locked`
      method whose class declares exactly one lock must happen with that
      lock lexically held on the same receiver (or from a `*_locked`
      sibling / `__init__` of the same class). Call sites inside nested
      closures are their own functions with their own (usually empty)
      held set — which is precisely the "closure runs later, lock not
      held" bug the old per-function skip could never express.
  locks/blocking-under-hot-lock — fsync / .result() / sleep / queue
      waits reachable (transitively) while an engine-hot lock
      (targets.hot_locks) is held: every lane's step stalls behind one
      blocking call. `.wait()` on the condition that IS the held lock is
      the CV idiom and exempt.

Acquisition reachability deliberately ignores DEFERRED edges (closures
created here but called later): the closure does not run under the
caller's `with`, so its acquisitions are not nested inside it — flagging
them would be pure noise. The closure body is still checked on its own.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .callgraph import FnKey, Program, lock_ref, resolve_lock_spec, walk_with_held
from .engine import CrossRule, Finding, FunctionInfo


def _chain_str(program: Program, chain: Tuple[FnKey, ...]) -> str:
    return " -> ".join(qn for _rp, qn in chain)


def _lexical_acquisitions(fn: FunctionInfo, targets):
    """(LockSpec, lineno) for every `with <lock>` in this function's own
    body (nested defs excluded — they are their own functions)."""
    out = []

    def visit(node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                ref = lock_ref(item.context_expr)
                if ref is not None:
                    spec = resolve_lock_spec(fn, targets, *ref)
                    if spec is not None:
                        out.append((spec, node.lineno))
        for c in ast.iter_child_nodes(node):
            visit(c)

    for c in fn.node.body:
        visit(c)
    return out


def _acq_star(program: Program):
    """FnKey -> {(cls, attr) -> (LockSpec, witness chain)} of every lock
    acquisition reachable through non-deferred call edges (fixpoint;
    chains are first-discovered witnesses, cycles terminate on set
    membership)."""
    graph = program.graph
    targets = program.targets
    acc: Dict[FnKey, Dict[Tuple[str, str], Tuple[object, Tuple[FnKey, ...]]]] = {}
    for key, fn in graph.functions.items():
        acc[key] = {}
        for spec, _ln in _lexical_acquisitions(fn, targets):
            acc[key].setdefault((spec.cls, spec.attr), (spec, (key,)))
    changed = True
    while changed:
        changed = False
        for key in graph.functions:
            for site in graph.callees(key):
                for k2, (spec, chain) in acc.get(site.callee, {}).items():
                    if k2 not in acc[key]:
                        acc[key][k2] = (spec, (key,) + chain)
                        changed = True
    return acc


class CrossLockOrder(CrossRule):
    id = "locks/cross-function-order"
    doc = (
        "holding a declared lock across a call whose callee (transitively) "
        "acquires an equal-or-outer-ranked lock — the nested-with deadlock "
        "shape split across stack frames"
    )
    motivation = (
        "ISSUE 20: the lexical rule's documented blind spot; a lock taken "
        "by a callee was invisible, so the hierarchy was only enforced "
        "within single functions"
    )

    def check_program(self, program: Program) -> Iterable[Finding]:
        acq = _acq_star(program)
        for site in program.graph.edges:
            if site.deferred or not site.held:
                continue
            caller_fn = program.graph.functions.get(site.caller)
            if caller_fn is None:
                continue
            for k2, (spec, chain) in sorted(acq.get(site.callee, {}).items()):
                for h in site.held:
                    if h.spec is None:
                        continue
                    if spec.rank > h.spec.rank:
                        continue
                    if spec is h.spec:
                        detail = (
                            "same lock reacquired through the call chain "
                            "(self-deadlock on one instance, undefined "
                            "order across two)"
                        )
                    else:
                        detail = "declared order is the reverse"
                    yield self.finding(
                        caller_fn,
                        site.node,
                        f"holds {h.spec.cls}.{h.spec.attr} (rank "
                        f"{h.spec.rank}) across a call that acquires "
                        f"{spec.cls}.{spec.attr} (rank {spec.rank}) via "
                        f"{_chain_str(program, chain)} — {detail}",
                    )


class LockedCalleeUnheld(CrossRule):
    id = "locks/locked-callee-unheld"
    doc = (
        "call to a `*_locked` method without its class's declared lock "
        "lexically held on the same receiver (callers named `*_locked` on "
        "the same class, and `__init__`, assert it instead) — the "
        "caller-holds convention, verified rather than trusted"
    )
    motivation = (
        "ISSUE 20: the `_locked` suffix was an unchecked comment; one "
        "call site that skips the lock makes the suffix a lie and the "
        "race invisible"
    )

    def check_program(self, program: Program) -> Iterable[Finding]:
        targets = program.targets
        suffix = targets.locked_suffix
        for site in program.graph.edges:
            if site.deferred:
                continue  # a nested def EXISTS here; it is not CALLED here
            callee = program.graph.functions.get(site.callee)
            if callee is None or not callee.name.endswith(suffix):
                continue
            if callee.class_name is None:
                continue  # module-level helper: no declared class lock
            specs = [s for s in targets.locks if s.cls == callee.class_name]
            if len(specs) != 1:
                # multi-lock classes (VectorEngine, _Shard): WHICH lock a
                # given _locked method asserts is not declared — skip
                # rather than guess
                continue
            lock_attr = specs[0].attr
            caller = program.graph.functions.get(site.caller)
            if caller is None:
                continue
            # ANY lock held on the same receiver satisfies the convention:
            # classes keep auxiliary undeclared mutexes (Node._init_mu
            # guards the one-shot recovery path) and a `*_locked` callee
            # may assert one of those — the bug class this rule exists
            # for is the call with NOTHING held on the receiver
            held = any(h.root == site.recv_root for h in site.held)
            if held:
                continue
            same_class = (
                caller.class_name == callee.class_name
                and site.recv_root in ("self", "cls")
            )
            if same_class and (
                caller.name.endswith(suffix) or caller.name == "__init__"
            ):
                continue
            yield self.finding(
                caller,
                site.node,
                f"calls {callee.class_name}.{callee.name} without holding "
                f"{site.recv_root or '<recv>'}.{lock_attr} — `{suffix}` "
                f"methods assert the caller holds the class lock",
            )


# call shapes that block the calling thread
_BLOCKING_ATTRS = ("result", "wait", "wait_for")
_CV_WAITS = ("wait", "wait_for")


def _blocking_desc(node: ast.Call, held_refs) -> Optional[str]:
    """Describe a blocking call, or None. `.wait()`/`wait_for()` on a
    lock lexically held at the site is the CV idiom (you wait ON the
    lock you hold) and returns None."""
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr in ("fsync", "sleep"):
            return f"{f.attr}()"
        if f.attr in _BLOCKING_ATTRS:
            if f.attr in _CV_WAITS:
                recv = lock_ref(f.value)
                if recv is not None and recv in held_refs:
                    return None
            return f".{f.attr}()"
    elif isinstance(f, ast.Name) and f.id in ("fsync", "sleep"):
        return f"{f.id}()"
    return None


def _lexical_blocking(fn: FunctionInfo):
    """(desc, node) for each non-exempt blocking call in this function's
    own body."""
    out = []
    for kind, node, held_refs in walk_with_held(fn.node):
        if kind != "call":
            continue
        desc = _blocking_desc(node, held_refs)
        if desc is not None:
            out.append((desc, node))
    return out


def _blk_star(program: Program):
    """FnKey -> (desc, witness chain) for functions from which a
    (non-exempt) blocking call is reachable through non-deferred edges."""
    graph = program.graph
    acc: Dict[FnKey, Tuple[str, Tuple[FnKey, ...]]] = {}
    for key, fn in graph.functions.items():
        sites = _lexical_blocking(fn)
        if sites:
            acc[key] = (sites[0][0], (key,))
    changed = True
    while changed:
        changed = False
        for key in graph.functions:
            if key in acc:
                continue
            for site in graph.callees(key):
                w = acc.get(site.callee)
                if w is not None:
                    acc[key] = (w[0], (key,) + w[1])
                    changed = True
                    break
    return acc


class BlockingUnderHotLock(CrossRule):
    id = "locks/blocking-under-hot-lock"
    doc = (
        "a blocking call (fsync, .result(), sleep, queue/future wait) "
        "lexically or transitively reachable while an engine-hot lock "
        "(targets.hot_locks) is held — one blocked thread stalls every "
        "lane's step"
    )
    motivation = (
        "ISSUE 20: the step loop's locks gate all lanes; blocking I/O "
        "under one turns a per-node hiccup into a cluster-wide stall, "
        "and only a transitive check can see the fsync three frames down"
    )

    def check_program(self, program: Program) -> Iterable[Finding]:
        targets = program.targets
        if not targets.hot_locks:
            return
        blk = _blk_star(program)
        for key, fn in program.graph.functions.items():
            # 1. blocking call directly under a hot `with`
            for kind, node, held_refs in walk_with_held(fn.node):
                if kind != "call":
                    continue
                desc = _blocking_desc(node, held_refs)
                if desc is None:
                    continue
                hot = self._hot_held(fn, targets, held_refs)
                if hot is None:
                    continue
                yield self.finding(
                    fn,
                    node,
                    f"{desc} while holding {hot.cls}.{hot.attr} "
                    f"(engine-hot) — blocks every lane's step",
                )
            # 2. hot lock held across an edge into blocking-reachable code
            for site in program.graph.callees(key):
                w = blk.get(site.callee)
                if w is None:
                    continue
                for h in site.held:
                    if targets.is_hot_lock_spec(h.spec):
                        yield self.finding(
                            fn,
                            site.node,
                            f"holds {h.spec.cls}.{h.spec.attr} (engine-hot) "
                            f"across a call that reaches {w[0]} via "
                            f"{_chain_str(program, w[1])}",
                        )
                        break

    @staticmethod
    def _hot_held(fn: FunctionInfo, targets, held_refs) -> Optional[object]:
        for r, a in held_refs:
            spec = resolve_lock_spec(fn, targets, r, a)
            if targets.is_hot_lock_spec(spec):
                return spec
        return None


RULES = [CrossLockOrder(), LockedCalleeUnheld(), BlockingUnderHotLock()]

__all__ = [
    "RULES",
    "BlockingUnderHotLock",
    "CrossLockOrder",
    "LockedCalleeUnheld",
]
