"""Cross-function retrace-hazard taint over the static call graph.

The lexical retrace rules (rules_retrace.py) only see taint born and
branched on inside ONE function: a traced value handed to a helper —
`plan = _route_plan(state.term)` two frames down — escaped the analysis
entirely. This pass runs a program-wide fixpoint:

  * functions the targets declare traced seed their own non-static
    parameters (same seeding as the lexical rule);
  * a call argument that references a tainted name taints the matching
    callee PARAMETER (positional and keyword mapping; `self` offset for
    method calls; `targets.static_param_names` never taint; static
    escapes — `x.shape`, `len(x)` — kill taint at the argument, exactly
    as they do at a branch);
  * a call to a function whose RETURN references taint taints the
    assigned name in the caller;
  * repeat to fixpoint (monotone sets over a finite program).

Findings are the same hazards the lexical rules flag — Python branches
and concretizations — but ONLY at sites the lexical analysis provably
misses (a site the lexical rule already reports is not re-reported), and
each message carries the call-chain provenance of the taint so the fix
site is obvious.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .callgraph import FnKey, Program
from .engine import CrossRule, Finding, FunctionInfo
from .rules_retrace import _CONCRETIZERS, _traced_name_set, _traced_refs


def _param_names(fn: FunctionInfo) -> Tuple[List[str], List[str]]:
    """(positional params, keyword-only params)."""
    a = fn.node.args
    return (
        [p.arg for p in a.posonlyargs + a.args],
        [p.arg for p in a.kwonlyargs],
    )


class _Taint:
    """The program-wide taint state."""

    def __init__(self, program: Program) -> None:
        self.program = program
        self.targets = program.targets
        #: callee param names tainted by some caller
        self.params: Dict[FnKey, Set[str]] = {}
        #: provenance: (key, param) -> (caller key, call line)
        self.prov: Dict[Tuple[FnKey, str], Tuple[FnKey, int]] = {}
        #: functions whose return value references taint
        self.returns: Set[FnKey] = set()
        #: per-function resolved call map: id(call node) -> callee key
        self.call_map: Dict[FnKey, Dict[int, FnKey]] = {}
        for key in program.graph.functions:
            self.call_map[key] = {
                id(s.node): s.callee
                for s in program.graph.callees(key)
            }
        self._fixpoint()

    def local(self, key: FnKey, precise: bool = False) -> Set[str]:
        """The tainted-name set of one function under the CURRENT global
        state: declared-traced seeding plus caller-fed params, propagated
        through assignments and taint-returning calls.

        `precise=True` drops the coarse all-params seeding of declared-
        traced functions and keeps only taint that ARRIVED through a call
        edge. Return-taint is computed from this set: a traced-module
        helper like `_route_segments(P, K, R)` is called with shape-
        derived Python ints, and letting its coarse param seeding leak
        out through its return would taint every caller's plumbing."""
        fn = self.program.graph.functions[key]
        if not precise and self.targets.is_traced(key):
            traced = _traced_name_set(fn, self.targets)
        else:
            traced = set()
        traced |= self.params.get(key, set())
        cmap = self.call_map.get(key, {})

        def value_tainted(value: ast.AST) -> bool:
            if _traced_refs(value, traced):
                return True
            for sub in ast.walk(value):
                if isinstance(sub, ast.Call) and cmap.get(id(sub)) in self.returns:
                    return True
            return False

        while True:
            before = len(traced)
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Assign) and value_tainted(node.value):
                    for t in node.targets:
                        for sub in ast.walk(t):
                            if isinstance(sub, ast.Name):
                                traced.add(sub.id)
                elif isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Name
                ):
                    if value_tainted(node.value):
                        traced.add(node.target.id)
            if len(traced) == before:
                return traced

    def _fixpoint(self) -> None:
        graph = self.program.graph
        static = self.targets.static_param_names
        changed = True
        while changed:
            changed = False
            for key, fn in graph.functions.items():
                traced = self.local(key)
                if not traced:
                    continue
                # returns: from PRECISELY-propagated taint only (see
                # local() — coarse seeding must not leak through returns)
                if key not in self.returns:
                    precise = self.local(key, precise=True)
                    if precise:
                        for node in ast.walk(fn.node):
                            if (
                                isinstance(node, ast.Return)
                                and node.value is not None
                                and _traced_refs(node.value, precise)
                            ):
                                self.returns.add(key)
                                changed = True
                                break
                # argument propagation
                for site in graph.callees(key):
                    callee = graph.functions.get(site.callee)
                    if callee is None:
                        continue
                    pos, kwonly = _param_names(callee)
                    if pos and pos[0] in ("self", "cls") and site.recv_root:
                        pos = pos[1:]
                    tgt = self.params.setdefault(site.callee, set())
                    for i, arg in enumerate(site.node.args):
                        if isinstance(arg, ast.Starred) or i >= len(pos):
                            break
                        p = pos[i]
                        if p in static or p in tgt:
                            continue
                        if _traced_refs(arg, traced):
                            tgt.add(p)
                            self.prov.setdefault(
                                (site.callee, p), (key, site.lineno)
                            )
                            changed = True
                    for kw in site.node.keywords:
                        p = kw.arg
                        if p is None or p in static or p in tgt:
                            continue
                        if p not in pos and p not in kwonly:
                            continue
                        if _traced_refs(kw.value, traced):
                            tgt.add(p)
                            self.prov.setdefault(
                                (site.callee, p), (key, site.lineno)
                            )
                            changed = True

    def chain(self, key: FnKey, names: Set[str]) -> str:
        """Render the provenance of the first tainted param among `names`
        back toward a declared-traced root (bounded)."""
        graph = self.program.graph
        hops: List[str] = []
        cur, cur_names = key, names
        for _ in range(6):
            hit = None
            for p in sorted(cur_names):
                if (cur, p) in self.prov:
                    hit = (p, self.prov[(cur, p)])
                    break
            if hit is None:
                break
            p, (caller, line) = hit
            cq = graph.functions[cur].qualname
            caller_fn = graph.functions[caller]
            hops.append(
                f"`{p}` of {cq} tainted by {caller_fn.qualname} "
                f"({caller_fn.module.relpath}:{line})"
            )
            cur, cur_names = caller, self.params.get(caller, set()) | (
                _traced_name_set(caller_fn, self.targets)
                if self.targets.is_traced(caller)
                else set()
            )
        return "; ".join(hops) if hops else "via call-return taint"


class CrossFunctionTaint(CrossRule):
    id = "retrace/cross-function-taint"
    doc = (
        "Python branch / iteration / concretization on a value that is "
        "traced through a CALL CHAIN (argument or return taint) — the "
        "same retrace hazard the lexical rules flag, at the sites they "
        "provably cannot see"
    )
    motivation = (
        "ISSUE 20: `plan = helper(state.term)` then `if plan:` two frames "
        "down forks the trace exactly like a same-function branch, and "
        "the PR 5 rules missed it by construction"
    )

    def check_program(self, program: Program) -> Iterable[Finding]:
        taint = _Taint(program)
        targets = program.targets
        for key, fn in program.graph.functions.items():
            cross = taint.local(key)
            if not cross:
                continue
            # the lexical rule already covers is_traced functions for
            # their OWN seeding; only report what it cannot see
            lexical = (
                _traced_name_set(fn, targets)
                if targets.is_traced(key)
                else set()
            )

            def new_taint(expr: ast.AST) -> bool:
                return _traced_refs(expr, cross) and not _traced_refs(
                    expr, lexical
                )

            for node in ast.walk(fn.node):
                if isinstance(node, (ast.If, ast.While)):
                    if new_taint(node.test):
                        kind = "if" if isinstance(node, ast.If) else "while"
                        yield self.finding(
                            fn,
                            node,
                            f"Python `{kind}` on a cross-function-traced "
                            f"value ({taint.chain(key, cross)}) — mask "
                            f"with jnp.where / lax.cond",
                        )
                elif isinstance(node, ast.For):
                    if new_taint(node.iter):
                        yield self.finding(
                            fn,
                            node,
                            f"Python iteration over a cross-function-"
                            f"traced value ({taint.chain(key, cross)})",
                        )
                elif isinstance(node, ast.IfExp):
                    if new_taint(node.test):
                        yield self.finding(
                            fn,
                            node,
                            f"conditional expression on a cross-function-"
                            f"traced value ({taint.chain(key, cross)})",
                        )
                elif isinstance(node, ast.Call):
                    f = node.func
                    name = ""
                    if isinstance(f, ast.Name) and f.id in _CONCRETIZERS:
                        name = f.id
                    elif (
                        isinstance(f, ast.Attribute)
                        and isinstance(f.value, ast.Name)
                        and f.value.id in ("np", "numpy")
                        and f.attr in ("asarray", "array")
                    ):
                        name = f"np.{f.attr}"
                    if name and node.args and new_taint(node.args[0]):
                        yield self.finding(
                            fn,
                            node,
                            f"{name}() concretizes a cross-function-traced "
                            f"value ({taint.chain(key, cross)}) — "
                            f"trace-time constant, retrace per value",
                        )


RULES = [CrossFunctionTaint()]

__all__ = ["RULES", "CrossFunctionTaint"]
