"""Static call-graph resolution over parsed SourceModules.

The interprocedural layer under `dragonboat_tpu.analysis` (ISSUE 20):
every rule before this pass was per-function, so a lock taken by a
callee, a traced value branched on inside a helper, or a device sync two
frames below a hot function were all invisible. This module resolves a
STATIC call graph over the existing `SourceModule`/`FunctionInfo` tables
and hands it to the cross-function rule families
(`rules_xlocks`/`rules_xretrace`/`rules_xsync`) as a `Program`.

Resolution rules (deliberately narrow — a false edge makes every
downstream finding noise, a missing edge costs one review comment):

  * `self.m(...)` / `cls.m(...)`  — method on the enclosing class,
    walking the single-level base map (module-local first, then a
    globally-unique class of that name);
  * `f(...)`                      — enclosing nested-def scopes innermost
    first, then module level, then the module's `from x import f` table
    (package-relative and `dragonboat_tpu.`-absolute imports, re-exports
    chased a bounded number of hops);
  * `C.m(...)` / `mod.f(...)`     — a known class name or an imported
    module name as the receiver;
  * `v.m(...)`                    — receiver class via the declared
    variable hints (`targets.lock_var_hints`: node -> Node, sq ->
    _SendQueue, ...); otherwise, ONLY for `*_locked`-suffixed method
    names, a globally-unique method of that name resolves (the
    caller-holds convention is exactly what the cross-lock rule needs
    call sites for);
  * anything else (dynamic dispatch, getattr, lambdas, callbacks)
    degrades to NO EDGE — never a crash, never a guess.

Each resolved edge is a `CallSite` carrying the lexically-held lock set
at the call expression. Nested `def`s additionally get an explicit
DEFERRED edge with an EMPTY held set: a closure created under a `with`
runs later, lock not held (the PR 5 lexical rules simply skipped nested
defs; the deferred edge makes "closure called later, lock not held" a
first-class fact the lock rules can act on). A direct invocation of the
closure inside the enclosing function still produces a normal edge with
the locks actually held at the invocation site.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .engine import FunctionInfo, SourceModule
from .rules_device import dotted_parts

FnKey = Tuple[str, str]  # (relpath, qualname)

#: bounded re-export chase depth for `from .x import y` chains
_IMPORT_HOPS = 4


def lock_ref(expr: ast.AST) -> Optional[Tuple[str, str]]:
    """`self._mu` / `sh._wmu` / `self._sq._cv` -> (dotted root, attr);
    None when the expression is not a name/attribute chain."""
    parts = dotted_parts(expr)
    if parts is None or len(parts) < 2:
        return None
    return ".".join(parts[:-1]), parts[-1]


def resolve_lock_spec(fn: FunctionInfo, targets, root: str, attr: str):
    """(root, attr) -> LockSpec via the declared hierarchy: `self` binds
    the enclosing class, declared variable hints bind theirs, and an
    attr name carried by exactly ONE spec resolves unambiguously."""
    if root == "self":
        spec = targets.lock_rank(fn.class_name, attr, fn.module)
        if spec is not None:
            return spec
    cls = targets.lock_var_hints.get(root)
    if cls is not None:
        spec = targets.lock_rank(cls, attr, fn.module)
        if spec is not None:
            return spec
    matches = [s for s in targets.locks if s.attr == attr]
    return matches[0] if len(matches) == 1 else None


class HeldLock:
    """One lexically-held lock at a call site: the (root, attr) spelling
    plus the resolved LockSpec (None when the hierarchy doesn't declare
    it — still useful for the caller-holds root/attr match)."""

    __slots__ = ("root", "attr", "spec")

    def __init__(self, root: str, attr: str, spec) -> None:
        self.root = root
        self.attr = attr
        self.spec = spec

    def __repr__(self) -> str:  # debugging aid
        rank = self.spec.rank if self.spec else "?"
        return f"<held {self.root}.{self.attr} rank={rank}>"


class CallSite:
    """One resolved edge caller -> callee."""

    __slots__ = (
        "caller", "callee", "node", "lineno", "held", "deferred", "recv_root",
    )

    def __init__(
        self,
        caller: FnKey,
        callee: FnKey,
        node: ast.AST,
        held: Tuple[HeldLock, ...],
        deferred: bool = False,
        recv_root: str = "",
    ) -> None:
        self.caller = caller
        self.callee = callee
        self.node = node
        self.lineno = getattr(node, "lineno", 1)
        self.held = held
        self.deferred = deferred
        self.recv_root = recv_root


def walk_with_held(fn_node: ast.AST):
    """Yield ("call", node, held_refs) for every call expression and
    ("def", node, held_refs) for every directly-nested function def,
    where held_refs is the tuple of (root, attr) lock spellings of the
    lexically-enclosing `with` items. Nested defs and lambdas are NOT
    entered: their bodies run later, possibly without the lock."""
    held: List[Tuple[str, str]] = []
    out: List[Tuple[str, ast.AST, Tuple[Tuple[str, str], ...]]] = []

    def visit(node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(("def", node, tuple(held)))
            return
        if isinstance(node, ast.Lambda):
            return  # runs later; unresolvable anyway
        if isinstance(node, (ast.With, ast.AsyncWith)):
            n = 0
            for item in node.items:
                # the context expression itself evaluates BEFORE the
                # lock is held
                visit_children(item.context_expr)
                ref = lock_ref(item.context_expr)
                if ref is not None:
                    held.append(ref)
                    n += 1
            for c in node.body:
                visit(c)
            if n:
                del held[-n:]
            return
        if isinstance(node, ast.Call):
            out.append(("call", node, tuple(held)))
        visit_children(node)

    def visit_children(node):
        for c in ast.iter_child_nodes(node):
            visit(c)

    for c in fn_node.body:
        visit(c)
    return out


class _ImportTable:
    """Per-module `from ... import name [as alias]` resolution."""

    def __init__(self, mod: SourceModule) -> None:
        # alias -> ("symbol", module_relpath_stub, original_name)
        #        | ("module", module_relpath_stub, "")
        self.entries: Dict[str, Tuple[str, str, str]] = {}
        pkg_dir = mod.relpath.rsplit("/", 1)[0] if "/" in mod.relpath else ""
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ImportFrom):
                continue
            base = self._base_path(node, pkg_dir)
            if base is None:
                continue
            for alias in node.names:
                name = alias.asname or alias.name
                if node.module is None:
                    # `from . import rules_device` — the NAME is a module
                    stub = (base + "/" if base else "") + alias.name
                    self.entries[name] = ("module", stub, "")
                else:
                    self.entries[name] = ("symbol", base, alias.name)

    @staticmethod
    def _base_path(node: ast.ImportFrom, pkg_dir: str) -> Optional[str]:
        """The imported module as a "/"-separated path stub (no .py)."""
        if node.level == 0:
            modname = node.module or ""
            if not modname.startswith("dragonboat_tpu"):
                return None  # stdlib / third-party: out of scope
            parts = modname.split(".")[1:]
            return "/".join(parts)
        # package-relative: level 1 = the module's own package dir
        parts = pkg_dir.split("/") if pkg_dir else []
        up = node.level - 1
        if up > len(parts):
            return None
        parts = parts[: len(parts) - up]
        if node.module:
            parts = parts + node.module.split(".")
        return "/".join(parts)


class CallGraph:
    """The resolved static call graph over a set of parsed modules."""

    def __init__(self, modules: Sequence[SourceModule], targets) -> None:
        self.targets = targets
        self.modules: Dict[str, SourceModule] = {m.relpath: m for m in modules}
        self.functions: Dict[FnKey, FunctionInfo] = {}
        #: relpath -> {class name -> {method name -> FunctionInfo}}
        self._methods: Dict[str, Dict[str, Dict[str, FunctionInfo]]] = {}
        #: class name -> [(relpath, method table)] across the program
        self._classes: Dict[str, List[Tuple[str, Dict[str, FunctionInfo]]]] = {}
        #: bare function name -> [FnKey] (for the *_locked unique fallback)
        self._by_name: Dict[str, List[FnKey]] = {}
        self._imports: Dict[str, _ImportTable] = {}
        for m in modules:
            self._index_module(m)
        self.edges: List[CallSite] = []
        self.out_edges: Dict[FnKey, List[CallSite]] = {}
        self.in_edges: Dict[FnKey, List[CallSite]] = {}
        for m in modules:
            for fn in m.functions:
                self._collect_edges(fn)

    # -- indexing ----------------------------------------------------------
    def _index_module(self, mod: SourceModule) -> None:
        meth: Dict[str, Dict[str, FunctionInfo]] = {}
        for fn in mod.functions:
            self.functions[fn.key()] = fn
            self._by_name.setdefault(fn.name, []).append(fn.key())
            if fn.class_name and fn.qualname == f"{fn.class_name}.{fn.name}":
                meth.setdefault(fn.class_name, {})[fn.name] = fn
        self._methods[mod.relpath] = meth
        for cls, table in meth.items():
            self._classes.setdefault(cls, []).append((mod.relpath, table))
        self._imports[mod.relpath] = _ImportTable(mod)

    # -- resolution --------------------------------------------------------
    def _module_for_stub(self, stub: str) -> Optional[SourceModule]:
        for cand in (stub + ".py", stub + "/__init__.py"):
            if cand in self.modules:
                return self.modules[cand]
        return None

    def _resolve_import(self, relpath: str, name: str, hops: int = _IMPORT_HOPS):
        """Chase `from x import name` (and one-level re-exports) to a
        FunctionInfo key, or None."""
        if hops <= 0:
            return None
        table = self._imports.get(relpath)
        if table is None:
            return None
        entry = table.entries.get(name)
        if entry is None:
            return None
        kind, stub, orig = entry
        if kind == "module":
            return None  # a module alias is not callable as a function
        mod = self._module_for_stub(stub)
        if mod is None:
            return None
        fn = mod.function(orig)
        if fn is not None:
            return fn.key()
        # re-export: the target module imports it from somewhere else
        return self._resolve_import(mod.relpath, orig, hops - 1)

    def _resolve_class_method(
        self, mod: SourceModule, cls: Optional[str], attr: str
    ) -> Optional[FnKey]:
        """Walk cls and its (single-level) bases looking for a method."""
        seen: Set[str] = set()
        while cls and cls not in seen:
            seen.add(cls)
            local = self._methods.get(mod.relpath, {}).get(cls)
            if local and attr in local:
                return local[attr].key()
            hits = self._classes.get(cls, [])
            if len(hits) == 1 and attr in hits[0][1]:
                return hits[0][1][attr].key()
            bases = mod.class_bases.get(cls, [])
            if not bases and len(hits) == 1:
                bases = self.modules[hits[0][0]].class_bases.get(cls, [])
            cls = bases[0] if bases else None
        return None

    def _resolve(self, fn: FunctionInfo, call: ast.Call):
        """-> (FnKey, recv_root) or None. Never raises on weird shapes."""
        f = call.func
        mod = fn.module
        if isinstance(f, ast.Name):
            name = f.id
            # enclosing nested-def scopes, innermost first
            parts = fn.qualname.split(".")
            for i in range(len(parts), 0, -1):
                cand = ".".join(parts[:i]) + "." + name
                hit = mod.function(cand)
                if hit is not None:
                    return hit.key(), ""
            hit = mod.function(name)
            if hit is not None:
                return hit.key(), ""
            key = self._resolve_import(mod.relpath, name)
            if key is not None:
                return key, ""
            return None
        if not isinstance(f, ast.Attribute):
            return None
        attr = f.attr
        parts = dotted_parts(f.value)
        if parts is not None:
            recv_root = ".".join(parts)
            if parts[0] in ("self", "cls") and len(parts) == 1:
                key = self._resolve_class_method(mod, fn.class_name, attr)
                if key is not None:
                    return key, parts[0]
            elif len(parts) == 1:
                v = parts[0]
                # a known class name used as receiver (classmethod/static
                # or an explicit Cls.m(self, ...) call)
                if v in self._methods.get(mod.relpath, {}) or v in self._classes:
                    key = self._resolve_class_method(mod, v, attr)
                    if key is not None:
                        return key, v
                table = self._imports.get(mod.relpath)
                entry = table.entries.get(v) if table else None
                if entry and entry[0] == "module":
                    tgt = self._module_for_stub(entry[1])
                    if tgt is not None:
                        hit = tgt.function(attr)
                        if hit is not None:
                            return hit.key(), v
                hint = self.targets.lock_var_hints.get(v)
                if hint is not None:
                    key = self._resolve_class_method(mod, hint, attr)
                    if key is not None:
                        return key, v
            # *_locked unique-name fallback: the caller-holds convention
            # is worth a slightly bolder resolution — but only when the
            # whole program has exactly one method of that name
            if attr.endswith(self.targets.locked_suffix):
                hits = self._by_name.get(attr, [])
                if len(hits) == 1:
                    return hits[0], recv_root
        return None

    # -- edge collection ---------------------------------------------------
    def _collect_edges(self, fn: FunctionInfo) -> None:
        key = fn.key()
        for kind, node, held_refs in walk_with_held(fn.node):
            if kind == "def":
                callee = (fn.module.relpath, f"{fn.qualname}.{node.name}")
                if callee in self.functions:
                    self._add(CallSite(key, callee, node, (), deferred=True))
                continue
            resolved = self._resolve(fn, node)
            if resolved is None:
                continue
            callee, recv_root = resolved
            held = tuple(
                HeldLock(r, a, resolve_lock_spec(fn, self.targets, r, a))
                for r, a in held_refs
            )
            self._add(CallSite(key, callee, node, held, recv_root=recv_root))

    def _add(self, site: CallSite) -> None:
        self.edges.append(site)
        self.out_edges.setdefault(site.caller, []).append(site)
        self.in_edges.setdefault(site.callee, []).append(site)

    # -- queries -----------------------------------------------------------
    def callees(self, key: FnKey, deferred: bool = False) -> List[CallSite]:
        return [
            e for e in self.out_edges.get(key, [])
            if deferred or not e.deferred
        ]

    def callers(self, key: FnKey) -> List[CallSite]:
        return list(self.in_edges.get(key, []))

    def caller_modules_of(self, relpaths: Set[str]) -> Set[str]:
        """Modules holding a caller of any function in `relpaths` (the
        --changed expansion: a change in a callee can create findings at
        its call sites)."""
        out: Set[str] = set()
        for e in self.edges:
            if e.callee[0] in relpaths and e.caller[0] not in relpaths:
                out.add(e.caller[0])
        return out


class Program:
    """Everything a CrossRule gets to see: the parsed modules, the
    resolved call graph, and the target configuration."""

    def __init__(self, modules: Sequence[SourceModule], targets) -> None:
        self.modules: List[SourceModule] = list(modules)
        self.targets = targets
        self.by_relpath: Dict[str, SourceModule] = {
            m.relpath: m for m in self.modules
        }
        self._by_path: Dict[str, SourceModule] = {}
        for m in self.modules:
            self._by_path[m.path] = m
            self._by_path.setdefault(m.relpath, m)
        self.graph = CallGraph(self.modules, targets)

    def module_for_path(self, path: str) -> Optional[SourceModule]:
        return self._by_path.get(path)


__all__ = [
    "CallGraph",
    "CallSite",
    "FnKey",
    "HeldLock",
    "Program",
    "lock_ref",
    "resolve_lock_spec",
    "walk_with_held",
]
