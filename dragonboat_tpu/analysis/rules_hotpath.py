"""The four migrated hot-path rule families.

These started life as ad-hoc AST checks embedded in
tests/test_hot_path_lint.py (PR 1, 2, 3, 4); the test file is now a thin
conformance shim and the rules live here, on the shared engine, with
pragma-based suppression.

  columnar/*   — per-element host work in the step loop (the 340x
                 kernel-vs-e2e regression class PR 1's columnar fan-out
                 closed)
  locks/lock-in-hot-loop
               — lock acquisition inside a per-message/per-lane loop in a
                 hot function (the PR 2 transport rule, generalized to the
                 whole step loop)
  telemetry/unguarded
               — histogram/recorder appends in hot functions without a
                 sampling gate (PR 3)
  trace/unguarded-stamp
               — causal-trace stamping outside the sampled path (PR 4:
                 unsampled requests stay allocation- and event-free)
"""
from __future__ import annotations

import ast
from typing import Iterable

from .engine import (
    Finding,
    FunctionInfo,
    Rule,
    guard_test_is_sampling_gate,
)

_TELEMETRY_CALLS = ("observe", "record")


class ColumnarItemInLoop(Rule):
    id = "columnar/item-in-loop"
    doc = (
        ".tolist()/.item() inside a for/while body of a step-loop hot "
        "function (column-level .tolist() OUTSIDE loops is the fast idiom)"
    )
    motivation = (
        "PR 1: per-(group, peer) scalar reads were the 340x kernel-vs-e2e "
        "gap; one creeping .item() per message silently reopens it"
    )

    def check_function(self, fn: FunctionInfo, targets) -> Iterable[Finding]:
        if fn.key() not in targets.hot_functions:
            return
        for _loop, sub in self.loop_body_nodes(fn.node):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in ("tolist", "item")
            ):
                yield self.finding(
                    fn, sub, f".{sub.func.attr}() inside a hot loop"
                )


class ColumnarScalarIndexInLoop(Rule):
    id = "columnar/scalar-index-in-loop"
    doc = (
        "int(x[...]) scalar conversion of a subscripted value inside a "
        "for/while body of a hot function (a per-element mirror read)"
    )
    motivation = "PR 1: same regression class as columnar/item-in-loop"

    def check_function(self, fn: FunctionInfo, targets) -> Iterable[Finding]:
        if fn.key() not in targets.hot_functions:
            return
        for _loop, sub in self.loop_body_nodes(fn.node):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
                and sub.func.id == "int"
                and sub.args
                and isinstance(sub.args[0], ast.Subscript)
            ):
                yield self.finding(
                    fn, sub, "per-element int(x[...]) inside a hot loop"
                )


class LockInHotLoop(Rule):
    id = "locks/lock-in-hot-loop"
    doc = (
        "`with <lock>` inside a for/while body of a hot function — every "
        "lock on the step/send path must cover the whole batch, not one "
        "message (bulk seams: _SendQueue.put_many / Transport.send_many / "
        "try_local_deliver_many)"
    )
    motivation = (
        "PR 2: a per-message lock acquisition silently reintroduces "
        "O(messages) synchronization per step"
    )

    def check_function(self, fn: FunctionInfo, targets) -> Iterable[Finding]:
        if not targets.is_hot_lock(fn.key()):
            return
        for _loop, sub in self.loop_body_nodes(fn.node):
            if isinstance(sub, ast.With):
                yield self.finding(
                    fn, sub, "lock acquisition inside a per-message loop"
                )


class _GuardedVisitRule(Rule):
    """Shared machinery for the sampling-guard families: walk a function
    tracking whether the current node sits under an `if` whose condition
    references a sampling/latency gate."""

    def _visit(self, node: ast.AST, guarded: bool, emit) -> None:
        if isinstance(node, ast.If):
            g = guarded or guard_test_is_sampling_gate(node.test)
            for c in node.body:
                self._visit(c, g, emit)
            for c in node.orelse:
                self._visit(c, guarded, emit)
            return
        if not guarded:
            emit(node)
        for c in ast.iter_child_nodes(node):
            self._visit(c, guarded, emit)


class UnguardedTelemetry(_GuardedVisitRule):
    id = "telemetry/unguarded"
    doc = (
        "Histogram.observe()/recorder.record() in a hot function outside "
        "a sampling guard — telemetry on the step path must be 1-in-N or "
        "anomaly-only, never per-call"
    )
    motivation = (
        "PR 3: per-message unconditional telemetry is exactly the "
        "O(messages) host work the columnar refactor removed"
    )

    def check_function(self, fn: FunctionInfo, targets) -> Iterable[Finding]:
        if fn.key() not in targets.hot_telemetry_functions:
            return []
        out = []

        def emit(node):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _TELEMETRY_CALLS
            ):
                out.append(
                    self.finding(
                        fn,
                        node,
                        f"unguarded .{node.func.attr}() telemetry in a hot "
                        f"function",
                    )
                )

        self._visit(fn.node, False, emit)
        return out


class UnguardedTraceStamp(_GuardedVisitRule):
    id = "trace/unguarded-stamp"
    doc = (
        "mint_trace_id() calls, `.trace_id = ...` writes and recorder "
        "appends in a hot function outside the sampling gate (passing a "
        "zero trace id through a constructor stays free and allowed)"
    )
    motivation = (
        "PR 4: trace ids ride the sampled LatencyTrace path only; "
        "unsampled requests must stay allocation- and event-free"
    )

    def check_function(self, fn: FunctionInfo, targets) -> Iterable[Finding]:
        if fn.key() not in targets.hot_trace_functions:
            return []
        out = []

        def emit(node):
            if isinstance(node, ast.Call):
                f = node.func
                name = (
                    f.id if isinstance(f, ast.Name)
                    else f.attr if isinstance(f, ast.Attribute)
                    else ""
                )
                if name == "mint_trace_id":
                    out.append(
                        self.finding(
                            fn, node,
                            "unguarded mint_trace_id() in a hot function",
                        )
                    )
                elif name in _TELEMETRY_CALLS and isinstance(f, ast.Attribute):
                    out.append(
                        self.finding(
                            fn, node,
                            f"unguarded .{name}() telemetry in a hot function",
                        )
                    )
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                tgts = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in tgts:
                    if isinstance(t, ast.Attribute) and t.attr == "trace_id":
                        out.append(
                            self.finding(
                                fn, node,
                                "unguarded .trace_id stamp in a hot function",
                            )
                        )

        self._visit(fn.node, False, emit)
        return out


RULES = [
    ColumnarItemInLoop(),
    ColumnarScalarIndexInLoop(),
    LockInHotLoop(),
    UnguardedTelemetry(),
    UnguardedTraceStamp(),
]

__all__ = [
    "RULES",
    "ColumnarItemInLoop",
    "ColumnarScalarIndexInLoop",
    "LockInHotLoop",
    "UnguardedTelemetry",
    "UnguardedTraceStamp",
]
