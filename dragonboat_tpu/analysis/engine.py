"""The rule engine under `dragonboat_tpu.analysis`.

Pure-AST static analysis: modules are PARSED, never imported, so the
checker runs in milliseconds with no jax (or any other dependency) in the
process, and `python -m dragonboat_tpu.tools.check` can gate CI before a
single kernel compiles.

Building blocks:

  * `SourceModule`  — one parsed file: source lines, AST, the function
    table (qualnames like `VectorEngine._decode`, nested defs like
    `make_step_fn.apply`), the single-level class->bases map, and the
    suppression pragmas scanned from the raw lines.
  * `FunctionInfo`  — one function with its qualname, enclosing class and
    a back-pointer to the module; rules receive these.
  * `Rule`          — one check: `id` ("family/name"), `doc`, `motivation`
    (which real bug/PR the rule exists for), and `check_function()`
    yielding findings. The family prefix groups rules for suppression
    (`# lint: allow(family)`) and for the conformance shim.
  * `Analyzer`      — walks files -> modules -> functions -> rules,
    applies suppressions, dedupes, and reports configuration drift
    (a targeted function that no longer exists is itself a finding:
    a silently-unenforced rule is how regressions sneak back in).

Suppression pragmas:

    x = arr[g].item()  # lint: allow(columnar/item-in-loop) rare lane, <=1/step

A pragma allows a rule id, a whole family (`allow(device-sync)`), a
comma-separated list, or `*`. It applies to findings on its own line, or
— when the line holds only the pragma comment — to the line below. Every
suppression must carry a reason; a bare `allow(...)` is itself reported
(`pragma/missing-reason`). The legacy `# hot-path: ok` mark from the old
test-embedded lint keeps working for the four migrated hot-path families.
"""
from __future__ import annotations

import ast
import io
import os
import re
import textwrap
import tokenize
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# families the pre-analysis `# hot-path: ok` mark (tests/test_hot_path_lint
# .py) may suppress — kept so existing in-tree marks migrate untouched
LEGACY_MARK = "hot-path: ok"
LEGACY_MARK_FAMILIES = ("columnar", "locks", "telemetry", "trace")

_PRAGMA_RE = re.compile(r"#\s*lint:\s*allow\(([^)]*)\)\s*(.*)$")

# identifier fragments that mark a sampling/latency gate in an `if` test
# ("trace": trace-id truthiness gates — nonzero only on sampled requests)
GUARD_HINTS = ("sampl", "lat", "sstats", "trace")


def guard_test_is_sampling_gate(test_node: ast.AST) -> bool:
    """True when an `if` condition references a sampling/latency gate."""
    dump = ast.dump(test_node).lower()
    return any(h in dump for h in GUARD_HINTS)


@dataclass
class Finding:
    """One reported violation. `suppressed` findings stay in the output
    (visible in --json and `--show-suppressed`) but do not fail the run."""

    rule: str
    path: str
    line: int
    message: str
    snippet: str = ""
    suppressed: bool = False
    suppress_reason: str = ""

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "snippet": self.snippet,
            "suppressed": self.suppressed,
            "suppress_reason": self.suppress_reason,
        }

    def render(self) -> str:
        tail = f"  [suppressed: {self.suppress_reason}]" if self.suppressed else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tail}"


@dataclass
class _Pragma:
    rules: Tuple[str, ...]
    reason: str
    standalone: bool  # the line holds only the comment -> applies below
    src_line: int = 0  # the line the pragma COMMENT is on (0 = synthetic)


class FunctionInfo:
    """One function/method with enough context for a rule to act on."""

    __slots__ = ("qualname", "name", "class_name", "node", "module")

    def __init__(self, qualname, name, class_name, node, module) -> None:
        self.qualname = qualname
        self.name = name
        self.class_name = class_name  # nearest enclosing class, or None
        self.node = node
        self.module = module

    def line(self, node: ast.AST) -> str:
        try:
            return self.module.lines[node.lineno - 1]
        except IndexError:
            return ""

    def key(self) -> Tuple[str, str]:
        return (self.module.relpath, self.qualname)


class SourceModule:
    """A parsed source file plus the lookup tables rules need."""

    def __init__(self, source: str, relpath: str, path: str = "") -> None:
        self.relpath = relpath  # package-relative, "/"-separated
        self.path = path or relpath  # display path for findings
        self.lines = source.split("\n")
        self.tree = ast.parse(source)
        self.functions: List[FunctionInfo] = []
        self.class_bases: Dict[str, List[str]] = {}
        self.pragmas: Dict[int, _Pragma] = {}
        self._collect_functions()
        self._scan_pragmas()

    @classmethod
    def from_file(cls, path: str, relpath: str) -> "SourceModule":
        with open(path, "r", encoding="utf-8") as f:
            return cls(f.read(), relpath, path)

    @classmethod
    def from_snippet(cls, source: str, relpath: str = "snippet.py") -> "SourceModule":
        return cls(textwrap.dedent(source), relpath)

    # -- structure ---------------------------------------------------------
    def _collect_functions(self) -> None:
        def visit(node, prefix: str, class_name: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    self.class_bases[child.name] = [
                        b.id for b in child.bases if isinstance(b, ast.Name)
                    ]
                    visit(child, prefix + child.name + ".", child.name)
                elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qn = prefix + child.name
                    self.functions.append(
                        FunctionInfo(qn, child.name, class_name, child, self)
                    )
                    visit(child, qn + ".", class_name)
                else:
                    # defs can hide inside any statement (with/if/try):
                    # keep the prefix and keep looking
                    visit(child, prefix, class_name)

        visit(self.tree, "", None)

    def function(self, qualname: str) -> Optional[FunctionInfo]:
        for fn in self.functions:
            if fn.qualname == qualname:
                return fn
        return None

    def is_subclass_of(self, cls: Optional[str], base: str) -> bool:
        """Single-level-per-hop base walk within this module."""
        seen = set()
        while cls is not None and cls not in seen:
            if cls == base:
                return True
            seen.add(cls)
            bases = self.class_bases.get(cls, [])
            cls = bases[0] if bases else None
        return False

    # -- suppression -------------------------------------------------------
    def _comment_lines(self) -> Optional[Set[int]]:
        """Line numbers holding REAL comment tokens. Docstrings and string
        literals that merely MENTION the pragma syntax (this engine's own
        documentation, for one) must neither suppress findings nor be
        reported by pragma/unused; tokenize is the only lexically-honest
        way to tell. None = tokenization failed, treat every regex hit
        as a comment (fail open: suppressions keep working)."""
        try:
            return {
                tok.start[0]
                for tok in tokenize.generate_tokens(
                    io.StringIO("\n".join(self.lines)).readline
                )
                if tok.type == tokenize.COMMENT
            }
        except (tokenize.TokenError, IndentationError):
            return None

    def _scan_pragmas(self) -> None:
        comments = self._comment_lines()
        for i, line in enumerate(self.lines, start=1):
            if comments is not None and i not in comments:
                continue
            m = _PRAGMA_RE.search(line)
            if m is None:
                continue
            rules = tuple(
                r.strip() for r in m.group(1).split(",") if r.strip()
            )
            reason = m.group(2).strip()
            standalone = line.strip().startswith("#")
            if not standalone:
                self.pragmas[i] = _Pragma(rules, reason, False, i)
                continue
            # a standalone pragma covers the next CODE line; comment lines
            # in between continue the reason text
            j = i + 1
            while j <= len(self.lines):
                nxt = self.lines[j - 1].strip()
                if nxt.startswith("#"):
                    reason = (reason + " " + nxt.lstrip("# ")).strip()
                    j += 1
                elif not nxt:
                    j += 1
                else:
                    break
            if j <= len(self.lines):
                self.pragmas.setdefault(j, _Pragma(rules, reason, True, i))

    def suppression_for(self, rule_id: str, line: int) -> Optional[_Pragma]:
        """Pragma covering `rule_id` at `line`: on the same line, or a
        standalone pragma comment directly above (continuation comment
        lines extend the reason)."""
        family = rule_id.split("/", 1)[0]
        p = self.pragmas.get(line)
        if p is not None:
            for r in p.rules:
                if r in ("*", rule_id, family):
                    return p
        if family in LEGACY_MARK_FAMILIES and 0 < line <= len(self.lines):
            if LEGACY_MARK in self.lines[line - 1]:
                return _Pragma(("*",), "legacy hot-path: ok mark", False, 0)
        return None


class Rule:
    """Base class: one named check over one function."""

    id: str = ""
    doc: str = ""  # one line: what it catches
    motivation: str = ""  # which real bug / PR motivated it

    def check_function(self, fn: FunctionInfo, targets) -> Iterable[Finding]:
        raise NotImplementedError

    # -- shared AST helpers ------------------------------------------------
    @staticmethod
    def loop_body_nodes(fn_node: ast.AST):
        """Yield (loop, sub) for every node inside a for/while BODY (the
        iterator expression runs once and is exempt — column-level
        `.tolist()` there is the fast idiom)."""
        for node in ast.walk(fn_node):
            if isinstance(node, (ast.For, ast.While)):
                for stmt in node.body + node.orelse:
                    for sub in ast.walk(stmt):
                        yield node, sub

    def finding(self, fn: FunctionInfo, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule=self.id,
            path=fn.module.path,
            line=line,
            message=f"{fn.qualname}: {message}",
            snippet=fn.line(node).strip(),
        )


class CrossRule(Rule):
    """A rule over the whole program — the interprocedural families.

    `check_function` never fires (the Analyzer routes CrossRules through
    `check_program` instead, once per run, with the resolved call graph).
    Findings still anchor at a concrete (function, node) site so the
    same pragma machinery suppresses them."""

    def check_function(self, fn: FunctionInfo, targets) -> Iterable[Finding]:
        return []

    def check_program(self, program) -> Iterable[Finding]:
        """`program` is a callgraph.Program: parsed modules + call graph
        + targets. Yield findings anchored via `self.finding(fn, node,
        msg)` on the function the site lives in."""
        raise NotImplementedError


_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class Analyzer:
    """Runs a rule set over a file tree and applies suppressions."""

    def __init__(
        self,
        rules: Sequence[Rule],
        targets,
        root: str = "",
        unused_pragmas: bool = True,
    ) -> None:
        self.rules = list(rules)
        self.function_rules = [r for r in self.rules if not isinstance(r, CrossRule)]
        self.cross_rules = [r for r in self.rules if isinstance(r, CrossRule)]
        self.targets = targets
        self.root = root or _PKG_ROOT
        # pragma/unused only makes sense when the FULL rule set ran over
        # the FULL tree — a family- or path-restricted run would report
        # every pragma for the excluded rules as dead
        self.unused_pragmas = unused_pragmas
        #: (relpath, pragma src line) of every pragma that suppressed
        #: at least one finding in the last run
        self._used_pragmas: Set[Tuple[str, int]] = set()
        #: the callgraph.Program from the last run (check.py --changed
        #: uses its caller index)
        self.last_program = None

    # -- discovery ---------------------------------------------------------
    def _iter_files(self, paths: Optional[Sequence[str]]):
        """Yield ("file", path) plus ("missing", path) markers: an explicit
        path that matches NOTHING must fail loudly — a typo'd path in CI
        would otherwise report a permanently-clean gate that checks
        nothing (the exact silently-unenforced failure mode
        config/missing-target exists to prevent). Relative paths that do
        not exist from the cwd are retried against the analyzer root, so
        `tools.check engine/ storage/` works from anywhere."""
        if not paths:
            paths = [self.root]
        for p in paths:
            if not os.path.exists(p):
                rooted = os.path.join(self.root, p)
                if os.path.exists(rooted):
                    p = rooted
                else:
                    yield ("missing", p)
                    continue
            if os.path.isdir(p):
                matched = False
                for dirpath, dirnames, filenames in os.walk(p):
                    dirnames[:] = [
                        d for d in dirnames
                        if d != "__pycache__" and not d.startswith(".")
                    ]
                    for fname in sorted(filenames):
                        if fname.endswith(".py"):
                            matched = True
                            yield ("file", os.path.join(dirpath, fname))
                if not matched:
                    yield ("missing", p)
            elif p.endswith(".py"):
                yield ("file", p)
            else:
                yield ("missing", p)

    def _relpath(self, path: str) -> str:
        rp = os.path.relpath(os.path.abspath(path), self.root)
        return rp.replace(os.sep, "/")

    # -- run ---------------------------------------------------------------
    def run(self, paths: Optional[Sequence[str]] = None) -> List[Finding]:
        findings: List[Finding] = []
        modules: List[SourceModule] = []
        seen_functions: Set[Tuple[str, str]] = set()
        seen_modules: Set[str] = set()
        self._used_pragmas = set()
        for kind, path in self._iter_files(paths):
            if kind == "missing":
                findings.append(
                    Finding(
                        "config/no-such-path",
                        path,
                        1,
                        "path matches no Python files — a typo here would "
                        "make the gate silently check nothing",
                    )
                )
                continue
            relpath = self._relpath(path)
            try:
                mod = SourceModule.from_file(path, relpath)
            except (SyntaxError, UnicodeDecodeError) as e:
                findings.append(
                    Finding("config/unparseable", path, 1, f"cannot parse: {e}")
                )
                continue
            seen_modules.add(relpath)
            for fn in mod.functions:
                seen_functions.add(fn.key())
            modules.append(mod)
        for mod in modules:
            findings.extend(self.run_module(mod))
        if self.cross_rules:
            findings.extend(self._run_cross(modules))
        if paths is None and self.unused_pragmas:
            findings.extend(self._unused_pragma_findings(modules))
        findings.extend(
            self._config_drift(seen_modules, seen_functions)
        )
        return findings

    def run_module(self, mod: SourceModule) -> List[Finding]:
        out: List[Finding] = []
        dedup: Set[Tuple] = set()
        for fn in mod.functions:
            for rule in self.function_rules:
                for f in rule.check_function(fn, self.targets):
                    key = (f.rule, f.line, f.message)
                    if key in dedup:
                        continue
                    dedup.add(key)
                    self._apply_suppression(mod, f, out, dedup)
                    out.append(f)
        out.sort(key=lambda f: (f.path, f.line, f.rule))
        return out

    def run_snippet(
        self, source: str, relpath: str = "snippet.py"
    ) -> List[Finding]:
        return self.run_module(SourceModule.from_snippet(source, relpath))

    def run_sources(self, sources: Dict[str, str]) -> List[Finding]:
        """Run function AND cross rules over in-memory sources (relpath ->
        source text). The meta-test entry point for interprocedural
        rules; no drift/unused-pragma checks (the sources are not the
        real tree)."""
        modules = [
            SourceModule.from_snippet(src, rp)
            for rp, src in sorted(sources.items())
        ]
        self._used_pragmas = set()
        findings: List[Finding] = []
        for mod in modules:
            findings.extend(self.run_module(mod))
        if self.cross_rules:
            findings.extend(self._run_cross(modules))
        return findings

    def _run_cross(self, modules: Sequence[SourceModule]) -> List[Finding]:
        from .callgraph import Program  # deferred: engine has no deps on it

        program = Program(modules, self.targets)
        self.last_program = program
        out: List[Finding] = []
        dedup: Set[Tuple] = set()
        for rule in self.cross_rules:
            for f in rule.check_program(program):
                key = (f.rule, f.path, f.line, f.message)
                if key in dedup:
                    continue
                dedup.add(key)
                mod = program.module_for_path(f.path)
                if mod is not None:
                    self._apply_suppression(mod, f, out, dedup)
                out.append(f)
        out.sort(key=lambda f: (f.path, f.line, f.rule))
        return out

    def _unused_pragma_findings(
        self, modules: Sequence[SourceModule]
    ) -> List[Finding]:
        """A `# lint: allow(...)` that suppressed nothing this run is
        itself a finding: dead suppressions are how rules silently stop
        enforcing (the code they excused was fixed or moved, the pragma
        stayed, and the next REAL violation on that line is invisible).
        Pragmas naming a rule/family in targets.unused_pragma_allowlist
        are exempt (rules gated off by config fire zero findings by
        design)."""
        allow = getattr(self.targets, "unused_pragma_allowlist", set())
        out: List[Finding] = []
        for mod in modules:
            seen_src: Set[int] = set()
            for _line, p in sorted(mod.pragmas.items()):
                if p.src_line in seen_src or p.src_line <= 0:
                    continue
                seen_src.add(p.src_line)
                if (mod.relpath, p.src_line) in self._used_pragmas:
                    continue
                if any(r in allow for r in p.rules):
                    continue
                out.append(
                    Finding(
                        "pragma/unused",
                        mod.path,
                        p.src_line,
                        f"allow({', '.join(p.rules)}) suppresses nothing — "
                        f"delete the pragma (dead suppressions are how "
                        f"rules silently stop enforcing)",
                        snippet=mod.lines[p.src_line - 1].strip()
                        if p.src_line <= len(mod.lines)
                        else "",
                    )
                )
        out.sort(key=lambda f: (f.path, f.line, f.rule))
        return out

    def _apply_suppression(
        self, mod: SourceModule, f: Finding, out: List[Finding], dedup
    ) -> None:
        p = mod.suppression_for(f.rule, f.line)
        if p is None:
            return
        f.suppressed = True
        f.suppress_reason = p.reason or "(no reason given)"
        if p.src_line > 0:
            self._used_pragmas.add((mod.relpath, p.src_line))
        if not p.reason:
            msg = (
                "suppression carries no reason — every allow() must say why"
            )
            key = ("pragma/missing-reason", f.path, f.line, msg)
            if key not in dedup:
                dedup.add(key)
                out.append(
                    Finding("pragma/missing-reason", f.path, f.line, msg)
                )

    def _config_drift(
        self, seen_modules: Set[str], seen_functions: Set[Tuple[str, str]]
    ) -> List[Finding]:
        """A targeted function that no longer exists means a rule silently
        stopped firing — that is a finding, exactly like the legacy lint's
        'update the HOT_FUNCTIONS list' failure."""
        missing: Dict[Tuple[str, str], List[str]] = {}
        for relpath, qualname, why in self.targets.all_function_targets():
            if relpath in seen_modules and (relpath, qualname) not in seen_functions:
                missing.setdefault((relpath, qualname), []).append(why)
        out = []
        for (relpath, qualname), whys in sorted(missing.items()):
            out.append(
                Finding(
                    "config/missing-target",
                    relpath,
                    1,
                    f"{qualname}: targeted by {', '.join(whys)} but no "
                    f"longer exists — update analysis/targets.py (and "
                    f"keep its replacement covered)",
                )
            )
        return out


def unsuppressed(findings: Iterable[Finding]) -> List[Finding]:
    return [f for f in findings if not f.suppressed]


__all__ = [
    "Analyzer",
    "CrossRule",
    "Finding",
    "FunctionInfo",
    "GUARD_HINTS",
    "LEGACY_MARK",
    "Rule",
    "SourceModule",
    "guard_test_is_sampling_gate",
    "unsuppressed",
]
