"""Cross-function device-sync detection over the static call graph.

The lexical device-sync rules only look INSIDE the declared hot
functions, so moving a `jax.device_get` into a helper one frame down
made it invisible while costing exactly the same per-step sync. This
pass walks the call graph from every hot root:

  * BFS over NON-deferred edges (a closure created on the hot path but
    called later is not per-step work);
  * the walk never enters a blessed seam (`_fetch_output`/`_fetch_super`
    — that transfer is the architecture) nor another hot function (its
    own BFS and the lexical rules cover it);
  * every reachable helper is scanned for sync sites: `device_get` and
    `.block_until_ready()` anywhere, plus `.item()`/coercions/
    `np.asarray` on declared device roots — the root-based checks only
    in modules that host hot functions, because `self._state` names the
    device plane there and ordinary host state elsewhere.

Each finding carries the hot-root call chain so the reviewer sees WHY a
helper is step-path code.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from .callgraph import FnKey, Program
from .engine import CrossRule, Finding, FunctionInfo
from .rules_device import _mentions_device_root

_COERCIONS = ("int", "float", "bool")


def _sync_sites(fn: FunctionInfo, targets, root_checks: bool):
    """(kind, node) for every lexical device-sync site in `fn`."""
    roots = targets.device_roots
    for node in ast.walk(fn.node):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name):
            if f.id == "device_get":
                yield "device_get", node
            elif (
                root_checks
                and f.id in _COERCIONS
                and node.args
                and roots
                and _mentions_device_root(node.args[0], roots)
            ):
                yield f"{f.id}() on a device value", node
            continue
        if not isinstance(f, ast.Attribute):
            continue
        if f.attr == "device_get":
            yield "device_get", node
        elif f.attr == "block_until_ready":
            yield ".block_until_ready()", node
        elif root_checks and roots:
            if f.attr == "item" and _mentions_device_root(f.value, roots):
                yield ".item() on a device value", node
            elif (
                f.attr in ("asarray", "array")
                and isinstance(f.value, ast.Name)
                and f.value.id in ("np", "numpy")
                and node.args
                and _mentions_device_root(node.args[0], roots)
            ):
                yield f"np.{f.attr}() on a device value", node


class CrossFunctionDeviceSync(CrossRule):
    id = "device-sync/cross-function"
    doc = (
        "device_get/.block_until_ready()/device-root coercion in a helper "
        "REACHABLE from a hot function through a call chain that does not "
        "pass a blessed seam — the same hidden per-step sync the lexical "
        "rules catch, one or more frames down"
    )
    motivation = (
        "ISSUE 20: extracting a transfer into a helper must not launder "
        "it past the one-transfer-per-step architecture; the BENCH "
        "numbers decay identically wherever the sync lives"
    )

    def check_program(self, program: Program) -> Iterable[Finding]:
        targets = program.targets
        graph = program.graph
        hot = targets.hot_functions
        blessed = targets.blessed_device_get
        hot_modules = {rp for rp, _qn in hot}
        # BFS from each hot root; keep the SHORTEST chain per function
        reached: Dict[FnKey, Tuple[FnKey, ...]] = {}
        frontier: List[Tuple[FnKey, Tuple[FnKey, ...]]] = [
            (k, (k,)) for k in sorted(hot) if k in graph.functions
        ]
        while frontier:
            nxt: List[Tuple[FnKey, Tuple[FnKey, ...]]] = []
            for key, chain in frontier:
                for site in graph.callees(key):
                    c = site.callee
                    if c in hot or c in blessed or c in reached:
                        continue
                    reached[c] = chain + (c,)
                    nxt.append((c, chain + (c,)))
            frontier = nxt
        for key in sorted(reached):
            fn = graph.functions.get(key)
            if fn is None:
                continue
            chain = " -> ".join(qn for _rp, qn in reached[key])
            root_checks = fn.module.relpath in hot_modules
            for kind, node in _sync_sites(fn, targets, root_checks):
                yield self.finding(
                    fn,
                    node,
                    f"{kind} reachable from the hot path ({chain}) outside "
                    f"a blessed seam — a hidden per-step device sync",
                )


RULES = [CrossFunctionDeviceSync()]

__all__ = ["RULES", "CrossFunctionDeviceSync"]
