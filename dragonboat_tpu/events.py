"""Raft event aggregation + Prometheus-style health metrics.

cf. reference event.go:30-141: a raftEventListener sits between the raft
core's event callbacks and (a) per-node gauges/counters exported in
Prometheus text exposition format (WriteHealthMetrics event.go:30-32) and
(b) the user's IRaftEventListener (LeaderUpdated via a dedicated queue —
nodehost.go:1686-1701; here the user callback runs on a single dispatcher
thread so a slow listener can't stall step workers).

The registry also carries the observability plane's latency histograms
(log-bucketed, Prometheus `_bucket`/`_sum`/`_count` exposition): the
proposal lifecycle (propose-enqueue -> quorum commit -> apply/notify),
linearizable reads, and the WAL fsync barrier.
"""
from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Tuple

from .raftio import IRaftEventListener, LeaderInfo
from .trace import flight_recorder

_LabelKey = Tuple[int, int]  # (cluster_id, node_id)


# log-bucketed latency bounds in seconds: powers of two from ~15us to
# ~131s (24 buckets + overflow). Log spacing keeps p50/p99 estimation
# error bounded at a constant relative factor across six decades — the
# proposal path spans sub-ms co-hosted commits to multi-second chaos
# stalls on one scale.
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = tuple(
    2.0**e for e in range(-16, 8)
)


class Histogram:
    """Log-bucketed histogram with Prometheus semantics.

    observe() is bucket-increment + two adds under one small lock — no
    allocation, so sampled hot-path observation stays cheap. Bucket counts
    are NON-cumulative internally; exposition writes the cumulative
    `_bucket{le=...}` / `_sum` / `_count` triplet."""

    __slots__ = ("bounds", "counts", "sum", "count", "_mu")

    def __init__(
        self, bounds: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS
    ) -> None:
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)  # +1 overflow (+Inf)
        self.sum = 0.0
        self.count = 0
        self._mu = threading.Lock()

    def observe(self, v: float) -> None:
        i = bisect.bisect_left(self.bounds, v)
        with self._mu:
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    def merge(self, other: "Histogram") -> None:
        """Fold another histogram with identical bounds into this one
        (bench aggregates per-host histograms into one distribution)."""
        if other.bounds != self.bounds:
            raise ValueError("histogram bounds mismatch")
        with other._mu:
            counts = list(other.counts)
            s, c = other.sum, other.count
        with self._mu:
            for i, n in enumerate(counts):
                self.counts[i] += n
            self.sum += s
            self.count += c

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile estimate (0 <= q <= 1)."""
        with self._mu:
            total = self.count
            counts = list(self.counts)
        if total == 0:
            return 0.0
        target = q * total
        cum = 0
        lo = 0.0
        for i, c in enumerate(counts[:-1]):
            if c and cum + c >= target:
                frac = (target - cum) / c
                hi = self.bounds[i]
                return lo + (hi - lo) * frac
            cum += c
            lo = self.bounds[i]
        return self.bounds[-1]  # landed in the +Inf overflow bucket

    def snapshot(self) -> Tuple[List[int], float, int]:
        with self._mu:
            return list(self.counts), self.sum, self.count

    def since(self, prev: Optional[Tuple[List[int], float, int]]) -> "Histogram":
        """A NEW histogram holding only the observations made after
        `prev` (a snapshot() of this histogram; None means everything).
        Delta semantics for verdicts over cumulative per-host series —
        e.g. one overload storm's urgent p99 on a host that has already
        run other storms."""
        h = Histogram(self.bounds)
        counts, s, c = self.snapshot()
        if prev is None:
            h.counts = counts
            h.sum, h.count = s, c
            return h
        pc, ps, pn = prev
        h.counts = [max(a - b, 0) for a, b in zip(counts, pc)]
        h.sum = max(s - ps, 0.0)
        h.count = max(c - pn, 0)
        return h


def _labels(pairs) -> str:
    """Prometheus label block with SORTED label keys."""
    return "{" + ",".join(f'{k}="{v}"' for k, v in sorted(pairs)) + "}"


def write_histogram_series(w, full: str, label_pairs, h: "Histogram") -> None:
    """One labelled histogram series in Prometheus text format: cumulative
    `_bucket{le=...}` lines, a `+Inf` bucket equal to `_count`, then
    `_sum`/`_count`. Shared by MetricsRegistry.write and the perf
    attribution plane (profile.PhasePlane), so both expositions obey the
    same conformance contract (tests/test_observability.py parser)."""
    counts, total_sum, count = h.snapshot()
    base = tuple(label_pairs)
    cum = 0
    for bound, c in zip(h.bounds, counts):
        cum += c
        w.write(
            f"{full}_bucket{_labels(base + (('le', f'{bound:g}'),))} {cum}\n"
        )
    w.write(f"{full}_bucket{_labels(base + (('le', '+Inf'),))} {count}\n")
    w.write(f"{full}_sum{_labels(base)} {total_sum:g}\n")
    w.write(f"{full}_count{_labels(base)} {count}\n")


class MetricsRegistry:
    """Counter/gauge/histogram registry with Prometheus text exposition."""

    def __init__(self, prefix: str = "dragonboat_tpu") -> None:
        self._prefix = prefix
        self._mu = threading.Lock()
        self._counters: Dict[str, Dict[_LabelKey, float]] = {}
        self._gauges: Dict[str, Dict[_LabelKey, float]] = {}
        self._hists: Dict[str, Dict[_LabelKey, Histogram]] = {}
        # per-metric label NAMES for the 2-tuple keys; families not
        # declared here expose the historical ("clusterid", "nodeid")
        self._label_names: Dict[str, tuple] = {}

    def declare_label_names(self, name: str, names) -> None:
        """Install the label names a metric family's 2-tuple keys mean
        (e.g. the serving plane's ("tenant", "klass")). Idempotent;
        undeclared families keep ("clusterid", "nodeid")."""
        with self._mu:
            self._label_names[name] = tuple(names)

    def inc(self, name: str, key: _LabelKey, delta: float = 1.0) -> None:
        with self._mu:
            self._counters.setdefault(name, {})
            self._counters[name][key] = self._counters[name].get(key, 0.0) + delta

    def set_gauge(self, name: str, key: _LabelKey, value: float) -> None:
        with self._mu:
            self._gauges.setdefault(name, {})[key] = value

    def counter_value(self, name: str, key: _LabelKey) -> float:
        with self._mu:
            return self._counters.get(name, {}).get(key, 0.0)

    def gauge_value(self, name: str, key: _LabelKey) -> Optional[float]:
        with self._mu:
            return self._gauges.get(name, {}).get(key)

    # -- histograms --------------------------------------------------------
    def observe(self, name: str, key: _LabelKey, value: float) -> None:
        """Record one observation into the (name, key) histogram. The
        common case (histogram exists) costs one dict probe under the
        registry lock plus the bucket increment."""
        with self._mu:
            table = self._hists.get(name)
            if table is None:
                table = self._hists[name] = {}
            h = table.get(key)
            if h is None:
                h = table[key] = Histogram()
        h.observe(value)

    def histogram(self, name: str, key: _LabelKey) -> Optional[Histogram]:
        with self._mu:
            return self._hists.get(name, {}).get(key)

    def histograms(self, name: str) -> List[Histogram]:
        """Every label key's histogram for `name` (bench merges them)."""
        with self._mu:
            return list(self._hists.get(name, {}).values())

    def histogram_items(self, name: str) -> List[Tuple[_LabelKey, Histogram]]:
        """(key, histogram) pairs for `name` — key-aware merges (the
        bench serving fold splits urgent vs bulk by the klass label)."""
        with self._mu:
            return list(self._hists.get(name, {}).items())

    def write(self, w) -> None:
        """Prometheus text exposition (cf. WriteHealthMetrics event.go:30).
        One `# TYPE` line per metric family; cumulative histogram buckets
        with a `+Inf` bucket equal to `_count`; label keys sorted."""
        with self._mu:
            for kind, table in (("counter", self._counters), ("gauge", self._gauges)):
                for name in sorted(table):
                    full = f"{self._prefix}_{name}"
                    lnames = self._label_names.get(
                        name, ("clusterid", "nodeid")
                    )
                    w.write(f"# TYPE {full} {kind}\n")
                    for key, v in sorted(table[name].items()):
                        w.write(
                            f"{full}{_labels(tuple(zip(lnames, key)))} {v:g}\n"
                        )
            for name in sorted(self._hists):
                full = f"{self._prefix}_{name}"
                lnames = self._label_names.get(name, ("clusterid", "nodeid"))
                w.write(f"# TYPE {full} histogram\n")
                for key, h in sorted(self._hists[name].items()):
                    write_histogram_series(
                        w, full, tuple(zip(lnames, key)), h
                    )


class RaftEventAggregator:
    """Receives the raft core's event callbacks (via the node's adapter),
    updates metrics, and forwards LeaderUpdated to the user listener
    (cf. event.go:34-141 raftEventListener)."""

    def __init__(
        self,
        metrics: MetricsRegistry,
        user_listener: Optional[IRaftEventListener] = None,
        enable_metrics: bool = True,
    ) -> None:
        self.metrics = metrics
        self._user = user_listener
        self._enabled = enable_metrics
        # Coalescing mailbox: only the LATEST LeaderInfo per (cluster, node)
        # is kept, so a slow listener can never block a step worker or miss
        # the final "leader is now X" update — intermediate churn collapses.
        self._cv = threading.Condition()
        self._pending: Dict[_LabelKey, LeaderInfo] = {}
        # last leader recorded per (cluster, node): the flight recorder
        # logs LEADER transitions (including ->0, the gap-opening edge)
        # but not term-only churn — bring-up election storms bump terms
        # every step and would flood the ring exactly when the host is
        # CPU-bound (plain dict: torn reads only cost a dup/missed event)
        self._last_leader: Dict[_LabelKey, int] = {}
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        if user_listener is not None:
            self._thread = threading.Thread(
                target=self._dispatch_main, name="raft-event-dispatch", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            with self._cv:
                self._stop = True
                self._cv.notify()
            self._thread.join(timeout=2)
            self._thread = None

    def _dispatch_main(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._stop:
                    self._cv.wait()
                if self._stop and not self._pending:
                    return
                batch = list(self._pending.values())
                self._pending.clear()
            for info in batch:
                try:
                    self._user.leader_updated(info)
                except Exception:
                    pass  # user listener errors must not kill the dispatcher

    # -- callbacks from the raft core (all on step-worker threads) ----------
    def leader_updated(self, cluster_id, node_id, leader_id, term) -> None:
        # flight-recorder breadcrumb regardless of the metrics flag (a
        # postmortem timeline without leader changes is useless). LEADER
        # transitions only — including ->0, the availability gap's
        # opening edge — while term-only churn is suppressed (bring-up
        # election storms bump terms every step and would flood the ring
        # exactly when the host is CPU-bound)
        key = (cluster_id, node_id)
        if self._last_leader.get(key) != leader_id:
            self._last_leader[key] = leader_id
            flight_recorder().record(
                "leader_changed",
                cluster=cluster_id,
                node=node_id,
                leader=leader_id,
                term=term,
            )
        if self._enabled:
            key = (cluster_id, node_id)
            self.metrics.set_gauge("raftnode_has_leader", key, 1.0 if leader_id else 0.0)
            self.metrics.set_gauge("raftnode_leader_id", key, float(leader_id))
            self.metrics.set_gauge("raftnode_term", key, float(term))
        if self._user is not None:
            info = LeaderInfo(
                cluster_id=cluster_id, node_id=node_id,
                leader_id=leader_id, term=term,
            )
            with self._cv:
                self._pending[(cluster_id, node_id)] = info
                self._cv.notify()

    def campaign_launched(self, cluster_id, node_id, term) -> None:
        if self._enabled:
            self.metrics.inc("raftnode_campaign_launched_total", (cluster_id, node_id))

    def campaign_skipped(self, cluster_id, node_id, term) -> None:
        if self._enabled:
            self.metrics.inc("raftnode_campaign_skipped_total", (cluster_id, node_id))

    def snapshot_rejected(
        self, cluster_id, node_id, index, term, from_node
    ) -> None:
        if self._enabled:
            self.metrics.inc("raftnode_snapshot_rejected_total", (cluster_id, node_id))

    def replication_rejected(
        self, cluster_id, node_id, log_index, log_term, from_node
    ) -> None:
        if self._enabled:
            self.metrics.inc(
                "raftnode_replication_rejected_total", (cluster_id, node_id)
            )

    def proposal_dropped(self, cluster_id, node_id, entries) -> None:
        if self._enabled:
            n = len(entries) if entries else 1
            self.metrics.inc(
                "raftnode_proposal_dropped_total", (cluster_id, node_id), n
            )

    def read_index_dropped(self, cluster_id, node_id) -> None:
        if self._enabled:
            self.metrics.inc(
                "raftnode_read_index_dropped_total", (cluster_id, node_id)
            )

    # Optional event-callback vocabulary the raft core MAY grow into (cf.
    # internal/server/event.go:75-83 raftEventListener's full surface):
    # these resolve to a shared noop until a real handler exists. Anything
    # else raises AttributeError — the old unconditional noop fallback
    # masked typo'd callback names and made hasattr() probing useless
    # (every probe answered True).
    _OPTIONAL_CALLBACKS = frozenset(
        {
            "connection_established",
            "connection_failed",
            "membership_changed",
            "send_snapshot_started",
            "send_snapshot_completed",
            "send_snapshot_aborted",
            "snapshot_received",
            "snapshot_recovered",
            "snapshot_created",
            "snapshot_compacted",
            "log_compacted",
            "logdb_compacted",
        }
    )

    @staticmethod
    def _noop(*a, **k):
        return None

    def __getattr__(self, name):
        if name in RaftEventAggregator._OPTIONAL_CALLBACKS:
            return RaftEventAggregator._noop
        raise AttributeError(
            f"RaftEventAggregator has no event callback {name!r} "
            f"(declared optional callbacks: sorted list in "
            f"_OPTIONAL_CALLBACKS)"
        )


__all__ = [
    "DEFAULT_LATENCY_BUCKETS",
    "Histogram",
    "MetricsRegistry",
    "RaftEventAggregator",
    "write_histogram_series",
]
