"""Raft event aggregation + Prometheus-style health metrics.

cf. reference event.go:30-141: a raftEventListener sits between the raft
core's event callbacks and (a) per-node gauges/counters exported in
Prometheus text exposition format (WriteHealthMetrics event.go:30-32) and
(b) the user's IRaftEventListener (LeaderUpdated via a dedicated queue —
nodehost.go:1686-1701; here the user callback runs on a single dispatcher
thread so a slow listener can't stall step workers).
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from .raftio import IRaftEventListener, LeaderInfo

_LabelKey = Tuple[int, int]  # (cluster_id, node_id)


class MetricsRegistry:
    """Minimal counter/gauge registry with Prometheus text exposition."""

    def __init__(self, prefix: str = "dragonboat_tpu") -> None:
        self._prefix = prefix
        self._mu = threading.Lock()
        self._counters: Dict[str, Dict[_LabelKey, float]] = {}
        self._gauges: Dict[str, Dict[_LabelKey, float]] = {}

    def inc(self, name: str, key: _LabelKey, delta: float = 1.0) -> None:
        with self._mu:
            self._counters.setdefault(name, {})
            self._counters[name][key] = self._counters[name].get(key, 0.0) + delta

    def set_gauge(self, name: str, key: _LabelKey, value: float) -> None:
        with self._mu:
            self._gauges.setdefault(name, {})[key] = value

    def counter_value(self, name: str, key: _LabelKey) -> float:
        with self._mu:
            return self._counters.get(name, {}).get(key, 0.0)

    def gauge_value(self, name: str, key: _LabelKey) -> Optional[float]:
        with self._mu:
            return self._gauges.get(name, {}).get(key)

    def write(self, w) -> None:
        """Prometheus text exposition (cf. WriteHealthMetrics event.go:30)."""
        with self._mu:
            for kind, table in (("counter", self._counters), ("gauge", self._gauges)):
                for name in sorted(table):
                    full = f"{self._prefix}_{name}"
                    w.write(f"# TYPE {full} {kind}\n")
                    for (cid, nid), v in sorted(table[name].items()):
                        w.write(
                            f'{full}{{clusterid="{cid}",nodeid="{nid}"}} {v:g}\n'
                        )


class RaftEventAggregator:
    """Receives the raft core's event callbacks (via the node's adapter),
    updates metrics, and forwards LeaderUpdated to the user listener
    (cf. event.go:34-141 raftEventListener)."""

    def __init__(
        self,
        metrics: MetricsRegistry,
        user_listener: Optional[IRaftEventListener] = None,
        enable_metrics: bool = True,
    ) -> None:
        self.metrics = metrics
        self._user = user_listener
        self._enabled = enable_metrics
        # Coalescing mailbox: only the LATEST LeaderInfo per (cluster, node)
        # is kept, so a slow listener can never block a step worker or miss
        # the final "leader is now X" update — intermediate churn collapses.
        self._cv = threading.Condition()
        self._pending: Dict[_LabelKey, LeaderInfo] = {}
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        if user_listener is not None:
            self._thread = threading.Thread(
                target=self._dispatch_main, name="raft-event-dispatch", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            with self._cv:
                self._stop = True
                self._cv.notify()
            self._thread.join(timeout=2)
            self._thread = None

    def _dispatch_main(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._stop:
                    self._cv.wait()
                if self._stop and not self._pending:
                    return
                batch = list(self._pending.values())
                self._pending.clear()
            for info in batch:
                try:
                    self._user.leader_updated(info)
                except Exception:
                    pass  # user listener errors must not kill the dispatcher

    # -- callbacks from the raft core (all on step-worker threads) ----------
    def leader_updated(self, cluster_id, node_id, leader_id, term) -> None:
        if self._enabled:
            key = (cluster_id, node_id)
            self.metrics.set_gauge("raftnode_has_leader", key, 1.0 if leader_id else 0.0)
            self.metrics.set_gauge("raftnode_leader_id", key, float(leader_id))
            self.metrics.set_gauge("raftnode_term", key, float(term))
        if self._user is not None:
            info = LeaderInfo(
                cluster_id=cluster_id, node_id=node_id,
                leader_id=leader_id, term=term,
            )
            with self._cv:
                self._pending[(cluster_id, node_id)] = info
                self._cv.notify()

    def campaign_launched(self, cluster_id, node_id, term) -> None:
        if self._enabled:
            self.metrics.inc("raftnode_campaign_launched_total", (cluster_id, node_id))

    def campaign_skipped(self, cluster_id, node_id, term) -> None:
        if self._enabled:
            self.metrics.inc("raftnode_campaign_skipped_total", (cluster_id, node_id))

    def snapshot_rejected(
        self, cluster_id, node_id, index, term, from_node
    ) -> None:
        if self._enabled:
            self.metrics.inc("raftnode_snapshot_rejected_total", (cluster_id, node_id))

    def replication_rejected(
        self, cluster_id, node_id, log_index, log_term, from_node
    ) -> None:
        if self._enabled:
            self.metrics.inc(
                "raftnode_replication_rejected_total", (cluster_id, node_id)
            )

    def proposal_dropped(self, cluster_id, node_id, entries) -> None:
        if self._enabled:
            n = len(entries) if entries else 1
            self.metrics.inc(
                "raftnode_proposal_dropped_total", (cluster_id, node_id), n
            )

    def read_index_dropped(self, cluster_id, node_id) -> None:
        if self._enabled:
            self.metrics.inc(
                "raftnode_read_index_dropped_total", (cluster_id, node_id)
            )

    def __getattr__(self, name):
        def noop(*a, **k):
            return None

        return noop


__all__ = ["MetricsRegistry", "RaftEventAggregator"]
