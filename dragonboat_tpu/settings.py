"""Compile-time-ish tunables, the equivalent of internal/settings Hard/Soft
(cf. internal/settings/hard.go:36-99, internal/settings/soft.go:54-230).

JSON overwrite files `dragonboat-tpu-hard-settings.json` and
`dragonboat-tpu-soft-settings.json` in the working directory can override any
field, mirroring the reference's overwrite mechanism
(internal/settings/overwrite.go).
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, fields


@dataclass
class HardSettings:
    """Values that must never change once data has been written to disk."""

    step_engine_worker_count: int = 16
    logdb_pool_size: int = 16
    lru_max_session_count: int = 4096
    logdb_entry_batch_size: int = 8


@dataclass
class SoftSettings:
    """Performance tunables safe to change between runs."""

    max_entry_size: int = 64 * 1024 * 1024
    in_mem_entry_slice_size: int = 512
    min_entry_slice_free_size: int = 96
    in_mem_gc_timeout: int = 100
    max_proposal_payload_size: int = 32 * 1024 * 1024
    max_message_batch_size: int = 64 * 1024 * 1024
    incoming_proposal_queue_length: int = 2048
    incoming_read_index_queue_length: int = 4096
    received_message_queue_length: int = 1024
    snapshot_status_push_delay_ms: int = 1000
    step_engine_task_worker_count: int = 16
    step_engine_snapshot_worker_count: int = 64
    max_concurrent_streaming_snapshots: int = 128
    sent_snapshot_chunk_size: int = 2 * 1024 * 1024
    snapshot_gc_tick: int = 30
    snapshot_chunk_timeout_tick: int = 900
    batched_entry_apply: bool = True
    max_entries_to_apply_size: int = 8 * 1024 * 1024
    node_ready_chan_capacity: int = 128
    unreachable_queue_length: int = 2048
    latency_sample_ratio: int = 0
    # TPU engine: ms between host driver loop iterations when idle.
    engine_idle_sleep_ms: float = 0.2


def _load_overrides(obj, filename: str):
    if os.path.exists(filename):
        try:
            with open(filename) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError):
            return obj
        for fld in fields(obj):
            if fld.name in data:
                setattr(obj, fld.name, data[fld.name])
    return obj


hard = _load_overrides(HardSettings(), "dragonboat-tpu-hard-settings.json")
soft = _load_overrides(SoftSettings(), "dragonboat-tpu-soft-settings.json")
