"""Replicated state machine management layer (cf. internal/rsm/)."""

from .managed import (
    ConcurrentManaged,
    ManagedStateMachine,
    OnDiskManaged,
    RegularManaged,
    wrap_state_machine,
)
from .manager import (
    INodeProxy,
    ISnapshotter,
    SSMeta,
    SSRequest,
    SS_REQ_EXPORTED,
    SS_REQ_PERIODIC,
    SS_REQ_STREAM,
    SS_REQ_USER,
    StateMachineManager,
    Task,
    TaskQueue,
)
from .membership import MembershipManager
from .session import Session, SessionManager
from .snapshotio import (
    SnapshotCorrupted,
    SnapshotHeader,
    SnapshotReader,
    SnapshotWriter,
    StreamValidator,
    validate_snapshot_file,
)

__all__ = [
    "ManagedStateMachine",
    "RegularManaged",
    "ConcurrentManaged",
    "OnDiskManaged",
    "wrap_state_machine",
    "StateMachineManager",
    "Task",
    "TaskQueue",
    "SSRequest",
    "SSMeta",
    "SS_REQ_PERIODIC",
    "SS_REQ_USER",
    "SS_REQ_EXPORTED",
    "SS_REQ_STREAM",
    "INodeProxy",
    "ISnapshotter",
    "MembershipManager",
    "Session",
    "SessionManager",
    "SnapshotHeader",
    "SnapshotReader",
    "SnapshotWriter",
    "SnapshotCorrupted",
    "StreamValidator",
    "validate_snapshot_file",
]
