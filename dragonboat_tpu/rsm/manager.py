"""Replicated state machine manager.

Applies committed entries / sessions / membership changes to the managed
user SM and orchestrates snapshot save/recover — the equivalent of
internal/rsm/statemachine.go:163-1054. The execution engine's task workers
drain the TaskQueue through handle(); all session dedup (at-most-once
semantics) and membership legality enforcement happens here, inside the
replicated apply path, so every replica makes identical decisions.
"""
from __future__ import annotations

import contextlib
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Protocol, Tuple

import enum

from ..config import Config
from ..requests import BATCH_KEY_BIT
from .encoded import decode_payload


class From(enum.IntEnum):
    """Components that hold a reference to a managed SM
    (cf. internal/rsm/offload.go:18-46)."""

    STEP_WORKER = 0
    COMMIT_WORKER = 1
    SNAPSHOT_WORKER = 2
    NODEHOST = 3


class OffloadedStatus:
    """Ref-counted destroy discipline (cf. offload.go:48-133): the SM dies
    exactly once, after the NodeHost requests teardown and every worker
    has released its reference."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._loaded: set = set()
        self._teardown = False
        self._destroyed = False

    def set_loaded(self, frm: From) -> bool:
        """False once teardown began: the caller lost the race with the
        NodeHost close and must NOT touch the SM (the reference panics on
        SetLoaded-after-destroyed; a skip is the non-fatal equivalent)."""
        with self._mu:
            if self._teardown or self._destroyed:
                return False
            self._loaded.add(frm)
            return True

    def set_offloaded(self, frm: From) -> bool:
        """Returns True exactly once, when the destroy must run."""
        with self._mu:
            self._loaded.discard(frm)
            if frm == From.NODEHOST:
                self._teardown = True
            if self._teardown and not self._loaded and not self._destroyed:
                self._destroyed = True
                return True
            return False
from ..statemachine import (
    SM_TYPE_ONDISK,
    AbortSignal,
    Result,
    SMEntry,
    SnapshotStopped,
)
from ..types import (
    ConfigChange,
    Entry,
    EntryType,
    Membership,
    Snapshot,
    SERIES_ID_FOR_REGISTER,
    SERIES_ID_FOR_UNREGISTER,
)
from ..core.peer import decode_config_change
from .managed import ManagedStateMachine
from .membership import MembershipManager
from .session import SessionManager


@dataclass(slots=True)
class Task:
    """A unit of apply/snapshot work queued to the task workers
    (cf. internal/rsm/statemachine.go:106-119 Task)."""

    cluster_id: int = 0
    node_id: int = 0
    index: int = 0
    entries: List[Entry] = field(default_factory=list)
    snapshot_available: bool = False  # recover from snapshot
    init_done: bool = False
    snapshot_requested: bool = False  # take a snapshot
    stream_snapshot: bool = False
    periodic_sync: bool = False
    new_node: bool = False
    ss_request: Optional["SSRequest"] = None

    def is_snapshot_task(self) -> bool:
        return (
            self.snapshot_available
            or self.snapshot_requested
            or self.stream_snapshot
        )


SS_REQ_PERIODIC = 0
SS_REQ_USER = 1
SS_REQ_EXPORTED = 2
SS_REQ_STREAM = 3


@dataclass(slots=True)
class SSRequest:
    """Why a snapshot is being taken (cf. rsm SSRequest)."""

    type: int = SS_REQ_PERIODIC
    key: int = 0
    path: str = ""
    override_compaction: bool = False
    compaction_overhead: int = 0

    def is_exported(self) -> bool:
        return self.type == SS_REQ_EXPORTED

    def is_streaming(self) -> bool:
        return self.type == SS_REQ_STREAM


@dataclass(slots=True)
class SSMeta:
    """Point-in-time metadata captured under the SM mutex before a snapshot
    is written (cf. rsm SSMeta / getSSMeta)."""

    from_index: int = 0
    index: int = 0
    term: int = 0
    on_disk_index: int = 0
    request: Optional[SSRequest] = None
    membership: Optional[Membership] = None
    session: bytes = b""
    ctx: object = None
    compression: int = 0


class TaskQueue:
    """MPSC queue of apply tasks (cf. internal/rsm/taskqueue.go:31-96)."""

    def __init__(self) -> None:
        self._q: deque = deque()
        self._mu = threading.Lock()

    def add(self, t: Task) -> None:
        with self._mu:
            self._q.append(t)

    def get_all(self) -> List[Task]:
        with self._mu:
            out = list(self._q)
            self._q.clear()
        return out

    def get(self) -> Optional[Task]:
        with self._mu:
            return self._q.popleft() if self._q else None

    def size(self) -> int:
        with self._mu:
            return len(self._q)


class INodeProxy(Protocol):
    """Callbacks from the RSM layer into the per-group node runtime
    (cf. internal/rsm/statemachine.go INodeProxy)."""

    def node_ready(self) -> None: ...

    def apply_update(
        self,
        entry: Entry,
        result: Result,
        rejected: bool,
        ignored: bool,
        notify_read: bool,
    ) -> None: ...

    def apply_update_run(self, entries, results) -> None: ...

    def apply_config_change(self, cc: ConfigChange) -> None: ...

    def config_change_processed(self, key: int, accepted: bool) -> None: ...

    def node_id(self) -> int: ...

    def cluster_id(self) -> int: ...

    def should_stop(self) -> bool: ...


class ISnapshotter(Protocol):
    """Host-side snapshot file lifecycle used by the manager
    (cf. internal/rsm/statemachine.go ISnapshotter)."""

    def save(self, save_fn, meta: SSMeta) -> Tuple[Snapshot, object]: ...

    def load(self, ss: Snapshot, load_fn) -> None: ...

    def stream(self, stream_fn, meta: SSMeta, sink) -> None: ...

    def get_most_recent_snapshot(self) -> Optional[Snapshot]: ...

    def is_no_snapshot_error(self, e: Exception) -> bool: ...


class StateMachineManager:
    """Drives one group's managed SM (cf. rsm.StateMachine
    statemachine.go:163-188)."""

    def __init__(
        self,
        snapshotter,
        managed: ManagedStateMachine,
        node: INodeProxy,
        cfg: Config,
    ) -> None:
        self._snapshotter = snapshotter
        self._sm = managed
        self._node = node
        self._cfg = cfg
        self._mu = threading.RLock()  # guards index/term/sessions/membership
        self._index = 0
        self._term = 0
        self._on_disk_init_index = 0  # applied index discovered at open()
        self._on_disk_index = 0  # latest persisted-by-SM index
        self._sessions = SessionManager()
        self._members = MembershipManager(
            cfg.cluster_id, cfg.node_id, cfg.ordered_config_change
        )
        self._snapshotting = False
        self._aborted = AbortSignal()
        self._offload = OffloadedStatus()
        self.task_queue = TaskQueue()
        self._batched_last_applied = 0
        self._sync_req_index = 0

    # ------------------------------------------------------------ properties
    def last_applied_index(self) -> int:
        with self._mu:
            return self._index

    def get_last_applied(self) -> Tuple[int, int]:
        with self._mu:
            return self._index, self._term

    def on_disk_state_machine(self) -> bool:
        return self._sm.on_disk()

    def concurrent_snapshot(self) -> bool:
        return self._sm.concurrent_snapshot()

    def sm_type(self) -> int:
        return self._sm.sm_type()

    def on_disk_init_index(self) -> int:
        with self._mu:
            return self._on_disk_init_index

    # ------------------------------------------------------------- lifecycle
    def open(self) -> int:
        """Open an on-disk SM (cf. OpenOnDiskStateMachine
        statemachine.go:374-389)."""
        idx = self._sm.open(self._aborted)
        with self._mu:
            self._on_disk_init_index = idx
            self._on_disk_index = idx
            self._index = idx
        return idx

    def loaded(self, frm: "From") -> bool:
        """A component takes a reference to the managed SM; False when
        teardown already began (cf. offload.go:48-133 SetLoaded)."""
        return self._offload.set_loaded(frm)

    def offloaded(self, frm: "From" = None) -> None:
        """Drop a component's reference; the user SM is destroyed exactly
        once, only after the NodeHost requested teardown AND every worker
        released it — destroying under a mid-flight apply/snapshot would
        hand the user a dead SM (cf. offload.go:48-133 SetOffloaded)."""
        if frm is None or frm == From.NODEHOST:
            frm = From.NODEHOST
            self._aborted.stop()
        if self._offload.set_offloaded(frm):
            self._sm.destroy()

    # ------------------------------------------------------------ membership
    def get_membership(self) -> Membership:
        with self._mu:
            return self._members.get_membership()

    def get_membership_hash(self) -> int:
        with self._mu:
            return self._members.hash()

    def get_session_hash(self) -> int:
        with self._mu:
            return self._sessions.hash()

    # ----------------------------------------------------------------- reads
    def lookup(self, query: object) -> object:
        return self._sm.lookup(query)

    def get_hash(self) -> int:
        """SM content digest for cross-replica checks; SMs may expose
        get_hash(); fall back to hashing a snapshot image."""
        sm = self._sm._sm
        if hasattr(sm, "get_hash"):
            return sm.get_hash()
        return 0

    # ------------------------------------------------------------ champions
    def recover_from_snapshot(self, t: Task) -> int:
        """Install the most recent snapshot file (init or follower-install
        path); returns the snapshot index, 0 if none
        (cf. statemachine.go:222-358)."""
        ss = self._snapshotter.get_most_recent_snapshot()
        if ss is None:
            return 0
        if ss.witness or ss.dummy:
            with self._mu:
                self._apply_snapshot_meta(ss)
            self._notify_membership_loaded(ss)
            return ss.index
        on_disk = self._sm.on_disk()
        with self._mu:
            if ss.index <= self._index and not t.init_done:
                # already ahead (restart replay); nothing to do
                return ss.index
        init = not t.init_done
        if on_disk and init and ss.index <= self._on_disk_init_index:
            # SM's own durable state is already newer than the snapshot image
            with self._mu:
                self._apply_snapshot_meta(ss)
            self._notify_membership_loaded(ss)
            return ss.index
        self._snapshotter.load(ss, self._make_load_fn(ss))
        with self._mu:
            self._apply_snapshot_meta(ss)
            if on_disk:
                self._on_disk_index = max(self._on_disk_index, ss.on_disk_index)
        self._notify_membership_loaded(ss)
        return ss.index

    def _apply_snapshot_meta(self, ss: Snapshot) -> None:
        self._index = max(self._index, ss.index)
        self._term = max(self._term, ss.term)
        if ss.membership is not None:
            self._members.set_membership(ss.membership)

    def _notify_membership_loaded(self, ss: Snapshot) -> None:
        """Outside _mu: a restored membership image names every member's
        ADDRESS — the node runtime registers them with the host transport
        (a join-started node's bootstrap is empty; the snapshot is its
        only source of peer routing). Optional on the proxy: minimal
        INodeProxy implementations (tests/tools) skip it."""
        if ss.membership is None:
            return
        cb = getattr(self._node, "membership_loaded", None)
        if cb is not None:
            cb(ss.membership)

    def _make_load_fn(self, ss: Snapshot):
        def load(reader, session_bytes: bytes, files) -> None:
            # on-disk SMs have no replicated session image in dummy
            # snapshots; everything else restores the session LRU first
            if session_bytes:
                with self._mu:
                    self._sessions.load(session_bytes)
            self._sm.recover_from_snapshot(reader, files, self._aborted)

        return load

    def load_sessions(self, data: bytes) -> None:
        with self._mu:
            self._sessions.load(data)

    # ---------------------------------------------------------------- saving
    def save_snapshot(self, req: Optional[SSRequest] = None) -> Tuple[Snapshot, object]:
        """Synchronously produce a snapshot (cf. statemachine.go:513-525,
        697-749). For concurrent SMs prepare runs under the apply mutex and
        the streaming write runs outside it. For NON-concurrent SMs the
        index label and the data write are one critical section under the
        wrapper mutex — a save racing the apply path could otherwise label
        post-capture data with a pre-capture index, and restart replay
        would re-apply the gap (observed as a double-applied counter)."""
        req = req or SSRequest()
        if self._sm.concurrent_snapshot() or self._sm.on_disk():
            meta = self._get_ss_meta(req)
            ss, env = self._snapshotter.save(self._make_save_fn(meta), meta)
            return ss, env
        with self._sm.exclusive():
            meta = self._get_ss_meta(req)
            ss, env = self._snapshotter.save(self._make_save_fn(meta), meta)
            return ss, env

    def stream_snapshot(self, sink) -> None:
        """Stream live state to a lagging peer (on-disk SMs,
        cf. statemachine.go:680-695)."""
        meta = self._get_ss_meta(SSRequest(type=SS_REQ_STREAM))
        self._snapshotter.stream(self._make_save_fn(meta), meta, sink)

    def _get_ss_meta(self, req: SSRequest) -> SSMeta:
        with self._mu:
            if self._members.is_empty():
                raise RuntimeError("taking snapshot with empty membership")
            ctx = self._sm.prepare_snapshot() if self._sm.concurrent_snapshot() else None
            return SSMeta(
                from_index=0,
                index=self._index,
                term=self._term,
                on_disk_index=self._on_disk_index,
                request=req,
                membership=self._members.get_membership(),
                session=b"" if self._sm.on_disk() else self._sessions.save(),
                ctx=ctx,
                compression=int(self._cfg.snapshot_compression_type),
            )

    def _make_save_fn(self, meta: SSMeta):
        def save(writer, files) -> None:
            self._sm.save_snapshot(meta.ctx, writer, files, self._aborted)

        return save

    def sync(self) -> None:
        self._sm.sync()

    # --------------------------------------------------------------- applying
    def _apply_section(self):
        """Critical section for `sm.update + applied-index advance`: a
        non-concurrent SM returns the wrapper mutex (the same lock
        save_snapshot holds across its index label + data write), so a
        snapshot can never capture an index older than the data it saves.
        Concurrent/on-disk SMs take point-in-time snapshots through
        prepare_snapshot and need no cross-section — they get a no-op."""
        if self._sm.concurrent_snapshot() or self._sm.on_disk():
            return contextlib.nullcontext()
        return self._sm.exclusive()

    def handle(self, batch: List[Task], apply: List[SMEntry]) -> Optional[Task]:
        """Drain the task queue, applying entry batches; returns the first
        snapshot task encountered (the engine routes it to a snapshot
        worker), cf. statemachine.go:560-608."""
        batch.clear()
        while True:
            t = self.task_queue.get()
            if t is None:
                break
            if t.is_snapshot_task():
                # apply what we have, then hand the snapshot task back
                self._handle_batch(batch, apply)
                return t
            if not t.entries:
                if t.periodic_sync:
                    self._periodic_sync()
                continue
            batch.append(t)
        self._handle_batch(batch, apply)
        return None

    def _periodic_sync(self) -> None:
        if self._sm.on_disk():
            self._sm.sync()

    def _handle_batch(self, batch: List[Task], apply: List[SMEntry]) -> None:
        if not batch:
            return
        use_batch = self._sm.concurrent_snapshot() or self._sm.on_disk()
        apply.clear()
        # fast path for EVERY SM type: maximal runs of plain no-op-session
        # updates apply under ONE lock round-trip with ONE run-level
        # completion notify (per-entry locks + notifications were the
        # apply-side hot spot at high proposal rates). Log order is
        # preserved by flushing the other buffer whenever the entry stream
        # switches between the run and the session/config slow path.
        run: List[Entry] = []
        for t in batch:
            for e in t.entries:
                if e.index <= self._index:
                    # already applied: a snapshot recovery can leapfrog
                    # entry tasks that were queued before it (the reference
                    # tolerates the same overlap, statemachine.go onUpdate)
                    continue
                if (
                    not e.is_config_change()
                    and e.is_update()
                    and not e.is_empty()
                    and e.is_noop_session()
                ):
                    if apply:
                        self._apply_batch(apply)
                        apply.clear()
                    run.append(e)
                    continue
                self._flush_run(run)
                if use_batch:
                    self._handle_entry_batched(e, apply)
                else:
                    self._handle_entry(e, False)
        self._flush_run(run)
        if apply:
            self._apply_batch(apply)
            apply.clear()
        batch.clear()

    def _flush_run(self, run: List[Entry]) -> None:
        """Apply a contiguous run of plain updates, then notify once."""
        if not run:
            return
        ents = run[:]
        run.clear()
        skip_until = self._on_disk_init_index if self._sm.on_disk() else 0
        smes = [SMEntry(index=e.index, cmd=decode_payload(e)) for e in ents]
        to_run = [se for se in smes if se.index > skip_until]
        last = ents[-1]
        with self._apply_section():
            done = self._sm.update(to_run) if to_run else []
            with self._mu:
                self._set_applied(last.index, last.term)
                if self._sm.on_disk():
                    self._on_disk_index = max(self._on_disk_index, last.index)
        # per-proposal results are only retained for per-request keys;
        # batch-tracked proposals complete by count alone, so the common
        # bulk path skips the result realignment entirely
        if any(e.key and not (e.key & BATCH_KEY_BIT) for e in ents):
            by_index = {se.index: se.result for se in done}
            empty = Result()
            results = [by_index.get(e.index, empty) for e in ents]
        else:
            results = None
        run_notify = getattr(self._node, "apply_update_run", None)
        if run_notify is not None:
            run_notify(ents, results)
        else:  # minimal INodeProxy implementations (tests, tools)
            if results is None:
                results = [Result()] * len(ents)
            for e, r in zip(ents, results):
                self._node.apply_update(e, r, False, False, False)

    def _handle_entry_batched(self, e: Entry, apply: List[SMEntry]) -> None:
        """Batched path: plain updates accumulate; anything session- or
        config-related flushes the batch first (cf. handleBatch
        statemachine.go:895-937)."""
        if e.is_config_change() or not e.is_update() or e.is_empty():
            self._apply_batch(apply)
            apply.clear()
            self._handle_entry(e, False)
            return
        # session dedup check must happen at apply time in order
        self._apply_batch_boundary(e, apply)

    def _apply_batch_boundary(self, e: Entry, apply: List[SMEntry]) -> None:
        with self._mu:
            if e.is_session_managed():
                session = self._sessions.get_registered_client(e.client_id)
                if session is None:
                    self._flush_then_reject(e, apply)
                    return
                if session.has_responded(e.series_id):
                    self._flush_then_ignore(e, apply)
                    return
                cached, has = session.get_response(e.series_id)
                if has:
                    self._set_applied(e.index, e.term)
                    self._node.apply_update(e, cached, False, False, True)
                    return
        apply.append(SMEntry(index=e.index, cmd=decode_payload(e)))
        self._pending_session_entries = getattr(self, "_pending_session_entries", {})
        self._pending_session_entries[e.index] = e

    def _flush_then_reject(self, e: Entry, apply: List[SMEntry]) -> None:
        self._apply_batch(apply)
        apply.clear()
        self._set_applied(e.index, e.term)
        self._node.apply_update(e, Result(), True, False, True)

    def _flush_then_ignore(self, e: Entry, apply: List[SMEntry]) -> None:
        self._apply_batch(apply)
        apply.clear()
        self._set_applied(e.index, e.term)
        self._node.apply_update(e, Result(), False, True, True)

    def _apply_batch(self, apply: List[SMEntry]) -> None:
        # only reachable for concurrent/on-disk SMs (_handle_batch's
        # use_batch gate), whose snapshots are point-in-time — no
        # _apply_section needed here
        if not apply:
            return
        skip_until = self._on_disk_init_index if self._sm.on_disk() else 0
        to_run = [se for se in apply if se.index > skip_until]
        results = self._sm.update(to_run) if to_run else []
        pend = getattr(self, "_pending_session_entries", {})
        with self._mu:
            for se in apply:
                ran = se.index > skip_until
                e = pend.pop(se.index, None)
                self._set_applied(se.index, e.term if e is not None else self._term)
                if self._sm.on_disk():
                    self._on_disk_index = max(self._on_disk_index, se.index)
                if e is None:
                    continue
                if e.is_session_managed() and ran:
                    session = self._sessions.get_registered_client(e.client_id)
                    if session is not None:
                        session.clear_to(e.responded_to)
                        if not session.has_responded(e.series_id):
                            session.add_response(e.series_id, se.result)
                self._node.apply_update(e, se.result, False, False, True)

    def _handle_entry(self, e: Entry, notify_read: bool) -> None:
        """Serial apply of one entry (cf. handleEntry
        statemachine.go:790-886, handleUpdate :989-1032)."""
        if e.is_config_change():
            accepted = self._handle_config_change(e)
            self._node.config_change_processed(e.key, accepted)
            return
        if not e.is_session_managed():
            if e.is_empty():
                # new-leader noop entry: only moves applied index
                with self._mu:
                    self._set_applied(e.index, e.term)
                self._node.apply_update(e, Result(), False, True, notify_read)
                return
            # noop-session proposal: apply without dedup
            self._do_update(e, notify_read)
            return
        if e.is_new_session_request():
            with self._mu:
                result = self._sessions.register_client_id(e.client_id)
                self._set_applied(e.index, e.term)
            self._node.apply_update(
                e, result, result.value == 0, False, notify_read
            )
            return
        if e.is_end_of_session_request():
            with self._mu:
                result = self._sessions.unregister_client_id(e.client_id)
                self._set_applied(e.index, e.term)
            self._node.apply_update(
                e, result, result.value == 0, False, notify_read
            )
            return
        # session-managed update with dedup
        with self._mu:
            session = self._sessions.get_registered_client(e.client_id)
            if session is None:
                self._set_applied(e.index, e.term)
                self._node.apply_update(e, Result(), True, False, notify_read)
                return
            session.clear_to(e.responded_to)
            if session.has_responded(e.series_id):
                self._set_applied(e.index, e.term)
                self._node.apply_update(e, Result(), False, True, notify_read)
                return
            cached, has = session.get_response(e.series_id)
            if has:
                self._set_applied(e.index, e.term)
                self._node.apply_update(e, cached, False, False, notify_read)
                return
        self._do_update(e, notify_read, session=e.client_id)

    def _do_update(self, e: Entry, notify_read: bool, session: int = 0) -> None:
        skip = self._sm.on_disk() and e.index <= self._on_disk_init_index
        with self._apply_section():
            if skip:
                results = [SMEntry(index=e.index, cmd=decode_payload(e))]
            else:
                results = self._sm.update(
                    [SMEntry(index=e.index, cmd=decode_payload(e))]
                )
            result = results[0].result if results else Result()
            with self._mu:
                if session:
                    s = self._sessions.get_registered_client(session)
                    if s is not None and not s.has_responded(e.series_id):
                        got, has = s.get_response(e.series_id)
                        if not has:
                            s.add_response(e.series_id, result)
                self._set_applied(e.index, e.term)
                if self._sm.on_disk():
                    self._on_disk_index = max(self._on_disk_index, e.index)
        self._node.apply_update(e, result, False, False, notify_read)

    def _handle_config_change(self, e: Entry) -> bool:
        cc = decode_config_change(e.cmd)
        with self._mu:
            accepted = self._members.handle_config_change(cc, e.index)
            self._set_applied(e.index, e.term)
        if accepted:
            self._node.apply_config_change(cc)
        return accepted

    def _set_applied(self, index: int, term: int) -> None:
        if index < self._index:
            raise RuntimeError(
                f"applied index moving backwards: {self._index} -> {index}"
            )
        self._index = index
        self._term = term


__all__ = [
    "Task",
    "TaskQueue",
    "SSRequest",
    "SSMeta",
    "SS_REQ_PERIODIC",
    "SS_REQ_USER",
    "SS_REQ_EXPORTED",
    "SS_REQ_STREAM",
    "INodeProxy",
    "ISnapshotter",
    "StateMachineManager",
]
