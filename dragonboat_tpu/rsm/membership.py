"""Membership image applied inside the replicated state machine.

Validated membership (addresses/observers/witnesses/removed + config-change
id ordering) is itself replicated state: every replica applies config-change
entries through the same legality checks so the image stays identical
(cf. internal/rsm/membership.go:55-298).
"""
from __future__ import annotations

import struct
import zlib
from typing import Optional

from ..config import Config
from ..types import ConfigChange, ConfigChangeType, Membership


class MembershipManager:
    def __init__(
        self, cluster_id: int, node_id: int, ordered: bool = False
    ) -> None:
        self.cluster_id = cluster_id
        self.node_id = node_id
        self.ordered = ordered
        self.members = Membership()

    # -- snapshot interface ---------------------------------------------------
    def get_membership(self) -> Membership:
        return self.members.copy()

    def set_membership(self, m: Membership) -> None:
        self.members = m.copy()

    def hash(self) -> int:
        """Deterministic digest (cf. membership.go GetHash)."""
        m = self.members
        parts = [struct.pack("<Q", m.config_change_id)]
        for nid in sorted(m.addresses):
            parts.append(struct.pack("<Q", nid) + m.addresses[nid].encode())
        for nid in sorted(m.observers):
            parts.append(b"o" + struct.pack("<Q", nid))
        for nid in sorted(m.witnesses):
            parts.append(b"w" + struct.pack("<Q", nid))
        for nid in sorted(m.removed):
            parts.append(b"r" + struct.pack("<Q", nid))
    # crc of the canonical serialization; identical across replicas by
    # construction
        return zlib.crc32(b"".join(parts))

    def is_empty(self) -> bool:
        return len(self.members.addresses) == 0

    # -- legality (cf. membership.go:133-262) ---------------------------------
    def is_conf_change_up_to_date(self, cc: ConfigChange) -> bool:
        if not self.ordered or cc.initialize:
            return True
        return self.members.config_change_id == cc.config_change_id

    def is_add_removed_node(self, cc: ConfigChange) -> bool:
        return (
            cc.type
            in (
                ConfigChangeType.ADD_NODE,
                ConfigChangeType.ADD_OBSERVER,
                ConfigChangeType.ADD_WITNESS,
            )
            and cc.node_id in self.members.removed
        )

    def is_promote_observer(self, cc: ConfigChange) -> bool:
        return (
            cc.type == ConfigChangeType.ADD_NODE
            and cc.node_id in self.members.observers
            and self.members.observers[cc.node_id] == cc.address
        )

    def is_invalid_observer_promotion(self, cc: ConfigChange) -> bool:
        return (
            cc.type == ConfigChangeType.ADD_NODE
            and cc.node_id in self.members.observers
            and self.members.observers[cc.node_id] != cc.address
        )

    def is_add_existing_member(self, cc: ConfigChange) -> bool:
        if self.is_promote_observer(cc):
            return False
        if cc.type == ConfigChangeType.ADD_NODE:
            if cc.node_id in self.members.addresses:
                return True
        elif cc.type == ConfigChangeType.ADD_OBSERVER:
            if cc.node_id in self.members.observers:
                return True
        elif cc.type == ConfigChangeType.ADD_WITNESS:
            if cc.node_id in self.members.witnesses:
                return True
        else:
            return False
        # address reuse by a different node id is also illegal
        return self._address_in_use(cc.address, cc.node_id)

    def is_add_node_as_observer(self, cc: ConfigChange) -> bool:
        return (
            cc.type == ConfigChangeType.ADD_OBSERVER
            and cc.node_id in self.members.addresses
        )

    def is_add_node_as_witness(self, cc: ConfigChange) -> bool:
        return cc.type == ConfigChangeType.ADD_WITNESS and (
            cc.node_id in self.members.addresses
            or cc.node_id in self.members.observers
        )

    def is_deleting_only_node(self, cc: ConfigChange) -> bool:
        return (
            cc.type == ConfigChangeType.REMOVE_NODE
            and len(self.members.addresses) == 1
            and cc.node_id in self.members.addresses
        )

    def _address_in_use(self, address: str, node_id: int) -> bool:
        for nid, addr in self.members.addresses.items():
            if nid != node_id and addr == address:
                return True
        for nid, addr in self.members.observers.items():
            if nid != node_id and addr == address:
                return True
        for nid, addr in self.members.witnesses.items():
            if nid != node_id and addr == address:
                return True
        return False

    def handle_config_change(self, cc: ConfigChange, index: int) -> bool:
        """Validate + apply; returns whether the change was accepted
        (cf. membership.go:299+ handleConfigChange)."""
        accepted = (
            self.is_conf_change_up_to_date(cc)
            and not self.is_add_removed_node(cc)
            and not self.is_add_existing_member(cc)
            and not self.is_invalid_observer_promotion(cc)
            and not self.is_add_node_as_observer(cc)
            and not self.is_add_node_as_witness(cc)
            and not self.is_deleting_only_node(cc)
        )
        if accepted:
            self._apply(cc, index)
        return accepted

    def _apply(self, cc: ConfigChange, index: int) -> None:
        # cf. membership.go:264-298 applyConfigChange; the entry index becomes
        # the new config change id
        m = self.members
        m.config_change_id = index
        if cc.type == ConfigChangeType.ADD_NODE:
            m.observers.pop(cc.node_id, None)
            if cc.node_id in m.witnesses:
                raise RuntimeError("promoting a witness is not allowed")
            m.addresses[cc.node_id] = cc.address
        elif cc.type == ConfigChangeType.ADD_OBSERVER:
            if cc.node_id in m.addresses:
                raise RuntimeError("adding an existing member as observer")
            m.observers[cc.node_id] = cc.address
        elif cc.type == ConfigChangeType.ADD_WITNESS:
            if cc.node_id in m.addresses or cc.node_id in m.observers:
                raise RuntimeError("adding an existing member as witness")
            m.witnesses[cc.node_id] = cc.address
        elif cc.type == ConfigChangeType.REMOVE_NODE:
            m.addresses.pop(cc.node_id, None)
            m.observers.pop(cc.node_id, None)
            m.witnesses.pop(cc.node_id, None)
            m.removed[cc.node_id] = True
        else:
            raise RuntimeError(f"unknown config change type {cc.type}")


__all__ = ["MembershipManager"]
