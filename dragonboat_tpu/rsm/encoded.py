"""Entry payload encoding: optional compression of proposal payloads.

Mirrors the reference's v0 header scheme (internal/rsm/encoded.go:47-176):
an ENCODED entry's payload starts with one header byte
`(version << 4) | compression_type`; plain APPLICATION entries carry raw
bytes and are never touched. Compression happens once at propose time on
the proposing replica and decompression once at apply time on every
replica — the wire, the logdb, and the device-metadata path all carry the
compressed bytes.

The reference uses snappy; this build uses zlib (stdlib — no external
deps are installable here) behind the same CompressionType seam. The
header byte makes the format self-describing, so adding real snappy later
is a new type value, not a migration.
"""
from __future__ import annotations

import zlib

from ..types import CompressionType, Entry, EntryType

_V0 = 0


def encode_payload(ct: CompressionType, data: bytes) -> bytes:
    """Header byte + compressed body (cf. encoded.go newEncodedPayload)."""
    if ct == CompressionType.NO_COMPRESSION:
        return data
    if ct == CompressionType.SNAPPY:
        # zlib body behind the SNAPPY seam (see module docstring)
        return bytes([(_V0 << 4) | int(ct)]) + zlib.compress(data, 1)
    raise ValueError(f"unknown compression type {ct}")


def decode_payload(e: Entry) -> bytes:
    """Payload bytes for the state machine (cf. encoded.go GetPayload)."""
    if e.type != EntryType.ENCODED:
        return e.cmd
    if not e.cmd:
        raise ValueError("empty encoded payload")
    hdr = e.cmd[0]
    version = hdr >> 4
    ct = hdr & 0x0F
    if version != _V0:
        raise ValueError(f"unknown encoded payload version {version}")
    if ct == int(CompressionType.NO_COMPRESSION):
        return e.cmd[1:]
    if ct == int(CompressionType.SNAPPY):
        return zlib.decompress(e.cmd[1:])
    raise ValueError(f"unknown compression type {ct}")


def maybe_encode_entry(ct: CompressionType, e: Entry) -> Entry:
    """Compress a freshly proposed APPLICATION entry in place when the
    group's config asks for it and it pays (tiny payloads skip)."""
    if (
        ct == CompressionType.NO_COMPRESSION
        or e.type != EntryType.APPLICATION
        or len(e.cmd) < 64
    ):
        return e
    encoded = encode_payload(ct, e.cmd)
    if len(encoded) >= len(e.cmd):
        return e  # incompressible: keep plain
    e.type = EntryType.ENCODED
    e.cmd = encoded
    return e


__all__ = ["encode_payload", "decode_payload", "maybe_encode_entry"]
