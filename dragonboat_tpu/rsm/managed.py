"""Managed state machine adapters.

Uniform IManagedStateMachine interface over the three user SM types
(cf. internal/rsm/native.go:33-290 and internal/rsm/sm.go:26-382). The
manager layer (rsm.manager.StateMachineManager) talks only to this
interface; whether the user implemented a regular, concurrent, or on-disk
SM is hidden behind it, including the locking discipline:

  - regular: update and lookup serialized by one mutex
  - concurrent: updates serialized; lookups + snapshot saves concurrent
  - on-disk: like concurrent, plus open()/sync() and streamed snapshots
"""
from __future__ import annotations

import threading
from typing import BinaryIO, List, Optional, Tuple

from ..statemachine import (
    SM_TYPE_CONCURRENT,
    SM_TYPE_ONDISK,
    SM_TYPE_REGULAR,
    AbortSignal,
    IConcurrentStateMachine,
    IOnDiskStateMachine,
    IStateMachine,
    ISnapshotFileCollection,
    Result,
    SMEntry,
    SnapshotFile,
)


class ManagedStateMachine:
    """Adapter base (cf. IManagedStateMachine internal/rsm/native.go:56)."""

    def __init__(self, sm, cluster_id: int, node_id: int) -> None:
        self._sm = sm
        self.cluster_id = cluster_id
        self.node_id = node_id
        self._mu = threading.RLock()
        self._destroyed = False

    # ---- type predicates
    def concurrent_snapshot(self) -> bool:
        return False

    def exclusive(self):
        """The wrapper's serialization lock (reentrant). Non-concurrent
        SMs hand it to the manager so `update + applied-index advance`
        and `snapshot (index label + data write)` each form ONE critical
        section — without it a save racing an apply can label data from
        index i+k with index i, and restart replay double-applies
        (i, i+k]."""
        return self._mu

    def on_disk(self) -> bool:
        return False

    def sm_type(self) -> int:
        raise NotImplementedError

    # ---- lifecycle
    def open(self, stopc: AbortSignal) -> int:
        raise RuntimeError("open called on non-disk SM")

    def sync(self) -> None:
        return None

    def destroy(self) -> None:
        with self._mu:
            if not self._destroyed:
                self._destroyed = True
                self._sm.close()

    # ---- apply / read
    def update(self, entries: List[SMEntry]) -> List[SMEntry]:
        raise NotImplementedError

    def lookup(self, query: object) -> object:
        raise NotImplementedError

    # ---- snapshot
    def prepare_snapshot(self) -> object:
        return None

    def save_snapshot(
        self,
        ctx: object,
        w: BinaryIO,
        files: Optional[ISnapshotFileCollection],
        done: AbortSignal,
    ) -> None:
        raise NotImplementedError

    def recover_from_snapshot(
        self, r: BinaryIO, files: List[SnapshotFile], done: AbortSignal
    ) -> None:
        raise NotImplementedError


class RegularManaged(ManagedStateMachine):
    """cf. internal/rsm/sm.go RegularStateMachine (:45)."""

    def sm_type(self) -> int:
        return SM_TYPE_REGULAR

    def update(self, entries: List[SMEntry]) -> List[SMEntry]:
        with self._mu:
            for e in entries:
                e.result = self._sm.update(e.cmd)
        return entries

    def lookup(self, query: object) -> object:
        with self._mu:
            if self._destroyed:
                raise RuntimeError("lookup on destroyed state machine")
            return self._sm.lookup(query)

    def save_snapshot(self, ctx, w, files, done) -> None:
        with self._mu:
            self._sm.save_snapshot(w, files, done)

    def recover_from_snapshot(self, r, files, done) -> None:
        with self._mu:
            self._sm.recover_from_snapshot(r, files, done)


class ConcurrentManaged(ManagedStateMachine):
    """cf. internal/rsm/sm.go ConcurrentStateMachine (:151). Snapshot save
    runs WITHOUT the update mutex — prepare captures the point-in-time view
    under the mutex, save streams it concurrently."""

    def concurrent_snapshot(self) -> bool:
        return True

    def sm_type(self) -> int:
        return SM_TYPE_CONCURRENT

    def update(self, entries: List[SMEntry]) -> List[SMEntry]:
        with self._mu:
            return self._sm.update(entries)

    def lookup(self, query: object) -> object:
        if self._destroyed:
            raise RuntimeError("lookup on destroyed state machine")
        return self._sm.lookup(query)

    def prepare_snapshot(self) -> object:
        with self._mu:
            return self._sm.prepare_snapshot()

    def save_snapshot(self, ctx, w, files, done) -> None:
        self._sm.save_snapshot(ctx, w, files, done)

    def recover_from_snapshot(self, r, files, done) -> None:
        with self._mu:
            self._sm.recover_from_snapshot(r, files, done)


class OnDiskManaged(ManagedStateMachine):
    """cf. internal/rsm/sm.go OnDiskStateMachine. The SM owns its own
    durable state; snapshots stream live state to peers and recovery is
    open() + optional stream apply."""

    def concurrent_snapshot(self) -> bool:
        return True

    def on_disk(self) -> bool:
        return True

    def sm_type(self) -> int:
        return SM_TYPE_ONDISK

    def open(self, stopc: AbortSignal) -> int:
        with self._mu:
            return self._sm.open(stopc)

    def sync(self) -> None:
        self._sm.sync()

    def update(self, entries: List[SMEntry]) -> List[SMEntry]:
        with self._mu:
            return self._sm.update(entries)

    def lookup(self, query: object) -> object:
        if self._destroyed:
            raise RuntimeError("lookup on destroyed state machine")
        return self._sm.lookup(query)

    def prepare_snapshot(self) -> object:
        with self._mu:
            return self._sm.prepare_snapshot()

    def save_snapshot(self, ctx, w, files, done) -> None:
        self._sm.save_snapshot(ctx, w, done)

    def recover_from_snapshot(self, r, files, done) -> None:
        with self._mu:
            self._sm.recover_from_snapshot(r, done)


def wrap_state_machine(sm, cluster_id: int, node_id: int) -> ManagedStateMachine:
    if isinstance(sm, IOnDiskStateMachine):
        return OnDiskManaged(sm, cluster_id, node_id)
    if isinstance(sm, IConcurrentStateMachine):
        return ConcurrentManaged(sm, cluster_id, node_id)
    if isinstance(sm, IStateMachine):
        return RegularManaged(sm, cluster_id, node_id)
    raise TypeError(f"unsupported state machine type: {type(sm)!r}")


__all__ = [
    "ManagedStateMachine",
    "RegularManaged",
    "ConcurrentManaged",
    "OnDiskManaged",
    "wrap_state_machine",
]
