"""Versioned, checksummed snapshot file format.

Equivalent of internal/rsm/snapshotio.go + rw.go: a snapshot is a header
(index/term/membership/sessions metadata) followed by the session image and
the SM payload written as CRC32-framed blocks, so a truncated or corrupted
file is always detected before recovery (cf. snapshotio.go:156-368,
rw.go:113-530 — the v2 block-checksum design; v1's whole-file hash is not
carried over).

Layout (little-endian):
    magic      8B  b"DBTPUSS1"
    version    u32 (=1)
    header_len u32
    header     header_len bytes (codec: index/term/on_disk_index/smtype/
               witness/dummy flags + membership)
    header_crc u32
    session    u64 len + bytes + u32 crc
    payload    blocks of [u32 len][bytes][u32 crc], terminated by len=0,
               then u64 total_payload_len + u32 crc32-of-crcs
The same byte stream is used on disk and on the wire (chunked streaming).
"""
from __future__ import annotations

import io
import os
import struct
import zlib
from dataclasses import dataclass, field
from typing import BinaryIO, List, Optional, Tuple

from .. import codec
from ..types import Membership

MAGIC = b"DBTPUSS1"
VERSION = 1
BLOCK_SIZE = 1024 * 1024
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


class SnapshotCorrupted(Exception):
    pass


@dataclass
class SnapshotHeader:
    index: int = 0
    term: int = 0
    on_disk_index: int = 0
    smtype: int = 0
    witness: bool = False
    dummy: bool = False
    compression: int = 0
    membership: Optional[Membership] = None

    def encode(self) -> bytes:
        parts = [
            struct.pack(
                "<QQQIBBB",
                self.index,
                self.term,
                self.on_disk_index,
                self.smtype,
                1 if self.witness else 0,
                1 if self.dummy else 0,
                self.compression,
            )
        ]
        if self.membership is not None:
            parts.append(b"\x01" + codec.encode_membership(self.membership))
        else:
            parts.append(b"\x00")
        return b"".join(parts)

    @staticmethod
    def decode(buf: bytes) -> "SnapshotHeader":
        index, term, odi, smtype, wit, dummy, comp = struct.unpack_from(
            "<QQQIBBB", buf, 0
        )
        off = 31
        h = SnapshotHeader(
            index=index,
            term=term,
            on_disk_index=odi,
            smtype=smtype,
            witness=bool(wit),
            dummy=bool(dummy),
            compression=comp,
        )
        if buf[off] == 1:
            h.membership, _ = codec.decode_membership(buf, off + 1)
        return h


class SnapshotWriter:
    """Streams the snapshot format to any file-like sink; payload written
    through write() is block-framed transparently."""

    def __init__(self, f: BinaryIO, header: SnapshotHeader, session: bytes) -> None:
        self._f = f
        self._buf = bytearray()
        self._payload_len = 0
        self._crc_of_crcs = zlib.crc32(b"")
        hdr = header.encode()
        f.write(MAGIC)
        f.write(_U32.pack(VERSION))
        f.write(_U32.pack(len(hdr)))
        f.write(hdr)
        f.write(_U32.pack(zlib.crc32(hdr)))
        f.write(_U64.pack(len(session)))
        f.write(session)
        f.write(_U32.pack(zlib.crc32(session)))

    def write(self, data: bytes) -> int:
        self._buf.extend(data)
        while len(self._buf) >= BLOCK_SIZE:
            self._flush_block(self._buf[:BLOCK_SIZE])
            del self._buf[:BLOCK_SIZE]
        return len(data)

    def _flush_block(self, block) -> None:
        block = bytes(block)
        crc = zlib.crc32(block)
        self._f.write(_U32.pack(len(block)))
        self._f.write(block)
        self._f.write(_U32.pack(crc))
        self._payload_len += len(block)
        self._crc_of_crcs = zlib.crc32(_U32.pack(crc), self._crc_of_crcs)

    def close(self) -> None:
        if self._buf:
            self._flush_block(self._buf)
            self._buf.clear()
        self._f.write(_U32.pack(0))  # terminator
        self._f.write(_U64.pack(self._payload_len))
        self._f.write(_U32.pack(self._crc_of_crcs & 0xFFFFFFFF))

    # context manager sugar
    def __enter__(self):
        return self

    def __exit__(self, et, ev, tb):
        if et is None:
            self.close()


class SnapshotReader:
    """Validating reader over the snapshot format."""

    def __init__(self, f: BinaryIO) -> None:
        self._f = f
        magic = f.read(8)
        if magic != MAGIC:
            raise SnapshotCorrupted(f"bad magic {magic!r}")
        (ver,) = _U32.unpack(f.read(4))
        if ver != VERSION:
            raise SnapshotCorrupted(f"unsupported version {ver}")
        (hlen,) = _U32.unpack(f.read(4))
        hdr = f.read(hlen)
        (hcrc,) = _U32.unpack(f.read(4))
        if zlib.crc32(hdr) != hcrc:
            raise SnapshotCorrupted("header crc mismatch")
        self.header = SnapshotHeader.decode(hdr)
        (slen,) = _U64.unpack(f.read(8))
        self.session = f.read(slen)
        (scrc,) = _U32.unpack(f.read(4))
        if zlib.crc32(self.session) != scrc:
            raise SnapshotCorrupted("session crc mismatch")
        self._payload_done = False
        self._pending = b""

    def read(self, n: int = -1) -> bytes:
        """Read validated payload bytes."""
        out = bytearray()
        while n < 0 or len(out) < n:
            if self._pending:
                take = len(self._pending) if n < 0 else n - len(out)
                out.extend(self._pending[:take])
                self._pending = self._pending[take:]
                continue
            if self._payload_done:
                break
            (blen,) = _U32.unpack(self._f.read(4))
            if blen == 0:
                self._payload_done = True
                break
            block = self._f.read(blen)
            (crc,) = _U32.unpack(self._f.read(4))
            if len(block) != blen or zlib.crc32(block) != crc:
                raise SnapshotCorrupted("payload block crc mismatch")
            self._pending = block
        return bytes(out)


def validate_snapshot_file(path: str) -> bool:
    """Full-scan validation (cf. SnapshotValidator snapshotio.go:386-435)."""
    try:
        with open(path, "rb") as f:
            r = SnapshotReader(f)
            while True:
                chunk = r.read(BLOCK_SIZE)
                if not chunk:
                    break
        return True
    except (SnapshotCorrupted, struct.error, OSError):
        return False


class StreamValidator:
    """Incremental validator for chunked snapshot reassembly: feed raw bytes
    in arrival order; valid() only after the full stream checks out."""

    def __init__(self) -> None:
        self._buf = io.BytesIO()

    def feed(self, data: bytes) -> None:
        self._buf.write(data)

    def valid(self) -> bool:
        self._buf.seek(0)
        try:
            r = SnapshotReader(self._buf)
            while r.read(BLOCK_SIZE):
                pass
            return True
        except (SnapshotCorrupted, struct.error):
            return False
        finally:
            self._buf.seek(0, io.SEEK_END)


__all__ = [
    "SnapshotHeader",
    "SnapshotWriter",
    "SnapshotReader",
    "SnapshotCorrupted",
    "StreamValidator",
    "validate_snapshot_file",
    "BLOCK_SIZE",
]
