"""Client sessions applied inside the state machine.

At-most-once semantics from the Raft thesis §6.3: each registered client
session caches the Result of every applied (series_id) until the client
acknowledges it via responded_to; a retried proposal returns the cached
Result instead of re-applying (cf. internal/rsm/session.go:48-165,
sessionmanager.go:25-133, lrusession.go:53-204).

The session image is part of replicated state: it is saved into snapshots
and must hash identically across replicas.
"""
from __future__ import annotations

import struct
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..settings import hard
from ..statemachine import Result


class Session:
    """Per-client cache of applied-but-unacknowledged results
    (cf. internal/rsm/session.go:48-165)."""

    __slots__ = ("client_id", "responded_up_to", "history")

    def __init__(self, client_id: int) -> None:
        self.client_id = client_id
        self.responded_up_to = 0
        self.history: Dict[int, Result] = {}

    def add_response(self, series_id: int, result: Result) -> None:
        if series_id in self.history:
            raise RuntimeError("adding a duplicated response")
        self.history[series_id] = result

    def get_response(self, series_id: int) -> Tuple[Optional[Result], bool]:
        if series_id in self.history:
            return self.history[series_id], True
        return None, False

    def has_responded(self, series_id: int) -> bool:
        return series_id <= self.responded_up_to

    def clear_to(self, series_id: int) -> None:
        """Client acknowledged everything <= series_id; evict cached results
        (cf. session.go clearTo)."""
        if series_id <= self.responded_up_to:
            return
        if series_id == self.responded_up_to + 1 and series_id in self.history:
            del self.history[series_id]
            self.responded_up_to = series_id
            return
        for k in [k for k in self.history if k <= series_id]:
            del self.history[k]
        self.responded_up_to = series_id

    # -- snapshot codec ------------------------------------------------------
    def save(self) -> bytes:
        items = sorted(self.history.items())
        parts = [struct.pack("<QQI", self.client_id, self.responded_up_to, len(items))]
        for sid, res in items:
            parts.append(struct.pack("<QQI", sid, res.value, len(res.data)))
            parts.append(res.data)
        return b"".join(parts)

    @staticmethod
    def load(data: bytes, off: int = 0) -> Tuple["Session", int]:
        cid, responded, n = struct.unpack_from("<QQI", data, off)
        off += 20
        s = Session(cid)
        s.responded_up_to = responded
        for _ in range(n):
            sid, val, dlen = struct.unpack_from("<QQI", data, off)
            off += 20
            s.history[sid] = Result(value=val, data=bytes(data[off : off + dlen]))
            off += dlen
        return s, off


class SessionManager:
    """LRU of client sessions, deterministic across replicas: eviction order
    is a pure function of the applied entry sequence (cf. lrusession.go —
    the reference uses an llrb-backed LRU; an ordered dict gives the same
    deterministic recency order)."""

    def __init__(self, max_sessions: Optional[int] = None) -> None:
        self._max = max_sessions or hard.lru_max_session_count
        self._lru: "OrderedDict[int, Session]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._lru)

    def register_client_id(self, client_id: int) -> Result:
        """Apply a session-register entry (cf. sessionmanager.go:49-60)."""
        if client_id in self._lru:
            self._lru.move_to_end(client_id)
            return Result(value=client_id)
        self._lru[client_id] = Session(client_id)
        if len(self._lru) > self._max:
            self._lru.popitem(last=False)
        return Result(value=client_id)

    def unregister_client_id(self, client_id: int) -> Result:
        if client_id not in self._lru:
            return Result(value=0)
        del self._lru[client_id]
        return Result(value=client_id)

    def get_registered_client(self, client_id: int) -> Optional[Session]:
        s = self._lru.get(client_id)
        if s is not None:
            self._lru.move_to_end(client_id)
        return s

    def add_response(self, s: Session, series_id: int, result: Result) -> None:
        s.add_response(series_id, result)

    # -- snapshot ------------------------------------------------------------
    def save(self) -> bytes:
        parts = [struct.pack("<I", len(self._lru))]
        # LRU order (oldest first) so load() reconstructs identical recency
        for cid, s in self._lru.items():
            parts.append(s.save())
        return b"".join(parts)

    def load(self, data: bytes) -> None:
        (n,) = struct.unpack_from("<I", data, 0)
        off = 4
        self._lru.clear()
        for _ in range(n):
            s, off = Session.load(data, off)
            self._lru[s.client_id] = s

    def hash(self) -> int:
        """Deterministic digest for cross-replica equality checks
        (cf. monkey.go GetSessionHash)."""
        import zlib

        return zlib.crc32(self.save())


__all__ = ["Session", "SessionManager"]
