"""FaultPlane: deterministic, seeded fault injection as a first-class
subsystem.

The reference dragonboat validates itself with monkey tests (docs/
test.md:11-33): kill, partition, drop and corrupt while client traffic
runs, then assert linearizability + replica convergence. Here that
methodology is a library citizen instead of ad-hoc lambdas monkeypatched
into tests: ONE seed derives every fault decision, so any chaos failure
replays from the CI log by re-running with the printed seed.

Determinism model
-----------------
Every injection site (a named stream: "wire:h1", "fsync:h2/shard-3",
"faultloop", ...) owns an independent PRNG seeded from (plane seed, site
name). Decisions are drawn in per-site arrival order, so a site that is
only touched from one thread (per-target transport workers, the engine
loop, the orchestration loop) produces a bit-identical verdict sequence
on replay. Each decision is appended to a bounded schedule log;
`schedule_signature()` hashes it so tests can assert two same-seeded runs
produced identical schedules.

Seams composed (all pre-existing, none test-private):

  * transport wire path — `Transport.set_pre_send_batch_hook`: the hook
    mutates the batch in place (per-message drop/duplicate/reorder) and
    sleeps for delay faults on the per-target worker thread;
  * co-hosted delivery — `VectorEngine.set_local_drop_hook` for traffic
    that short-circuits the wire inside a shared core;
  * partitions — `NodeHost.set_partitioned` driven from the seeded
    orchestration stream (`partition_schedule`);
  * storage — `wrap_kv` / `kv_factory` wrap `IKVStore.sync`/commit with
    fsync-stall and fsync-error injection; `tear_wal_tail` simulates the
    torn-tail crash write.
"""
from __future__ import annotations

import hashlib
import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from .storage.kv import IKVStore, WriteBatch, _BarrierStats
from .trace import flight_recorder
from .types import Message, MessageBatch, MessageType


@dataclass
class FaultSpec:
    """Per-message / per-sync fault probabilities. All default to off."""

    drop: float = 0.0  # P(message dropped)
    duplicate: float = 0.0  # P(message duplicated in-batch)
    reorder: float = 0.0  # P(message held back and re-injected later)
    reorder_hold: int = 2  # batches a reordered message is held for
    delay: float = 0.0  # P(batch delayed on the worker thread)
    delay_s: Tuple[float, float] = (0.001, 0.02)
    fsync_stall: float = 0.0  # P(sync stalls)
    fsync_stall_s: Tuple[float, float] = (0.002, 0.02)
    fsync_error: float = 0.0  # P(sync raises IOError)
    append_error: float = 0.0  # P(one WAL record append raises IOError)
    # P(a crash_restart window also tears the victim's WAL tail before
    # the restart) — the mid-write power-cut on top of the process death
    tear_tail: float = 0.0
    # restrict wire faults to these types (None = all); lets a schedule
    # target e.g. replication only while heartbeats flow
    only_types: Optional[frozenset] = None

    def wire_active(self) -> bool:
        return bool(self.drop or self.duplicate or self.reorder or self.delay)


class _Stream:
    """One deterministic decision stream: seeded RNG + decision counter."""

    __slots__ = ("rng", "n", "mu")

    def __init__(self, plane_seed: int, site: str) -> None:
        digest = hashlib.sha256(
            f"{plane_seed}:{site}".encode()
        ).digest()
        self.rng = random.Random(int.from_bytes(digest[:8], "big"))
        self.n = 0
        self.mu = threading.Lock()


class FaultPlane:
    """Deterministic fault scheduler; see module docstring.

    `install(nh, site)` arms the wire seams of one NodeHost;
    `partition_schedule` drives partitions from the seeded orchestration
    stream; `wrap_kv`/`kv_factory` cover storage. The spec can be swapped
    live (`set_spec`) to open/close fault windows mid-run."""

    def __init__(
        self,
        seed: int,
        spec: Optional[FaultSpec] = None,
        record_schedule: bool = True,
        max_log: int = 200_000,
    ) -> None:
        self.seed = seed
        self.spec = spec or FaultSpec()
        self._streams: Dict[str, _Stream] = {}
        self._streams_mu = threading.Lock()
        self._log: List[tuple] = []
        self._log_mu = threading.Lock()
        self._record = record_schedule
        self._max_log = max_log
        self._installed: List[tuple] = []  # (kind, target) for uninstall
        # reorder holding pens: site -> list of (release_at_batch, Message)
        self._held: Dict[str, list] = {}
        self._batch_no: Dict[str, int] = {}

    # ------------------------------------------------------------- streams
    def _stream(self, site: str) -> _Stream:
        s = self._streams.get(site)
        if s is None:
            with self._streams_mu:
                s = self._streams.setdefault(site, _Stream(self.seed, site))
        return s

    def decide(self, site: str, kind: str, p: float) -> bool:
        """Draw one fault decision on `site`'s stream; logged for replay
        verification."""
        if p <= 0.0:
            return False
        s = self._stream(site)
        with s.mu:
            n = s.n
            s.n += 1
            verdict = s.rng.random() < p
        self._log_decision(site, kind, n, verdict)
        if verdict:
            # only FIRED faults hit the flight recorder: the timeline
            # answers "what was injected when", not "what was rolled"
            flight_recorder().record(
                "fault_injected", site=site, kind=kind, n=n,
                seed=self.seed,
            )
        return verdict

    def uniform(self, site: str, kind: str, lo: float, hi: float) -> float:
        s = self._stream(site)
        with s.mu:
            n = s.n
            s.n += 1
            v = lo + (hi - lo) * s.rng.random()
        self._log_decision(site, kind, n, round(v, 9))
        return v

    def choice(self, site: str, kind: str, options):
        """Seeded choice for orchestration loops (fault kind, victim)."""
        s = self._stream(site)
        with s.mu:
            n = s.n
            s.n += 1
            v = options[int(s.rng.random() * len(options)) % len(options)]
        self._log_decision(site, kind, n, v)
        return v

    def _log_decision(self, site, kind, n, verdict) -> None:
        if not self._record:
            return
        with self._log_mu:
            if len(self._log) < self._max_log:
                self._log.append((site, kind, n, verdict))

    def schedule_log(self) -> List[tuple]:
        with self._log_mu:
            return list(self._log)

    def schedule_signature(self, sites=None) -> str:
        """Stable digest of the schedule, ORDER-INSENSITIVE across sites
        (thread interleaving between sites is not deterministic; the
        per-site sequence is). `sites` restricts the digest to those
        site streams — orchestration loops use this to print a signature
        that is bit-identical across same-seeded replays even while
        per-message wire draws (whose COUNT depends on traffic timing)
        ride the same plane."""
        with self._log_mu:
            lines = sorted(
                repr(e) for e in self._log
                if sites is None or e[0] in sites
            )
        h = hashlib.sha256()
        for ln in lines:
            h.update(ln.encode())
            h.update(b"\n")
        return h.hexdigest()

    def set_spec(self, spec: FaultSpec) -> None:
        """Swap the live fault probabilities (open/close a fault window).
        Streams and their positions are preserved, so a window change does
        not desynchronize replay."""
        self.spec = spec

    # -------------------------------------------------------- wire faults
    def batch_hook(self, site: str) -> Callable[[MessageBatch], bool]:
        """Pre-send hook for `Transport.set_pre_send_batch_hook`: applies
        per-message drop/duplicate/reorder by mutating batch.requests and
        per-batch delay by sleeping on the (per-target) worker thread.
        Returns False when the whole batch should drop."""

        def hook(batch: MessageBatch) -> bool:
            # one Transport runs one worker thread PER TARGET address, and
            # all of them share this hook: sub-key the stream and the
            # reorder pen by the (stable) worker thread name so each
            # stream stays single-threaded — the determinism contract —
            # and the pen is never mutated concurrently
            site_t = f"{site}#{threading.current_thread().name}"
            spec = self.spec
            held = self._held.setdefault(site_t, [])
            active = spec.wire_active()
            if not active and not held:
                return True
            bno = self._batch_no.get(site_t, 0) + 1
            self._batch_no[site_t] = bno
            out: List[Message] = []
            # release previously held (reordered) messages first: they
            # jump the queue relative to their original position. The pen
            # drains even after the fault window closes — a held message
            # must never be silently leaked.
            if held:
                due = [m for rel, m in held if rel <= bno or not active]
                held[:] = [] if not active else [
                    (rel, m) for rel, m in held if rel > bno
                ]
                out.extend(due)
            if not active:
                out.extend(batch.requests)
                batch.requests[:] = out
                return True
            for m in batch.requests:
                targeted = spec.only_types is None or m.type in spec.only_types
                if targeted and self.decide(site_t, "drop", spec.drop):
                    continue
                if targeted and self.decide(site_t, "reorder", spec.reorder):
                    held.append((bno + spec.reorder_hold, m))
                    continue
                out.append(m)
                if targeted and self.decide(site_t, "dup", spec.duplicate):
                    out.append(m)
            batch.requests[:] = out
            if spec.delay and self.decide(site_t, "delay", spec.delay):
                time.sleep(
                    self.uniform(site_t, "delay_s", *spec.delay_s)
                )
            return bool(batch.requests)

        return hook

    def message_hook(self, site: str) -> Callable[[Message], bool]:
        """Drop predicate for co-hosted delivery
        (`VectorEngine.set_local_drop_hook`): True = drop. Duplicate/
        reorder/delay do not apply on the in-core path — it models a
        shared-memory exchange, not a lossy wire."""

        def hook(m: Message) -> bool:
            spec = self.spec
            if not spec.drop:
                return False
            if spec.only_types is not None and m.type not in spec.only_types:
                return False
            return self.decide(site, "local_drop", spec.drop)

        return hook

    def install(self, nh, site: str) -> None:
        """Arm one NodeHost's wire seams: the transport pre-send hook and,
        when its engine is a (possibly shared) vector core, the co-hosted
        delivery drop hook."""
        nh.transport.set_pre_send_batch_hook(self.batch_hook(f"wire:{site}"))
        core = getattr(nh.engine, "core", None) or nh.engine
        set_local = getattr(core, "set_local_drop_hook", None)
        if set_local is not None:
            set_local(self.message_hook(f"local:{site}"))
            self._installed.append(("local", core))
        self._installed.append(("wire", nh.transport))

    def uninstall(self, nh) -> None:
        """Disarm one NodeHost's wire seams (the windowed-fault path: arm
        the victim, sleep the window, disarm)."""
        nh.transport.set_pre_send_batch_hook(None)
        core = getattr(nh.engine, "core", None) or nh.engine
        set_local = getattr(core, "set_local_drop_hook", None)
        if set_local is not None:
            set_local(None)
        self._installed = [
            (k, t)
            for k, t in self._installed
            if t is not nh.transport and t is not core
        ]

    def uninstall_all(self) -> None:
        for kind, target in self._installed:
            try:
                if kind == "wire":
                    target.set_pre_send_batch_hook(None)
                else:
                    target.set_local_drop_hook(None)
            except Exception:
                pass
        self._installed.clear()

    # -------------------------------------------------------- partitions
    def partition_schedule(
        self,
        site: str,
        victims,
        total_s: float,
        min_window_s: float = 0.3,
        max_window_s: float = 0.8,
    ):
        """Yield a seeded sequence of (victim, heal_after_s, idle_s)
        partition windows covering ~total_s seconds. The caller applies
        them (`nh.set_partitioned(True)`, sleep, heal, sleep) so restarts
        and other orchestration can interleave."""
        budget = total_s
        victims = list(victims)
        while budget > 0:
            victim = self.choice(site, "victim", victims)
            window = self.uniform(site, "window", min_window_s, max_window_s)
            idle = self.uniform(site, "idle", 0.1, 0.4)
            flight_recorder().record(
                "partition_window", site=site, victim=victim,
                window_s=round(window, 4), seed=self.seed,
            )
            yield victim, window, idle
            budget -= window + idle

    # -------------------------------------------------- crash / restart
    def crash_restart_schedule(
        self,
        site: str,
        victims,
        total_s: float,
        min_down_s: float = 0.1,
        max_down_s: float = 0.5,
        tear_tail: Optional[float] = None,
    ):
        """Yield a seeded sequence of (victim, down_s, idle_s, tear)
        crash/restart windows covering ~total_s seconds — restart as a
        first-class FaultPlane verdict (the reference's drummer/monkey
        kill schedule, docs/test.md). The caller executes each window:
        crash the victim (NodeHost.crash() for process-death semantics,
        or crash_cluster() for one node of a multi-group host), wait the
        seeded down_s restart delay — during which the surviving quorum
        must keep serving (the graceful-degradation guarantee the
        fairness watchdog asserts) — then restart (a fresh NodeHost on
        the durable dir / restart_cluster) and idle idle_s. tear=True
        directs the caller to run tear_wal_tails() on the victim's
        closed WAL dir before the restart. All decisions ride this
        site's single stream, so a same-seeded rerun replays the crash
        schedule bit-identically (schedule_signature)."""
        budget = total_s
        victims = list(victims)
        p_tear = self.spec.tear_tail if tear_tail is None else tear_tail
        while budget > 0:
            victim = self.choice(site, "crash_victim", victims)
            down = self.uniform(site, "down_s", min_down_s, max_down_s)
            idle = self.uniform(site, "crash_idle", 0.1, 0.4)
            tear = self.decide(site, "tear_tail", p_tear)
            flight_recorder().record(
                "crash_restart_window", site=site, victim=victim,
                down_s=round(down, 4), tear=tear, seed=self.seed,
            )
            yield victim, down, idle, tear
            budget -= down + idle

    # ----------------------------------------------------- overload storms
    def overload_storm_schedule(
        self,
        site: str,
        tenants,
        total_s: float,
        min_window_s: float = 0.2,
        max_window_s: float = 0.6,
    ):
        """Yield a seeded sequence of (profile, mult, window_s, weights)
        overload windows covering ~total_s seconds — the serving front's
        storm scenario (see serving/storm.py). `profile` is "burst"
        (short, 2-4x offered load) or "sustained" (longer, 1.5-2.5x);
        `mult` multiplies each tenant's admitted capacity into its
        OFFERED load; `weights` skews the tenant mix per window (seeded
        per tenant in sorted order, so the draw sequence — and the
        schedule signature — replays bit-identically for the same
        seed). The caller drives traffic per window; op counts derived
        from (mult, window_s) keep the replayed op sequence identical
        without wall-clock coupling."""
        budget = total_s
        tenants = sorted(tenants)
        while budget > 0:
            profile = self.choice(
                site, "storm_profile", ["burst", "sustained"]
            )
            if profile == "burst":
                mult = self.uniform(site, "storm_mult", 2.0, 4.0)
                window = self.uniform(
                    site, "storm_window", min_window_s,
                    (min_window_s + max_window_s) / 2,
                )
            else:
                mult = self.uniform(site, "storm_mult", 1.5, 2.5)
                window = self.uniform(
                    site, "storm_window",
                    (min_window_s + max_window_s) / 2, max_window_s,
                )
            weights = {
                t: round(self.uniform(site, "storm_weight", 0.5, 2.0), 6)
                for t in tenants
            }
            flight_recorder().record(
                "overload_storm_window", site=site, profile=profile,
                mult=round(mult, 4), window_s=round(window, 4),
                seed=self.seed,
            )
            yield profile, mult, window, weights
            budget -= window

    def tear_wal_tails(self, logdb_dir: str, site: str) -> int:
        """Tear the tail of every shard WAL under a CLOSED ShardedLogDB
        root (shard-<i>/wal.log) — the disk half of a crash_restart
        window with tear=True. Each shard tears on its own seeded
        stream. Returns total bytes removed; recovery must roll every
        shard back to its last sealed record group."""
        total = 0
        if not logdb_dir or not os.path.isdir(logdb_dir):
            return 0
        for name in sorted(os.listdir(logdb_dir)):
            d = os.path.join(logdb_dir, name)
            if name.startswith("shard-") and os.path.isdir(d):
                total += self.tear_wal_tail(d, f"{site}/{name}")
        return total

    # ----------------------------------------------------- storage faults
    def wrap_kv(self, kv: IKVStore, site: str) -> "FaultyKV":
        return FaultyKV(kv, self, site)

    def kv_factory(
        self, site: str, base_factory: Callable[[str], IKVStore]
    ) -> Callable[[str], IKVStore]:
        """Factory adapter for ShardedLogDB(kv_factory=...): every shard's
        store is wrapped with fsync fault injection on its own stream."""

        def make(dirname: str) -> IKVStore:
            shard = os.path.basename(dirname) if dirname else "mem"
            return self.wrap_kv(base_factory(dirname), f"{site}/{shard}")

        return make

    def maybe_append_fault(self, site: str) -> None:
        """Injection point FaultyKV arms on the store's per-record append
        seam (`WalKV.set_append_fault`): raises mid-record-group, BEFORE
        the commit seal, so the store's rollback path — not recovery
        luck — must guarantee no half-sealed group survives a reopen."""
        spec = self.spec
        if spec.append_error and self.decide(
            site, "append_error", spec.append_error
        ):
            raise IOError(
                f"FaultPlane(seed={self.seed}): injected append error"
            )

    def maybe_fsync_fault(self, site: str) -> None:
        """The injection point FaultyKV runs before a durability barrier."""
        spec = self.spec
        if spec.fsync_stall and self.decide(site, "fsync_stall", spec.fsync_stall):
            time.sleep(self.uniform(site, "fsync_stall_s", *spec.fsync_stall_s))
        if spec.fsync_error and self.decide(site, "fsync_error", spec.fsync_error):
            raise IOError(f"FaultPlane(seed={self.seed}): injected fsync error")

    def tear_wal_tail(self, wal_dir: str, site: str) -> int:
        """Simulate a torn tail write: chop a seeded number of bytes off
        the WAL's end (the store must be closed). Returns bytes removed;
        recovery must roll back to the last sealed record group."""
        path = os.path.join(wal_dir, "wal.log")
        size = os.path.getsize(path) if os.path.exists(path) else 0
        if size == 0:
            return 0
        cut = 1 + int(self.uniform(site, "tear", 0, min(size - 1, 64)))
        with open(path, "ab") as f:
            f.truncate(size - cut)
        return cut


class FaultyKV(IKVStore):
    """Delegating IKVStore wrapper that injects fsync stalls/errors at the
    durability barriers (commit_write_batch's implicit barrier and the
    group-commit sync())."""

    def __init__(self, inner: IKVStore, plane: FaultPlane, site: str) -> None:
        self.inner = inner
        self.plane = plane
        self.site = site
        self._fsync_observer = None
        # the wrapper's OWN barrier ledger: ShardedLogDB.barrier_stats()
        # aggregates per-store `bstats`, and with the wrapper in front
        # the inner store's ledger is unreachable — worse, the inner
        # ledger times only the REAL fsync, so an injected stall would
        # vanish from the per-host WAL pressure signal (and from
        # tools.doctor's wal_fsync_stall evidence) exactly when it
        # matters most
        self.bstats = _BarrierStats()
        # arm the per-record append seam when the store exposes one
        # (WalKV): the fault fires INSIDE a record group, before the
        # commit seal, which is the torn-batch case fsync faults can't
        # reach
        set_af = getattr(inner, "set_append_fault", None)
        if set_af is not None:
            set_af(lambda: plane.maybe_append_fault(site))

    def name(self) -> str:
        return f"faulty-{self.inner.name()}"

    def close(self) -> None:
        self.inner.close()

    def close_crashed(self) -> None:
        cc = getattr(self.inner, "close_crashed", None)
        (cc if cc is not None else self.inner.close)()

    def get_value(self, key):
        return self.inner.get_value(key)

    def iterate_value(self, fk, lk, inc_last, op) -> None:
        self.inner.iterate_value(fk, lk, inc_last, op)

    def _timed_barrier(self, fn) -> None:
        """Run one durability barrier (injected fault + the real thing)
        under the fsync observer's clock: the histogram must see the
        EFFECTIVE barrier latency including injected stalls, or a chaos
        run's fsync_latency p99 would never line up with its
        fault_injected{kind="fsync_stall"} timeline."""
        obs = self._fsync_observer
        t0 = time.monotonic()
        self.bstats.enter()
        try:
            self.plane.maybe_fsync_fault(self.site)
            fn()
        finally:
            self.bstats.exit(time.monotonic() - t0)
        if obs is not None:
            obs(time.monotonic() - t0)

    def commit_write_batch(self, wb: WriteBatch) -> None:
        self._timed_barrier(lambda: self.inner.commit_write_batch(wb))

    def commit_write_batch_deferred(self, wb: WriteBatch) -> bool:
        return self.inner.commit_write_batch_deferred(wb)

    def sync(self) -> None:
        self._timed_barrier(self.inner.sync)

    def set_fsync_observer(self, cb) -> None:
        # observation stays at the WRAPPER (not forwarded to the inner
        # store) so injected stalls are part of the measured barrier
        self._fsync_observer = cb

    def bulk_remove_entries(self, fk, lk) -> None:
        self.inner.bulk_remove_entries(fk, lk)

    def compact_entries(self, fk, lk) -> None:
        self.inner.compact_entries(fk, lk)

    def full_compaction(self) -> None:
        self.inner.full_compaction()


class ClockPlane:
    """Seeded clock-fault injection for the tick plane.

    Raft here has no wall clock: every timeout is counted in ticks, and
    ticks are minted by each NodeHost's tick worker off a monotonic
    clock. A machine whose clock skews, drifts or step-jumps therefore
    shows up as a tick stream that runs fast, slow, or lurches — exactly
    the failure a leader lease must survive. ClockPlane models that by
    owning an injectable per-host clock (`clock_fn(host)`) that NodeHost
    substitutes for `time.monotonic` in its tick worker.

    Per host the faulted clock is a piecewise-linear transform of real
    monotonic time: ``fault(t) = f0 + (t - r0) * rate``. Mutations
    re-anchor (r0, f0) at the current faulted reading first, so:

      * `set_skew` / `step_jump` — add an instant offset (negative jumps
        make the clock read BACKWARD, the anomaly the tick worker must
        detect rather than replay as a tick burst);
      * `set_drift` — change the rate (0.5 = half speed, 2.0 = double);
      * `clear` — pin rate back to 1.0 while keeping the accumulated
        offset (continuity: healing a drift must not itself be a jump).

    The transform is draw-free, so the *clock* needs no replay contract;
    the seeded part is `chaos_schedule`, whose decisions ride the owning
    FaultPlane's streams and land in its schedule log — the same
    bit-identical `schedule_signature()` replay contract as
    `crash_restart_schedule`."""

    def __init__(self, plane: FaultPlane) -> None:
        self.plane = plane
        self._mu = threading.Lock()
        # host -> [anchor_real r0, anchor_fault f0, rate]
        self._hosts: Dict[str, list] = {}

    # ------------------------------------------------------------ reading
    def now(self, host) -> float:
        real = time.monotonic()
        with self._mu:
            st = self._hosts.get(host)
            if st is None:
                return real
            r0, f0, rate = st
        return f0 + (real - r0) * rate

    def clock_fn(self, host) -> Callable[[], float]:
        """The injectable clock a NodeHost mounts in its tick worker
        (`NodeHost.set_tick_clock`). Hosts without injected faults read
        real monotonic time, so mounting the plane everywhere is free."""
        return lambda: self.now(host)

    # ---------------------------------------------------------- mutations
    def _reanchor_locked(self, host) -> list:
        """Pin (r0, f0) at the current faulted reading so the mutation
        about to follow is continuous. Caller holds self._mu."""
        real = time.monotonic()
        st = self._hosts.get(host)
        if st is None:
            st = [real, real, 1.0]
            self._hosts[host] = st
        else:
            r0, f0, rate = st
            st[0] = real
            st[1] = f0 + (real - r0) * rate
        return st

    def set_skew(self, host, offset_s: float) -> None:
        """Step the host's clock by offset_s (instant, signed)."""
        with self._mu:
            self._reanchor_locked(host)[1] += float(offset_s)

    def step_jump(self, host, offset_s: float) -> None:
        """A large instant step — same mechanics as `set_skew`, named
        separately so fault schedules and flight-recorder timelines can
        distinguish sub-tick skew from multi-tick lurches."""
        self.set_skew(host, offset_s)

    def set_drift(self, host, rate: float) -> None:
        """Run the host's clock at `rate` × real time from now on."""
        with self._mu:
            self._reanchor_locked(host)[2] = max(float(rate), 0.0)

    def clear(self, host) -> None:
        """Heal drift (rate back to 1.0) keeping the accumulated offset;
        clearing must not itself inject a jump."""
        with self._mu:
            self._reanchor_locked(host)[2] = 1.0

    def reset(self, host) -> None:
        """Drop all fault state: the host reads real time again. This IS
        a (possibly backward) jump — use `clear` for a continuous heal."""
        with self._mu:
            self._hosts.pop(host, None)

    # ----------------------------------------------------------- schedule
    def chaos_schedule(
        self,
        site: str,
        hosts,
        total_s: float,
        min_window_s: float = 0.2,
        max_window_s: float = 0.8,
    ):
        """Yield a seeded sequence of (host, kind, magnitude, window_s,
        idle_s) clock-fault windows covering ~total_s seconds. kind is
        "skew" (± fractions of a second), "drift" (rate 0.25..3.0) or
        "jump" (± seconds, enough to cross tick-burst and backward-
        reading thresholds). The caller applies each window
        (`apply(host, kind, magnitude)`, sleep window_s, `clear(host)`,
        sleep idle_s) so clock chaos interleaves with crash/partition
        orchestration. All draws ride the owning FaultPlane's `site`
        stream — same-seeded reruns replay the schedule bit-identically
        (schedule_signature)."""
        budget = total_s
        hosts = list(hosts)
        plane = self.plane
        while budget > 0:
            host = plane.choice(site, "clock_host", hosts)
            kind = plane.choice(
                site, "clock_kind", ["skew", "drift", "jump"]
            )
            if kind == "skew":
                mag = plane.uniform(site, "clock_skew_s", -0.25, 0.25)
            elif kind == "drift":
                mag = plane.uniform(site, "clock_rate", 0.25, 3.0)
            else:
                mag = plane.uniform(site, "clock_jump_s", -2.0, 2.0)
            window = plane.uniform(
                site, "clock_window", min_window_s, max_window_s
            )
            idle = plane.uniform(site, "clock_idle", 0.05, 0.3)
            flight_recorder().record(
                "clock_fault_window", site=site, host=host, kind=kind,
                magnitude=round(mag, 4), window_s=round(window, 4),
                seed=plane.seed,
            )
            yield host, kind, mag, window, idle
            budget -= window + idle

    def apply(self, host, kind: str, magnitude: float) -> None:
        """Apply one schedule entry to the live clock."""
        if kind == "drift":
            self.set_drift(host, magnitude)
        elif kind == "jump":
            self.step_jump(host, magnitude)
        else:
            self.set_skew(host, magnitude)


# message classes a chaos schedule usually wants to target (bulk data
# plane) while the control plane keeps flowing
REPLICATION_TYPES = frozenset(
    {MessageType.REPLICATE, MessageType.REPLICATE_RESP}
)


__all__ = [
    "ClockPlane",
    "FaultPlane",
    "FaultSpec",
    "FaultyKV",
    "REPLICATION_TYPES",
]
