"""NodeHost: the public facade hosting many Raft groups in one process.

cf. nodehost.go:243-2103 — lifecycle of all groups, the tick fanout, the
transport receive path, and every user-facing request method
(propose/read/membership/snapshot/transfer) in both async (RequestState)
and synchronous (Sync*) forms.
"""
from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional

from .client import Session
from .config import Config, NodeHostConfig
from .core.peer import PeerAddress
from .engine.execengine import ExecEngine
from .engine.node import Node
from .events import MetricsRegistry, RaftEventAggregator
from .engine.snapshotter import Snapshotter
from .raftio import ErrNoBootstrapInfo, IMessageHandler
from .requests import (
    RequestError,
    ErrClusterClosed,
    ErrClusterNotFound,
    ErrClusterNotReady,
    ErrInvalidSession,
    ErrLeaseExpired,
    ErrRejected,
    ErrTimeout,
    RequestResult,
    RequestState,
    PendingLeaderTransfer,
)
from .rsm import SSRequest, SS_REQ_EXPORTED, SS_REQ_USER
from .statemachine import Result, sm_type_of
from .storage import LogReader, ShardedLogDB
from .profile import HistorySampler, compile_watch, sync_audit
from .profile import write_exposition as _write_profile_exposition
from .trace import flight_recorder, read_mmap_ring
from .transport import Transport, loopback_factory
from .transport.tcp import tcp_factory
from .types import (
    Bootstrap,
    ConfigChange,
    ConfigChangeType,
    Membership,
    Message,
    MessageType,
)


class ErrDirNotExist(RequestError):
    """Export path does not exist (cf. nodehost.go:905)."""


class ErrClusterAlreadyExist(RequestError):
    code = "cluster already exist"


class ErrInvalidClusterSettings(RequestError):
    code = "cluster settings are invalid"


class ErrDeadlineNotSet(RequestError):
    code = "deadline not set"


class ErrDirLocked(RuntimeError):
    """The nodehost dir is held by another live NodeHost
    (cf. internal/server/context.go dir-lock files)."""


class ClusterInfo:
    """cf. nodehost.go GetNodeHostInfo ClusterInfo."""

    def __init__(self, cluster_id, node_id, nodes, config_change_index, is_leader):
        self.cluster_id = cluster_id
        self.node_id = node_id
        self.nodes = nodes
        self.config_change_index = config_change_index
        self.is_leader = is_leader


class NodeHostInfo:
    """Aggregate introspection record (cf. nodehost.go:1289-1302
    GetNodeHostInfo): the host's address, per-cluster states, and the logdb
    inventory. Iterable over cluster_info for drop-in compatibility with
    callers that treated get_nodehost_info() as a ClusterInfo list."""

    def __init__(self, raft_address, cluster_info, log_info):
        self.raft_address = raft_address
        self.cluster_info = cluster_info
        self.log_info = log_info

    def __iter__(self):
        return iter(self.cluster_info)

    def __len__(self):
        return len(self.cluster_info)


class NodeHost(IMessageHandler):
    def __init__(self, cfg: NodeHostConfig) -> None:
        cfg.validate()
        self.config = cfg
        self._nodes_mu = threading.RLock()
        self._nodes: Dict[int, Node] = {}
        # restart plane: how each cluster was started, so
        # restart_cluster() can re-run WAL recovery and rejoin without
        # the caller re-supplying members/factory/config
        # (cluster_id -> (initial_members, join, sm_factory, cfg))
        self._launch_specs: Dict[int, tuple] = {}
        self._stopped = threading.Event()
        # --- events + metrics (cf. event.go:34-141)
        self.metrics = MetricsRegistry()
        self._event_aggregator = RaftEventAggregator(
            self.metrics,
            user_listener=cfg.raft_event_listener,
            enable_metrics=cfg.enable_metrics,
        )
        # --- directories
        self._dir_lock_fd = None
        if cfg.nodehost_dir:
            self._dir = os.path.join(
                cfg.nodehost_dir, cfg.raft_address.replace(":", "-")
            )
            os.makedirs(self._dir, exist_ok=True)
            self._acquire_dir_lock()
            self._tmpdir = None
        else:
            self._tmpdir = tempfile.TemporaryDirectory(prefix="dbtpu-")
            self._dir = self._tmpdir.name
        # --- logdb
        if cfg.logdb_factory is not None:
            self.logdb = cfg.logdb_factory(self._dir)
        elif cfg.nodehost_dir:
            self.logdb = ShardedLogDB(os.path.join(self._dir, "logdb"))
        else:
            self.logdb = ShardedLogDB()  # in-memory
        # WAL durability-barrier latency -> fsync_latency_seconds histogram
        # (observed at every real fsync; barriers are ms-scale and the
        # observation is two clock reads + a bucket increment)
        set_fsync_obs = getattr(self.logdb, "set_fsync_observer", None)
        if set_fsync_obs is not None:
            set_fsync_obs(self._observe_fsync)
        # --- transport
        if cfg.raft_rpc_factory is not None:
            rpc_factory = cfg.raft_rpc_factory(cfg.get_listen_address())
        else:
            rpc_factory = tcp_factory(cfg.get_listen_address())
        self.transport = Transport(
            cfg.raft_address,
            cfg.deployment_id,
            rpc_factory,
            # max_send_queue_size is a BYTE bound (cf. NodeHostConfig in
            # config.go); the count bound stays at the soft default
            max_send_queue_bytes=cfg.max_send_queue_size or 0,
        )
        self.transport.set_message_handler(self)
        from .transport.chunks import Chunks  # lazy: needs snapshot dir root

        self._chunks = Chunks(self)
        self.transport.set_chunk_sink(self._recv_chunk)
        self.transport.start()
        # outbound snapshot stream admission (cf. lane.go:40-237 +
        # StreamConnections, config.go:299-306): hard caps on total and
        # per-target concurrent lanes — a request over either cap fails
        # fast via snapshot-status feedback, never queues a thread
        from .transport.snapshotstream import RateLimiter

        self._lane_mu = threading.Lock()
        self._lanes_total = 0
        self._lanes_by_target: Dict[str, int] = {}
        self._max_lanes = max(1, cfg.max_snapshot_connections)
        self._max_lanes_per_target = max(1, cfg.max_snapshot_lanes_per_target)
        self._snap_send_rate = (
            RateLimiter(cfg.max_snapshot_send_bytes_per_second)
            if cfg.max_snapshot_send_bytes_per_second
            else None
        )
        self._snap_recv_rate = (
            RateLimiter(cfg.max_snapshot_recv_bytes_per_second)
            if cfg.max_snapshot_recv_bytes_per_second
            else None
        )
        # --- engine
        if cfg.engine.kind == "vector":
            from .engine.vector import get_vector_engine

            self.engine = get_vector_engine(self.logdb, cfg)
        else:
            self.engine = ExecEngine(
                self.logdb,
                tick_period_s=cfg.rtt_millisecond / 1000.0,
                fairness_yield_ms=getattr(
                    cfg.engine, "fairness_yield_ms", None
                ),
            )
        # --- tick loop
        self._tick_ms = cfg.rtt_millisecond
        # injectable tick clock (faults.ClockPlane.clock_fn): the tick
        # worker mints ticks off THIS clock, so injected skew/drift/
        # step-jumps reach the tick plane exactly where a faulty machine
        # clock would. Default is real monotonic time; anomaly detection
        # only arms when a non-default clock is mounted.
        self._tick_clock: Callable[[], float] = time.monotonic
        self._clock_anomalies = 0
        self._tick_thread = threading.Thread(
            target=self._tick_worker_main, name="nh-tick", daemon=True
        )
        self._tick_thread.start()
        self._partitioned = False  # monkey-test knob
        # lazily-created overload-robust ingress (serving/front.py); read
        # lock-free by the gauge exporter, created/torn down under
        # _serving_mu
        self._serving = None
        self._serving_mu = threading.Lock()
        # lazily-created placement plane (serving/placement.py); same
        # create/teardown discipline as the front
        self._placement = None
        # clusters mid live-migration (serving/placement.py): consulted
        # by the inbound chunk tracker to tag migration install streams;
        # guarded by _nodes_mu like the rest of the cluster tables
        self._migrating: set = set()
        # ping/pong RTT samples: (cluster_id, peer) -> deque of microseconds
        self._rtt_mu = threading.Lock()
        self._rtt: Dict[tuple, object] = {}
        # crash-persistent flight recorder: DRAGONBOAT_FLIGHT_RING=<path>
        # tees the process-global recorder into an mmap ring so a
        # SIGKILL'd host still leaves a timeline recover_flight_ring()
        # can read (attach is idempotent across co-hosted NodeHosts)
        ring_path = os.environ.get("DRAGONBOAT_FLIGHT_RING")
        if ring_path:
            try:
                flight_recorder().attach_mmap(ring_path)
            except Exception:
                pass  # forensics must never block bring-up
        # telemetry history ring (profile.HistorySampler): a background
        # sampler turning this host's zero-sync stat surfaces into a
        # crash-persistent time series next to the flight ring.
        # DRAGONBOAT_HISTORY_RING=<path> auto-starts it at bring-up
        # (tools.doctor reads the ring back); start_history() is the
        # programmatic path (tools.longhaul samples a whole fleet into
        # one per-round ring instead).
        self._history: Optional[HistorySampler] = None
        hist_path = os.environ.get("DRAGONBOAT_HISTORY_RING")
        if hist_path:
            try:
                self.start_history(hist_path)
            except Exception:
                pass  # forensics must never block bring-up

    def _acquire_dir_lock(self) -> None:
        """Exclusive advisory lock on the nodehost dir (cf. reference
        internal/server/context.go:72-333 dir-lock files): a second process
        or NodeHost opening the same dir would silently corrupt the WAL, so
        it must fail fast instead."""
        import fcntl

        path = os.path.join(self._dir, "LOCK")
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            raise ErrDirLocked(
                f"nodehost dir {self._dir} is locked by another NodeHost"
            )
        os.ftruncate(fd, 0)
        os.write(fd, f"pid={os.getpid()} addr={self.config.raft_address}\n".encode())
        self._dir_lock_fd = fd

    def _release_dir_lock(self) -> None:
        if self._dir_lock_fd is not None:
            import fcntl

            try:
                fcntl.flock(self._dir_lock_fd, fcntl.LOCK_UN)
            finally:
                os.close(self._dir_lock_fd)
                self._dir_lock_fd = None

    # ------------------------------------------------------------ properties
    def raft_address(self) -> str:
        return self.config.raft_address

    def snapshot_dir_root(self) -> str:
        return os.path.join(self._dir, "snapshots")

    # --------------------------------------------------------------- lifecyle
    def stop(self) -> None:
        self._teardown(crashed=False)

    def crash(self) -> None:
        """SIGKILL-equivalent in-process teardown of the WHOLE host (the
        drummer harness's kill verdict, cf. reference docs/test.md):
        nothing is drained or flushed — nodes are abandoned mid-flight
        (their pending requests terminate like a reset connection), a
        sole-tenant vector core discards its un-decoded in-flight step
        instead of decoding and saving it, and the WAL files close
        WITHOUT a final durability barrier (close_crashed), so the only
        durable state is what past save waves already fsynced. The
        nodehost dir survives for a restarted NodeHost to recover from;
        run FaultPlane.tear_wal_tails(crashed.logdb_dir(), ...) before
        the restart to also simulate a torn mid-write tail."""
        flight_recorder().record(
            "host_crashed", host=self.config.raft_address,
        )
        self._teardown(crashed=True)

    def _teardown(self, crashed: bool) -> None:
        self._stopped.set()
        # history sampler dies FIRST: it reads engine/logdb surfaces that
        # are about to close under it. Graceful stop flushes one final
        # sample; a crash abandons the ring mid-write like a SIGKILL
        # would — recovering THAT state is what the ring is for.
        try:
            self.stop_history(final_sample=not crashed)
        except Exception:
            pass  # forensics must never block teardown
        with self._serving_mu:
            front, self._serving = self._serving, None
            plane, self._placement = self._placement, None
        if plane is not None:
            # the pacer thread must die first (graceful or not): a
            # migration step against a closing host is just churn
            plane.abort()
            plane.stop()
        if front is not None and not crashed:
            # graceful stop drains queued tickets with ErrClusterClosed;
            # a crash abandons them exactly like every other in-flight
            # request on this host
            front.stop()
        with self._nodes_mu:
            nodes = list(self._nodes.values())
            self._nodes.clear()
            self._launch_specs.clear()
        for n in nodes:
            if crashed:
                # abrupt: terminate waiters FIRST so the engine's
                # in-flight step observes a dead node (skips sends/task
                # handoff) rather than a live one being unplugged
                n.close()
                self.engine.remove_node(n.cluster_id)
            else:
                self.engine.remove_node(n.cluster_id)
                n.close()
        if crashed:
            crash = getattr(self.engine, "crash", None)
            (crash if crash is not None else self.engine.stop)()
        else:
            self.engine.stop()
        self.transport.stop()
        if crashed:
            cc = getattr(self.logdb, "close_crashed", None)
            (cc if cc is not None else self.logdb.close)()
        else:
            self.logdb.close()
        self._event_aggregator.stop()
        if self._tick_thread.is_alive():
            self._tick_thread.join(timeout=2)
        self._release_dir_lock()
        if self._tmpdir is not None:
            self._tmpdir.cleanup()

    def logdb_dir(self) -> str:
        """On-disk logdb root (shard WALs live in shard-<i> below it) —
        the tear_wal_tails target after a crash(). Derived from the live
        store's own layout when it exposes one (shard_dirs), so a custom
        logdb_factory rooting the WALs elsewhere still tears the real
        files; the `<nodehost_dir>/logdb` convention is the fallback."""
        sd = getattr(self.logdb, "shard_dirs", None)
        if sd is not None:
            dirs = sd()
            if dirs:
                return os.path.dirname(dirs[0])
        return os.path.join(self._dir, "logdb")

    def _observe_fsync(self, seconds: float) -> None:
        self.metrics.observe("fsync_latency_seconds", (0, 0), seconds)

    def write_health_metrics(self, w) -> None:
        """Prometheus text exposition of node + transport metrics
        (cf. WriteHealthMetrics event.go:30-32)."""
        self.metrics.write(w)
        for name, v in sorted(self.transport.metrics().items()):
            full = f"dragonboat_tpu_transport_{name}_total"
            w.write(f"# TYPE {full} counter\n")
            w.write(f"{full} {v:g}\n")
        # perf attribution plane: engine_phase_seconds{engine=,phase=}
        # histograms + per-jitted-function compile-cache gauges
        _write_profile_exposition(w)

    # ----------------------------------------------------------- forensics
    # dump_flight artifact bound: a runaway event source must not turn a
    # forensic dump into a disk-filling liability on a production host
    # (the ROADMAP "ship recorder dumps off-host" headroom's shippable
    # slice — bounded, compressed artifacts)
    DUMP_FLIGHT_MAX_BYTES = 8 << 20

    def dump_flight(
        self,
        path: str,
        cluster_id: Optional[int] = None,
        max_bytes: int = DUMP_FLIGHT_MAX_BYTES,
    ) -> str:
        """Write the process flight recorder as JSONL (optionally filtered
        to one cluster) with a `_meta` header line so tools.timeline can
        merge this host's dump with other hosts' on one clock.

        Artifact discipline: the dump is capped at `max_bytes` — when the
        serialized timeline exceeds it, the OLDEST lines are dropped (the
        recent tail is the forensic payload) and the `_meta` line carries
        `dropped_events`. A pre-existing artifact at `path` rotates to
        `<path>.1.gz` (gzip-compressed, previous rotation overwritten) so
        repeated dumps keep exactly one bounded predecessor. A `path`
        ending in `.gz` writes gzip directly; tools.timeline reads both
        transparently. Returns the path."""
        import gzip

        rec = flight_recorder()
        kw = {} if cluster_id is None else {"cluster_id": cluster_id}
        meta = {"source": self.config.raft_address}
        text = rec.to_jsonl(meta=meta, **kw) + "\n"
        if max_bytes and len(text) > max_bytes:
            lines = text.splitlines(keepends=True)
            head, tail = lines[0], lines[1:]  # _meta line stays first
            size = len(head)
            keep: List[str] = []
            for ln in reversed(tail):  # newest-first fill
                if size + len(ln) > max_bytes:
                    break
                keep.append(ln)
                size += len(ln)
            keep.reverse()
            import json

            # re-emit the meta header with the drop count
            m = {
                "event": "_meta",
                "mono_offset": round(rec.mono_offset, 6),
                "dropped_events": len(tail) - len(keep),
            }
            m.update(meta)
            head = json.dumps(m, default=str, sort_keys=True) + "\n"
            text = head + "".join(keep)
        if os.path.exists(path) and not path.endswith(".gz"):
            # gzip rotation: the previous artifact survives, compressed
            try:
                with open(path, "rb") as src, gzip.open(
                    path + ".1.gz", "wb"
                ) as dst:
                    dst.write(src.read())
            except OSError:
                pass  # rotation is best-effort; the fresh dump matters more
        if path.endswith(".gz"):
            with gzip.open(path, "wt") as f:
                f.write(text)
        else:
            with open(path, "w") as f:
                f.write(text)
        return path

    @staticmethod
    def recover_flight_ring(path: str) -> List[dict]:
        """Read a (possibly SIGKILL'd) process's mmap flight ring back as
        an ordered event list (see trace.read_mmap_ring)."""
        _meta, events = read_mmap_ring(path)
        return events

    def start_history(
        self,
        path: Optional[str] = None,
        interval_s: Optional[float] = None,
        **kw,
    ) -> HistorySampler:
        """Start the telemetry history sampler for THIS host: every
        ``interval_s`` (profile.HISTORY_INTERVAL_S default) a bounded
        snapshot of the zero-sync stat surfaces lands in a
        crash-persistent ring at ``path`` (default
        ``<nodehost_dir>/history.ring``, next to the WAL). Idempotent —
        a second call returns the running sampler. Entirely off the
        engine step path; the ``engine_history_*`` gauges report its
        measured cost."""
        if self._history is not None:
            return self._history
        if path is None:
            path = os.path.join(self._dir, "history.ring")
        if interval_s is not None:
            kw["interval_s"] = interval_s
        self._history = HistorySampler(path, {0: self}, **kw).start()
        return self._history

    def stop_history(self, final_sample: bool = True) -> None:
        """Stop the history sampler (graceful path takes one final
        sample so the last state of a clean shutdown is on disk too).
        No-op when no sampler is running."""
        sampler, self._history = self._history, None
        if sampler is not None:
            sampler.stop(final_sample=final_sample)

    def clock_anomalies(self) -> int:
        """Cumulative tick-clock fault count (the tick worker's
        divergence detector) — the history sampler's clock-fault
        series and tools.doctor's clock_anomaly signal."""
        return self._clock_anomalies

    # ------------------------------------------------------------ start paths
    def start_cluster(
        self,
        initial_members: Dict[int, str],
        join: bool,
        sm_factory: Callable,
        cfg: Config,
    ) -> None:
        """cf. nodehost.go:431-475 StartCluster + startCluster:1476-1560.
        sm_factory(cluster_id, node_id) returns an IStateMachine /
        IConcurrentStateMachine / IOnDiskStateMachine."""
        if self._stopped.is_set():
            raise ErrClusterClosed()
        bootstrap, new_node = self._prepare_cluster(
            initial_members, join, sm_factory, cfg
        )
        if new_node:
            self.logdb.save_bootstrap_info(
                cfg.cluster_id, cfg.node_id, bootstrap
            )
        self._launch_node(
            initial_members, join, sm_factory, cfg, bootstrap, new_node
        )

    def _prepare_cluster(self, initial_members, join, sm_factory, cfg: Config):
        """Shared validation + SM-type probing + bootstrap construction for
        both the single and bulk start paths (persisting is the caller's
        job — the bulk path batches it)."""
        cfg.validate()
        cluster_id, node_id = cfg.cluster_id, cfg.node_id
        with self._nodes_mu:
            if cluster_id in self._nodes:
                raise ErrClusterAlreadyExist()
        if join and initial_members:
            raise ErrInvalidClusterSettings()
        probe = sm_factory(cluster_id, node_id)
        smtype = sm_type_of(probe)
        if hasattr(probe, "close"):
            probe.close()
        return self._peek_bootstrap(initial_members, join, cfg, smtype)

    def start_clusters(self, specs) -> None:
        """Bulk StartCluster for fleet bring-up: specs are
        (initial_members, join, sm_factory, config) tuples. Bootstrap
        records for all new clusters persist in ONE fsynced batch per logdb
        shard, and the engine activates all lanes in its batched scatter —
        50k idle groups come up in seconds instead of minutes (the
        reference brings groups up one StartCluster at a time,
        nodehost.go:431-475; its cheap-idle-group story starts only after
        launch, README.md:48-51)."""
        if self._stopped.is_set():
            raise ErrClusterClosed()
        prepared = []
        boots = []
        seen: set = set()
        for initial_members, join, sm_factory, cfg in specs:
            if cfg.cluster_id in seen:
                raise ErrClusterAlreadyExist()
            seen.add(cfg.cluster_id)
            bootstrap, new_node = self._prepare_cluster(
                initial_members, join, sm_factory, cfg
            )
            if new_node:
                boots.append((cfg.cluster_id, cfg.node_id, bootstrap))
            prepared.append(
                (initial_members, join, sm_factory, cfg, bootstrap, new_node)
            )
        # durability order preserved: every bootstrap record is on disk
        # before any of these nodes writes raft state
        if boots:
            self.logdb.save_bootstrap_infos(boots)
        for initial_members, join, sm_factory, cfg, bootstrap, new in prepared:
            self._launch_node(
                initial_members, join, sm_factory, cfg, bootstrap, new
            )

    def _launch_node(
        self, initial_members, join, sm_factory, cfg, bootstrap, new_node
    ) -> None:
        cluster_id, node_id = cfg.cluster_id, cfg.node_id
        addresses = bootstrap.addresses if not join else {}
        peer_addresses = [
            PeerAddress(node_id=nid, address=addr)
            for nid, addr in sorted(addresses.items())
        ]
        for nid, addr in addresses.items():
            self.transport.nodes.add_node(cluster_id, nid, addr)
        log_reader = LogReader(cluster_id, node_id, self.logdb)
        snapshotter = Snapshotter(
            self.snapshot_dir_root(), cluster_id, node_id, self.logdb
        )
        # restart path: position the window from snapshot + persisted log
        # BEFORE the protocol core launches and reads it (node.go:553-583)
        ss = snapshotter.get_most_recent_snapshot()
        if not new_node or (ss is not None and not ss.is_empty()):
            log_reader.load(ss)
        if self.config.engine.kind == "vector":
            from .engine.vector import VectorNode

            node_cls = VectorNode
        else:
            node_cls = Node
        node = node_cls(
            cfg,
            peer_addresses,
            initial=bool(initial_members) and new_node,
            new_node=new_node,
            sm_factory=sm_factory,
            log_reader=log_reader,
            logdb=self.logdb,
            snapshotter=snapshotter,
            send_message=self._send_message,
            send_messages=self._send_messages,
            engine=self.engine,
            event_listener=self._event_aggregator,
            register_peer=self._register_peer_address,
        )
        with self._nodes_mu:
            self._nodes[cluster_id] = node
            self._launch_specs[cluster_id] = (
                initial_members, join, sm_factory, cfg,
            )
        # initial-snapshot recovery runs HERE, on the control-plane
        # thread, BEFORE the engine sees the node: the vector engine's
        # lane activation otherwise runs it on the step-loop thread, and
        # a seconds-long SM restore (restart with a big image) would
        # stall every co-hosted lane's step cadence — the monolithic-
        # install stall the streamed-install plane exists to prevent.
        # (The activation path keeps its own idempotent call as the
        # race fallback.)
        node.recover_initial_snapshot()
        self.engine.add_node(node)

    def _bootstrap_cluster(
        self, initial_members, join, cfg: Config, smtype: int
    ):
        """cf. nodehost.go:1445-1474 bootstrapCluster."""
        bootstrap, new_node = self._peek_bootstrap(
            initial_members, join, cfg, smtype
        )
        if new_node:
            self.logdb.save_bootstrap_info(
                cfg.cluster_id, cfg.node_id, bootstrap
            )
        return bootstrap, new_node

    def _peek_bootstrap(self, initial_members, join, cfg: Config, smtype: int):
        """Validate + build the bootstrap record WITHOUT persisting it (the
        bulk path persists many records in one batch)."""
        cluster_id, node_id = cfg.cluster_id, cfg.node_id
        try:
            bootstrap = self.logdb.get_bootstrap_info(cluster_id, node_id)
            if not bootstrap.validate(initial_members or {}, join, smtype):
                raise ErrInvalidClusterSettings()
            return bootstrap, False
        except ErrNoBootstrapInfo:
            pass
        members = {} if join else dict(initial_members or {})
        if not join and cfg.is_witness is False and cfg.is_observer is False:
            if not members:
                raise ErrInvalidClusterSettings()
        bootstrap = Bootstrap(addresses=members, join=join, type=smtype)
        return bootstrap, True

    def stop_cluster(self, cluster_id: int) -> None:
        """Graceful detach of one cluster node (cf. nodehost.go
        StopCluster): the engine stops stepping it, its lane/worker
        registration drains fully (drain barrier) so the slot is
        immediately reusable, pending requests terminate, and the launch
        spec is KEPT — restart_cluster() rejoins from the durable state."""
        self._detach_cluster(cluster_id, crashed=False)

    def crash_cluster(self, cluster_id: int) -> None:
        """SIGKILL-equivalent teardown of ONE cluster node: no graceful
        handoff — staged proposals and in-flight snapshot work are
        abandoned, pending requests terminate like a reset connection,
        and nothing beyond past save waves is made durable. The node's
        engine lane is reaped for reuse; restart_cluster() later re-runs
        WAL recovery and rejoins the live group (log replay from the
        leader, or snapshot install when the log has been compacted past
        this node's index). The host's OTHER clusters keep running — use
        NodeHost.crash() for whole-process death semantics (incl. the
        skipped WAL barrier and torn-tail injection)."""
        self._detach_cluster(cluster_id, crashed=True)

    def _detach_cluster(self, cluster_id: int, crashed: bool) -> None:
        with self._nodes_mu:
            node = self._nodes.pop(cluster_id, None)
        if node is None:
            raise ErrClusterNotFound()
        flight_recorder().record(
            "node_crashed" if crashed else "cluster_stopped",
            cluster=cluster_id, host=self.config.raft_address,
        )
        if crashed:
            # abrupt: stop accepting + terminate waiters FIRST, so the
            # engine's in-flight step observes a dead node (skips sends/
            # task handoff) rather than a live one being unplugged
            node.close()
            self.engine.remove_node(cluster_id)
        else:
            self.engine.remove_node(cluster_id)
            node.close()
        # ordering barrier: the freed lane must be on the engine's free
        # list before this returns, or an immediate restart_cluster could
        # fail on its own predecessor's not-yet-reaped lane
        drain = getattr(self.engine, "drain", None)
        if drain is not None:
            drain()

    def restart_cluster(self, cluster_id: int) -> None:
        """Relaunch a stopped/crashed cluster node IN PROCESS from its
        durable state: re-runs WAL recovery (bootstrap record + persisted
        raft state + most recent snapshot, exactly the restart path a new
        process takes), rebuilds the engine lane from the recovered
        state, and rejoins the live group — the leader replays log from
        its window, or streams a snapshot when compaction has passed this
        node's index. Uses the launch spec recorded by start_cluster;
        raises ErrClusterNotFound if this host never started the cluster,
        ErrClusterAlreadyExist if it is still running."""
        if self._stopped.is_set():
            raise ErrClusterClosed()
        with self._nodes_mu:
            if cluster_id in self._nodes:
                raise ErrClusterAlreadyExist()
            spec = self._launch_specs.get(cluster_id)
        if spec is None:
            raise ErrClusterNotFound()
        initial_members, join, sm_factory, cfg = spec
        flight_recorder().record(
            "cluster_restarted", cluster=cluster_id,
            host=self.config.raft_address,
        )
        self.start_cluster(initial_members, join, sm_factory, cfg)

    def _register_peer_address(
        self, cluster_id: int, node_id: int, address: str
    ) -> None:
        """Replicated-state address registration (Node.apply_config_change
        / membership_loaded): an applied ADD_* change or a restored
        snapshot membership names a member's address — record it so THIS
        host can route to the member no matter which host requested the
        change (live migration depends on it: the swapped-in member must
        stay reachable after the adding host leaves the group)."""
        self.transport.nodes.add_node(cluster_id, node_id, address)

    def has_node(self, cluster_id: int) -> bool:
        with self._nodes_mu:
            return cluster_id in self._nodes

    def _get_node(self, cluster_id: int) -> Node:
        with self._nodes_mu:
            node = self._nodes.get(cluster_id)
        if node is None:
            raise ErrClusterNotFound()
        return node

    # ------------------------------------------------------- time conversion
    def _to_ticks(self, timeout_s: float) -> int:
        return max(1, int(timeout_s * 1000 / self._tick_ms))

    # ---------------------------------------------------------------- writes
    def propose(
        self, session: Session, cmd: bytes, timeout_s: float
    ) -> RequestState:
        node = self._get_node(session.cluster_id)
        return node.propose(session, cmd, self._to_ticks(timeout_s))

    def propose_batch(
        self, session: Session, cmds, timeout_s: float
    ) -> List[RequestState]:
        """Pipelined submission: many proposals, one registry/queue lock
        round-trip and one engine wake-up (no-op sessions only — see
        Node.propose_batch). The engines ingest, replicate, persist and
        apply in batches already; this extends the batching to the
        client boundary."""
        node = self._get_node(session.cluster_id)
        return node.propose_batch(session, cmds, self._to_ticks(timeout_s))

    def propose_batch_async(
        self, session: Session, cmds, timeout_s: float
    ):
        """Fire-and-collect batch submission: returns ONE BatchRequestState
        whose event fires when every proposal in the batch has applied or
        timed out. Two orders of magnitude fewer Python objects than
        per-proposal RequestStates — the API for pipelined bulk writers."""
        node = self._get_node(session.cluster_id)
        return node.propose_batch_async(
            session, cmds, self._to_ticks(timeout_s)
        )

    def sync_propose(
        self, session: Session, cmd: bytes, timeout_s: float = 4.0
    ) -> Result:
        """cf. nodehost.go:514 SyncPropose."""
        rs = self.propose(session, cmd, timeout_s)
        r = rs.wait(timeout_s + 1.0)
        return self._unwrap(r)

    def _unwrap(self, r: RequestResult):
        if r.completed:
            return r.result
        if r.timeout:
            raise ErrTimeout()
        if r.rejected:
            raise ErrRejected()
        if r.terminated:
            raise ErrClusterClosed()
        raise ErrClusterNotReady()  # dropped

    # ----------------------------------------------------------------- reads
    def read_index(self, cluster_id: int, timeout_s: float) -> RequestState:
        node = self._get_node(cluster_id)
        return node.read(self._to_ticks(timeout_s))

    def sync_read(self, cluster_id: int, query, timeout_s: float = 4.0):
        """Linearizable read (cf. nodehost.go:539 SyncRead)."""
        rs = self.read_index(cluster_id, timeout_s)
        r = rs.wait(timeout_s + 1.0)
        self._unwrap(r)
        return self.read_local_node(cluster_id, query)

    def read_local_node(self, cluster_id: int, query):
        """Must only be called after a successful read_index round
        (cf. nodehost.go:808-820)."""
        node = self._get_node(cluster_id)
        return node.sm.lookup(query)

    def lease_read(self, cluster_id: int, query, timeout_s: float = 4.0):
        """Lease-ONLY linearizable read probe: raises ErrLeaseExpired
        immediately unless this host's replica holds a live leader lease
        (latency-SLO callers that would rather retry elsewhere than pay
        a quorum round). This is the one API that surfaces lease loss as
        an error — sync_read never does; with Config.lease_read on it
        serves off the lease when valid and silently degrades to the
        ReadIndex quorum path when not. If the lease lapses between the
        probe and the serve, the read degrades too: the outcome is
        always linearizable, only the latency contract is lease-only."""
        node = self._get_node(cluster_id)
        valid = getattr(self.engine, "lease_valid", None)
        if valid is None or not valid(cluster_id):
            raise ErrLeaseExpired(
                retry_after_s=self._tick_ms / 1000.0,
                reason="no live leader lease on this replica",
            )
        rs = node.read(self._to_ticks(timeout_s))
        r = rs.wait(timeout_s + 1.0)
        self._unwrap(r)
        return self.read_local_node(cluster_id, query)

    def stale_read(self, cluster_id: int, query):
        node = self._get_node(cluster_id)
        return node.sm.lookup(query)

    # --------------------------------------------------------- serving front
    def serving_front(self, admission=None, front=None):
        """The overload-robust ingress for this host (serving/front.py):
        per-tenant admission control + weighted-fair fan-in onto the
        batched propose path, fed by this host's live backpressure
        signals. Created lazily, ONE per host (the first call's knobs
        win); stop() tears it down with the host. Its per-tenant
        admit/shed/latency ledger exports through write_health_metrics
        alongside every other gauge."""
        with self._serving_mu:
            if self._serving is None:
                from .serving import ServingFront

                self._serving = ServingFront(
                    self, admission=admission, front=front
                )
            return self._serving

    def placement_plane(self, targets=None, config=None):
        """This host's load-aware placement brain (serving/placement.py):
        folds the saturation score, per-lane gauges and per-tenant
        serving histograms into a load model and live-migrates hot
        groups (leadership transfer + streamed-snapshot member swap) to
        the given MigrationTargets. Created lazily, ONE per host (the
        first call's targets/config win); torn down with the host. Its
        migration ledger exports through write_health_metrics."""
        # resolve the front FIRST: serving_front() takes _serving_mu too
        # (non-reentrant), and the plane's constructor needs it
        front = self.serving_front()
        with self._serving_mu:
            if self._placement is None:
                from .serving import PlacementPlane

                self._placement = PlacementPlane(
                    self, targets or [], config=config, front=front
                )
            return self._placement

    def mark_migrating(self, cluster_id: int, active: bool) -> None:
        """Tag/untag a cluster as mid live-migration on this host (both
        the source and the join target get marked): the inbound snapshot
        chunk tracker counts streams for marked clusters as MIGRATION
        streams, so the bench/longhaul ledgers can tell a migration's
        install traffic from ordinary catch-up."""
        with self._nodes_mu:
            if active:
                self._migrating.add(cluster_id)
            else:
                self._migrating.discard(cluster_id)

    def is_migrating(self, cluster_id: int) -> bool:
        with self._nodes_mu:
            return cluster_id in self._migrating

    def local_node_id(self, cluster_id: int) -> int:
        """The node id THIS host runs for the cluster (placement needs
        to know which member is 'here' before it can move it away)."""
        return self._get_node(cluster_id).node_id()

    def ingress_fill(self) -> float:
        """Worst incoming-proposal/read queue fill across this host's
        groups, in [0, 1] — the request-pool backpressure signal the
        serving front's SaturationMonitor folds into admission (a full
        queue here is the ErrSystemBusy raise site one add() later).
        Lock-free queue probes; a torn read costs one stale sample."""
        with self._nodes_mu:
            nodes = list(self._nodes.values())
        fill = 0.0
        for node in nodes:
            fill = max(
                fill,
                node.incoming_proposals.fill(),
                node.incoming_reads.fill(),
            )
        return fill

    def notify_group_admission(self, cluster_id: int) -> bool:
        """Serving-front first-admit wake (engine/quiesce.py contract):
        returns True when the group was idle-quiesced and is being woken
        ahead of the admitted op reaching the step loop. Unknown groups
        are a no-op — admission must not fail before the real propose
        path gets to say ErrClusterNotFound itself."""
        with self._nodes_mu:
            node = self._nodes.get(cluster_id)
        if node is None:
            return False
        return node.notify_admission()

    # -------------------------------------------------------------- sessions
    def get_noop_session(self, cluster_id: int) -> Session:
        return Session.noop_session(cluster_id)

    def sync_get_session(self, cluster_id: int, timeout_s: float = 4.0) -> Session:
        """Register a client session (cf. nodehost.go SyncGetSession)."""
        s = Session.new_session(cluster_id)
        s.prepare_for_register()
        self._sync_session_op(s, timeout_s)
        s.prepare_for_propose()
        return s

    def sync_close_session(self, session: Session, timeout_s: float = 4.0) -> None:
        session.prepare_for_unregister()
        self._sync_session_op(session, timeout_s)

    def _sync_session_op(self, session: Session, timeout_s: float) -> None:
        node = self._get_node(session.cluster_id)
        rs = node.propose(session, b"", self._to_ticks(timeout_s))
        result = self._unwrap(rs.wait(timeout_s + 1.0))
        if result.value != session.client_id:
            raise ErrRejected()

    # ------------------------------------------------------------ membership
    def request_add_node(
        self, cluster_id: int, node_id: int, address: str, cc_id: int = 0,
        timeout_s: float = 4.0,
    ) -> RequestState:
        return self._request_config_change(
            cluster_id, ConfigChangeType.ADD_NODE, node_id, address, cc_id, timeout_s
        )

    def request_delete_node(
        self, cluster_id: int, node_id: int, cc_id: int = 0, timeout_s: float = 4.0
    ) -> RequestState:
        return self._request_config_change(
            cluster_id, ConfigChangeType.REMOVE_NODE, node_id, "", cc_id, timeout_s
        )

    def request_add_observer(
        self, cluster_id, node_id, address, cc_id=0, timeout_s=4.0
    ) -> RequestState:
        return self._request_config_change(
            cluster_id, ConfigChangeType.ADD_OBSERVER, node_id, address, cc_id,
            timeout_s,
        )

    def request_add_witness(
        self, cluster_id, node_id, address, cc_id=0, timeout_s=4.0
    ) -> RequestState:
        return self._request_config_change(
            cluster_id, ConfigChangeType.ADD_WITNESS, node_id, address, cc_id,
            timeout_s,
        )

    def _request_config_change(
        self, cluster_id, cctype, node_id, address, cc_id, timeout_s
    ) -> RequestState:
        node = self._get_node(cluster_id)
        cc = ConfigChange(
            config_change_id=cc_id, type=cctype, node_id=node_id, address=address
        )
        flight_recorder().record(
            "config_change_requested", cluster=cluster_id,
            kind=cctype.name, target=node_id, host=self.config.raft_address,
        )
        if address:
            self.transport.nodes.add_node(cluster_id, node_id, address)
        return node.request_config_change(cc, self._to_ticks(timeout_s))

    def sync_request_add_node(self, cluster_id, node_id, address, cc_id=0,
                              timeout_s=4.0) -> None:
        rs = self.request_add_node(cluster_id, node_id, address, cc_id, timeout_s)
        self._unwrap(rs.wait(timeout_s + 1.0))

    def sync_request_delete_node(self, cluster_id, node_id, cc_id=0,
                                 timeout_s=4.0) -> None:
        rs = self.request_delete_node(cluster_id, node_id, cc_id, timeout_s)
        self._unwrap(rs.wait(timeout_s + 1.0))

    def sync_request_add_observer(self, cluster_id, node_id, address, cc_id=0,
                                  timeout_s=4.0) -> None:
        rs = self.request_add_observer(cluster_id, node_id, address, cc_id, timeout_s)
        self._unwrap(rs.wait(timeout_s + 1.0))

    def sync_request_add_witness(self, cluster_id, node_id, address, cc_id=0,
                                 timeout_s=4.0) -> None:
        rs = self.request_add_witness(cluster_id, node_id, address, cc_id, timeout_s)
        self._unwrap(rs.wait(timeout_s + 1.0))

    def get_cluster_membership(self, cluster_id: int) -> Membership:
        node = self._get_node(cluster_id)
        return node.sm.get_membership()

    # ---------------------------------------------------- leadership / status
    def get_leader_id(self, cluster_id: int):
        """Returns (leader_node_id, has_leader)."""
        node = self._get_node(cluster_id)
        lid = node.get_leader_id()
        return lid, lid != 0

    def request_leader_transfer(self, cluster_id: int, target_node_id: int) -> None:
        node = self._get_node(cluster_id)
        node.request_leader_transfer(target_node_id)

    def request_snapshot(
        self, cluster_id: int, export_path: str = "", compaction_overhead: int = 0,
        timeout_s: float = 10.0,
    ) -> RequestState:
        """cf. nodehost.go:877-949 RequestSnapshot (incl. exported)."""
        if export_path and not os.path.isdir(export_path):
            # fail fast before any snapshot work (cf. nodehost.go:905
            # ErrDirNotExist)
            raise ErrDirNotExist(export_path)
        node = self._get_node(cluster_id)
        req = SSRequest(
            type=SS_REQ_EXPORTED if export_path else SS_REQ_USER,
            path=export_path,
            override_compaction=compaction_overhead > 0,
            compaction_overhead=compaction_overhead,
        )
        flight_recorder().record(
            "snapshot_requested", cluster=cluster_id,
            exported=bool(export_path), host=self.config.raft_address,
        )
        return node.request_snapshot(req, self._to_ticks(timeout_s))

    def sync_request_snapshot(self, cluster_id: int, export_path: str = "",
                              timeout_s: float = 10.0) -> int:
        rs = self.request_snapshot(cluster_id, export_path, timeout_s=timeout_s)
        r = rs.wait(timeout_s + 1.0)
        if r.completed:
            return r.snapshot_index
        self._unwrap(r)

    def get_nodehost_info(self, skip_log_info: bool = False) -> NodeHostInfo:
        """cf. nodehost.go:1289-1302 GetNodeHostInfo."""
        out = []
        with self._nodes_mu:
            nodes = list(self._nodes.values())
        for n in nodes:
            st = n.local_status()
            m = n.sm.get_membership()
            out.append(
                ClusterInfo(
                    cluster_id=n.cluster_id,
                    node_id=n.node_id(),
                    nodes=dict(m.addresses),
                    config_change_index=m.config_change_id,
                    is_leader=st["leader_id"] == n.node_id(),
                )
            )
        log_info = [] if skip_log_info else self.logdb.list_node_info()
        return NodeHostInfo(
            raft_address=self.raft_address(),
            cluster_info=out,
            log_info=log_info,
        )

    # -------------------------------------------------------- RTT probing
    def ping_peers(self, cluster_id: Optional[int] = None) -> int:
        """Send Ping probes (cf. nodehost.go:2069-2088 sendPingMessage) to
        every remote member of the given cluster (or all local clusters).
        Pongs echo the monotonic timestamp; RTT samples land in
        get_rtt_samples() and the transport_ping_rtt_us metric. Returns
        the number of probes sent."""
        if self._partitioned:
            return 0  # probes are raft traffic too (monkey.go semantics)
        with self._nodes_mu:
            if cluster_id is not None:
                node = self._nodes.get(cluster_id)
                nodes = [node] if node is not None else []
            else:
                nodes = list(self._nodes.values())
        sent = 0
        now_us = time.monotonic_ns() // 1000
        for n in nodes:
            try:
                members = n.sm.get_membership().addresses
            except Exception:
                continue
            for nid in members:
                if nid == n.node_id():
                    continue
                # deliberately NOT the co-hosted shortcut: the probe
                # measures the WIRE path (a shared-core peer would answer
                # from the inbox and report zero while the NIC is dead)
                if self.transport.send(
                    Message(
                        type=MessageType.PING,
                        cluster_id=n.cluster_id,
                        to=nid,
                        from_=n.node_id(),
                        hint=now_us,
                    )
                ):
                    sent += 1
        return sent

    def get_rtt_samples(self) -> Dict[tuple, List[int]]:
        """(cluster_id, peer_node_id) -> recent RTT samples in microseconds."""
        with self._rtt_mu:
            return {k: list(v) for k, v in self._rtt.items()}

    def _record_pong(self, m: Message) -> None:
        rtt_us = max(0, time.monotonic_ns() // 1000 - m.hint)
        key = (m.cluster_id, m.from_)
        with self._rtt_mu:
            dq = self._rtt.get(key)
            if dq is None:
                from collections import deque

                dq = self._rtt[key] = deque(maxlen=16)
            dq.append(rtt_us)
        self.metrics.set_gauge("transport_ping_rtt_us", key, float(rtt_us))

    # ----------------------------------------------------- chaos-test knobs
    # cf. monkey.go:90-198 (build-tag-gated in the reference; here plain
    # methods — they cost nothing unless used)
    def set_partitioned(self, partitioned: bool) -> None:
        """Partition mode: drop ALL inbound and outbound raft traffic
        (cf. monkey.go:169-198)."""
        flight_recorder().record(
            "partition_set", host=self.config.raft_address,
            partitioned=partitioned,
        )
        self._partitioned = partitioned
        # co-hosted delivery bypasses the transport, so the engine core
        # must drop inbound traffic for this host too
        gate = getattr(self.engine, "set_host_partitioned", None)
        if gate is not None:
            gate(partitioned)

    def is_partitioned(self) -> bool:
        return self._partitioned

    def get_sm_hash(self, cluster_id: int) -> int:
        """Content digest of the node's SM for cross-replica equality checks
        (cf. monkey.go:90-142)."""
        return self._get_node(cluster_id).sm.get_hash()

    def get_session_hash(self, cluster_id: int) -> int:
        return self._get_node(cluster_id).sm.get_session_hash()

    def get_membership_hash(self, cluster_id: int) -> int:
        return self._get_node(cluster_id).sm.get_membership_hash()

    def get_applied_index(self, cluster_id: int) -> int:
        return self._get_node(cluster_id).sm.last_applied_index()

    # ------------------------------------------------------------- transport
    def _send_message(self, m: Message) -> None:
        if self._partitioned:
            return
        if m.type == MessageType.INSTALL_SNAPSHOT:
            self._async_send_snapshot(m)
            return
        # co-hosted short-circuit: replicas living on this process's engine
        # core receive directly (no codec, no transport thread); anything
        # else rides the wire
        deliver = getattr(self.engine, "try_local_deliver", None)
        if deliver is not None and deliver(m):
            return
        self.transport.send(m)

    def _send_messages(self, msgs) -> None:
        """Bulk send: one co-hosted delivery pass (grouped per destination
        lane, one queue lock + one wake per lane) and one grouped
        transport.send_many for whatever must ride the wire. The engine's
        columnar fan-out emits each step's messages through this seam
        instead of per-message _send_message calls."""
        if self._partitioned:
            return
        wire = []
        for m in msgs:
            if m.type == MessageType.INSTALL_SNAPSHOT:
                self._async_send_snapshot(m)
            else:
                wire.append(m)
        deliver_many = getattr(self.engine, "try_local_deliver_many", None)
        if deliver_many is not None:
            wire = deliver_many(wire)
        if not wire:
            return
        send_many = getattr(self.transport, "send_many", None)
        if send_many is not None:
            send_many(wire)
        else:
            for m in wire:
                self.transport.send(m)

    def _on_snapshot_stream_aborted(
        self, cluster_id: int, node_id: int, from_: int, reason: str
    ) -> None:
        """Inbound install stream died (Chunks._drop): open the receiving
        node's fail-fast window so client ops gated on the install get the
        typed ErrSnapshotStreamAborted (+ retry-after hint) instead of a
        generic timeout. The hint is the raft snapshot-status retry
        cadence — when the sender's re-streamed install should have
        landed (cf. feedback.go:38-128 / VectorEngine._run_snapshot_feedback)."""
        with self._nodes_mu:
            node = self._nodes.get(cluster_id)
        if node is None or node.node_id() != node_id:
            return
        retry_ticks = max(4 * node.config.election_rtt, 16)
        node.notify_install_aborted(retry_ticks * self._tick_ms / 1000.0)

    def _recv_chunk(self, chunk) -> bool:
        """Inbound chunk sink with the receive-side bandwidth cap: the
        throttle sleeps the transport's delivery thread, back-pressuring
        the sender's stream naturally."""
        if self._snap_recv_rate is not None:
            self._snap_recv_rate.acquire(getattr(chunk, "chunk_size", 0))
        return self._chunks.add_chunk(chunk)

    def _try_admit_lane(self, addr: str) -> bool:
        with self._lane_mu:
            per = self._lanes_by_target.get(addr, 0)
            if (
                self._lanes_total >= self._max_lanes
                or per >= self._max_lanes_per_target
            ):
                return False
            self._lanes_total += 1
            self._lanes_by_target[addr] = per + 1
        return True

    def _release_lane(self, addr: str) -> None:
        with self._lane_mu:
            self._lanes_total = max(0, self._lanes_total - 1)
            per = self._lanes_by_target.get(addr, 1) - 1
            if per <= 0:
                self._lanes_by_target.pop(addr, None)
            else:
                self._lanes_by_target[addr] = per

    def _async_send_snapshot(self, m: Message) -> None:
        """Stream a snapshot to a lagging peer on a dedicated lane
        (cf. nodehost.go:1724-1744 + transport snapshot.go:55-110), subject
        to the total and per-target lane caps."""
        from .transport.snapshotstream import SnapshotLane

        addr = self.transport.nodes.resolve(m.cluster_id, m.to)
        if addr is None:
            self._report_snapshot_status(m.cluster_id, m.to, True)
            return
        if not self._try_admit_lane(addr):
            # over the cap: fail fast through the status-feedback path (the
            # raft core retries after its snapshot-status window) instead
            # of parking an unbounded thread on a slow sink
            self._report_snapshot_status(m.cluster_id, m.to, True)
            return
        try:
            try:
                ss_state = self._get_node(m.cluster_id).ss
                ss_state.begin_stream()
            except Exception:
                ss_state = None

            def on_done(cluster_id: int, to: int, failed: bool) -> None:
                if ss_state is not None:
                    ss_state.end_stream()
                self._report_snapshot_status(cluster_id, to, failed)

            lane = SnapshotLane(
                self.transport, addr, m, on_done,
                release=lambda: self._release_lane(addr),
                rate_limiter=self._snap_send_rate,
            )
            lane.start()
        except Exception:
            # thread exhaustion etc.: the admitted slot must not leak —
            # a few leaks would permanently fail-fast this target
            self._release_lane(addr)
            self._report_snapshot_status(m.cluster_id, m.to, True)

    def _report_snapshot_status(self, cluster_id: int, node_id: int, failed: bool):
        # status lands in the sender's own raft (remote leaves Snapshot state)
        self.handle_snapshot_status(cluster_id, node_id, failed)

    def handle_message_batch(self, batch) -> None:
        """Inbound traffic (cf. nodehost.go:1978-2026)."""
        if self._partitioned:
            return 0, 0
        snapshot_count = msg_count = 0
        for m in batch.requests:
            if m.type == MessageType.SNAPSHOT_RECEIVED:
                self._on_snapshot_received(m)
                continue
            if m.type == MessageType.PING:
                # transport-level RTT probe: echo without raft involvement
                # (cf. nodehost.go:1759-1773 handlePingMessage)
                self.transport.send(
                    Message(
                        type=MessageType.PONG,
                        cluster_id=m.cluster_id,
                        to=m.from_,
                        from_=m.to,
                        hint=m.hint,
                    )
                )
                continue
            if m.type == MessageType.PONG:
                self._record_pong(m)
                continue
            with self._nodes_mu:
                node = self._nodes.get(m.cluster_id)
            if node is None:
                continue
            if m.to != node.node_id():
                continue
            if m.type == MessageType.INSTALL_SNAPSHOT:
                if node.mq.add_snapshot(m):
                    snapshot_count += 1
            else:
                if node.mq.add(m):
                    msg_count += 1
            self.engine.set_node_ready(m.cluster_id)
        return snapshot_count, msg_count

    def handle_unreachable(self, cluster_id: int, node_id: int) -> None:
        with self._nodes_mu:
            node = self._nodes.get(cluster_id)
        if node is None:
            return
        node.mq.add(
            Message(
                type=MessageType.UNREACHABLE, cluster_id=cluster_id, from_=node_id
            )
        )
        self.engine.set_node_ready(cluster_id)

    def handle_snapshot_status(self, cluster_id: int, node_id: int, failed: bool):
        with self._nodes_mu:
            node = self._nodes.get(cluster_id)
        if node is None:
            return
        node.mq.add(
            Message(
                type=MessageType.SNAPSHOT_STATUS,
                cluster_id=cluster_id,
                from_=node_id,
                reject=failed,
            )
        )
        self.engine.set_node_ready(cluster_id)

    def handle_snapshot(self, cluster_id: int, node_id: int, from_: int) -> None:
        """A snapshot finished arriving: ack the sender
        (cf. nodehost.go:2057-2067)."""
        self.transport.send(
            Message(
                type=MessageType.SNAPSHOT_RECEIVED,
                cluster_id=cluster_id,
                to=from_,
                from_=node_id,
            )
        )

    def _on_snapshot_received(self, m: Message) -> None:
        self.handle_snapshot_status(m.cluster_id, m.from_, False)

    # ------------------------------------------------------------- tick loop
    def set_tick_clock(self, clock: Optional[Callable[[], float]]) -> None:
        """Mount an injectable tick clock (faults.ClockPlane.clock_fn) —
        or None to return to real monotonic time. The tick worker picks
        the new clock up on its next iteration and re-anchors, so a
        mount is never itself misread as a jump."""
        self._tick_clock = clock or time.monotonic

    def _on_clock_anomaly(self, hold_s: float) -> None:
        """The tick clock read backward or diverged from real monotonic
        elapsed — a clock fault, not a scheduling stall (a stall
        advances both clocks equally). The caller sheds the phantom tick
        backlog (no burst replay past the clamp); here we keep the
        fairness gauge honest and put leases on suspect hold so reads
        degrade to ReadIndex instead of trusting a lying clock."""
        self._clock_anomalies += 1
        wd = getattr(self.engine, "watchdog", None)
        if wd is not None:
            try:
                wd.note_clock_anomaly()
            except Exception:
                pass
        suspect = getattr(self.engine, "set_clock_suspect", None)
        if suspect is not None:
            try:
                suspect(hold_s)
            except Exception:
                pass

    def _tick_worker_main(self) -> None:
        """cf. nodehost.go:1668-1684 tickWorkerMain."""
        period = self._tick_ms / 1000.0
        # a tick-clock reading that diverges from REAL monotonic elapsed
        # by more than this (since the last anchor) is a clock fault;
        # divergence below it replays as a bounded, clamp-safe backlog
        divergence_limit = max(8 * period, 0.05)
        # lease-suspect hold after an anomaly: comfortably past one
        # election RTT at default tick rates, so a healed clock must
        # re-earn its lease with a full quorum round
        suspect_hold_s = max(0.25, 32 * period)
        clock = self._tick_clock
        anchor_real = time.monotonic()
        anchor_fault = clock()
        next_t = anchor_fault + period
        next_gauges_t = anchor_fault + 1.0
        last_now = anchor_fault
        while not self._stopped.is_set():
            if clock is not self._tick_clock:
                # live (un)mount: re-anchor, never misread as a jump
                clock = self._tick_clock
                anchor_real = time.monotonic()
                anchor_fault = clock()
                next_t = anchor_fault + period
                last_now = anchor_fault
            now = clock()
            if clock is not time.monotonic:
                real = time.monotonic()
                div = (now - anchor_fault) - (real - anchor_real)
                if now < last_now or abs(div) > divergence_limit:
                    self._on_clock_anomaly(suspect_hold_s)
                    anchor_real, anchor_fault = real, now
                    next_t = now + period  # resync: shed phantom backlog
                    next_gauges_t = min(next_gauges_t, now + 1.0)
                    last_now = now
                    continue
            last_now = now
            if now >= next_gauges_t:
                next_gauges_t = now + 1.0
                try:
                    self._export_health_gauges()
                except Exception:
                    pass  # gauge export must never kill the tick loop
            if now < next_t:
                time.sleep(min(period, next_t - now))
                continue
            # catch-up ticks are coalesced by the MessageQueue counter
            # (scalar engine) or the engine-global tick counter (vector
            # engine: one increment covers every lane, no per-node work)
            global_tick = getattr(self.engine, "global_tick", None)
            while next_t <= now:
                next_t += period
                if global_tick is not None:
                    global_tick()
                else:
                    with self._nodes_mu:
                        nodes = list(self._nodes.values())
                    for n in nodes:
                        n.mq.add(Message(type=MessageType.LOCAL_TICK))
                        self.engine.set_node_ready(n.cluster_id)
                self._chunks.tick()  # abandoned inbound stream GC

    def _export_health_gauges(self) -> None:
        """Refresh host-level gauges (label key (0, 0)) in the
        MetricsRegistry: the engine's tick-fairness watchdog and the
        transport's breaker/queue state. Runs ~1/s on the tick thread so
        the Prometheus exposition (write_health_metrics) always carries a
        recent starvation/backpressure picture."""
        fairness = getattr(self.engine, "fairness_stats", None)
        if fairness is not None:
            s = fairness()
            key = (0, 0)
            self.metrics.set_gauge(
                "engine_tick_starvation_ratio", key, s["starvation_ratio"]
            )
            self.metrics.set_gauge(
                "engine_tick_gap_max_seconds", key, s["recent_max_gap_s"]
            )
            self.metrics.set_gauge(
                "engine_fairness_yields", key, s["fairness_yields"]
            )
            self.metrics.set_gauge(
                "engine_tick_bursts_clamped", key, s["tick_bursts_clamped"]
            )
        tm = self.transport.metrics()
        for name in (
            "breakers_open",
            "breaker_probe_failures",
            "dropped_while_open",
            "queue_evicted_bulk",
            "queue_dropped_bulk",
            "queue_dropped_urgent",
            "queued_urgent",
            "queued_bulk",
        ):
            if name in tm:
                self.metrics.set_gauge(f"transport_{name}", (0, 0), tm[name])
        # vector-engine per-step columnar counters (messages by plane,
        # commit-advancing lanes, elections, applied entries) — derived
        # host-side from decoded StepOutput, no device syncs to read
        step_stats = getattr(self.engine, "step_stats", None)
        if step_stats is not None:
            for name, v in step_stats().items():
                self.metrics.set_gauge(f"engine_step_{name}", (0, 0), float(v))
        # runtime device-sync / retrace audit (profile.py): total and
        # out-of-seam transfer counts plus XLA compile events, so a stray
        # sync or steady-state retrace is visible on the same dashboard
        # that watches throughput (counter semantics, exported 1/s)
        sa = sync_audit().snapshot()
        self.metrics.set_gauge(
            "engine_device_syncs_total", (0, 0),
            float(sa["in_seam"] + sa["out_of_seam"]),
        )
        self.metrics.set_gauge(
            "engine_device_syncs_out_of_seam", (0, 0),
            float(sa["out_of_seam"]),
        )
        # the multi-step engine's amortization ratio: protocol steps per
        # blessed _fetch_output/_fetch_super transfer (~1 classic, ~K
        # with steps_per_sync=K) — the honest denominator for the
        # zero-out-of-seam-per-step assertion at any K
        self.metrics.set_gauge(
            "engine_steps_per_sync", (0, 0),
            float(sa.get("steps_per_sync", 0.0)),
        )
        self.metrics.set_gauge(
            "engine_compile_events_total", (0, 0),
            float(compile_watch().total),
        )
        # history-sampler cost accounting: ALWAYS exported (zero-filled
        # when no sampler runs) so the engine_history_* schema is stable
        # and a dashboard can prove the sampler's overhead stayed noise
        sampler = self._history
        hs = (
            sampler.stats() if sampler is not None
            else HistorySampler.empty_stats()
        )
        for hname, v in hs.items():
            self.metrics.set_gauge(f"engine_history_{hname}", (0, 0), float(v))
        # HBM census: device-plane bytes + per-lane log fill vs the dense
        # widest-lane allocation (VectorEngine folds from its numpy
        # mirrors, the scalar engine reports an all-zero shape twin) —
        # the paged-arena sizing baseline on the live dashboard
        census = getattr(self.engine, "device_census", None)
        if census is not None:
            c = census()
            for gname, ckey in (
                ("engine_hbm_bytes_total", "hbm_bytes_total"),
                ("engine_hbm_log_bytes", "hbm_log_bytes"),
                ("engine_hbm_log_fill_p50", "log_fill_p50"),
                ("engine_hbm_log_fill_p99", "log_fill_p99"),
                ("engine_hbm_waste_ratio", "hbm_waste_ratio"),
            ):
                self.metrics.set_gauge(gname, (0, 0), float(c[ckey]))
        # protocol-event counter plane (ops/state.CTR): accumulated
        # on-device inside step_batch, decoded through the blessed fetch
        # seam — exporting is a numpy fold, never a device sync
        counter_stats = getattr(self.engine, "counter_stats", None)
        if counter_stats is not None:
            for name, v in counter_stats().items():
                self.metrics.set_gauge(
                    f"engine_counter_{name}", (0, 0), float(v)
                )
        # per-lane (cluster_id-labelled) introspection from the engine's
        # numpy mirrors: leader, term, commit gap, ticks since the last
        # leader change — zero device syncs (see VectorEngine.lane_stats)
        # serving-front overload plane: the per-tenant admit/shed/wake
        # ledger, queue depths and the folded saturation score (the
        # latency histograms are fed live by the completion callbacks)
        front = self._serving
        if front is not None:
            front.export_gauges(self.metrics)
        # placement plane: the migration ledger (started/completed/
        # aborted), same cadence as the serving gauges
        plane = self._placement
        if plane is not None:
            plane.export_gauges(self.metrics)
        lane_stats = getattr(self.engine, "lane_stats", None)
        if lane_stats is not None:
            for cid, s in lane_stats().items():
                key = (cid, s["node_id"])
                self.metrics.set_gauge(
                    "engine_lane_leader_id", key, float(s["leader_id"])
                )
                self.metrics.set_gauge(
                    "engine_lane_term", key, float(s["term"])
                )
                self.metrics.set_gauge(
                    "engine_lane_commit_gap", key, float(s["commit_gap"])
                )
                self.metrics.set_gauge(
                    "engine_lane_ticks_since_leader_change", key,
                    float(s["ticks_since_leader_change"]),
                )


__all__ = [
    "NodeHost",
    "ClusterInfo",
    "ErrClusterAlreadyExist",
    "ErrInvalidClusterSettings",
]
