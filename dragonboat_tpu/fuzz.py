"""In-tree mutation fuzzer for the wire codec, the TCP framing and the
WAL record-group decoder.

The reference ships go-fuzz harnesses for the first two surfaces —
entry/message unmarshal round-trips (raftpb/fuzz.go:15-49) and the framed
transport decoder (internal/transport/fuzz.go:68-77). Without network
egress or external fuzzers, this is a self-contained deterministic
harness: seeded generators produce valid wire objects, byte-level
mutators corrupt their encodings, and the decoders must either succeed
or raise a CONTROLLED error (CodecError / FrameError) — never crash,
hang, or attempt an unbounded allocation.

The WAL campaign (fuzz_wal_recovery / fuzz_wal_garbage) drives WalKV's
record-group replay: a log whose TAIL was mutated or truncated must
recover to the state after some PREFIX of committed record groups —
atomically per group, never crashing and never accepting a record whose
CRC/framing does not hold.

Run standalone for a timed campaign:
    python -m dragonboat_tpu.fuzz --seconds 30
CI runs a bounded iteration count through tests/test_fuzz.py.
"""
from __future__ import annotations

import argparse
import random
import time
from typing import List, Tuple

from . import codec
from .types import (
    Entry,
    EntryType,
    Membership,
    Message,
    MessageBatch,
    MessageType,
    Snapshot,
    SnapshotFile,
    State,
)

# every way a decoder is allowed to fail on corrupt input
ALLOWED_ERRORS = (codec.CodecError,)


def _rand_bytes(rng: random.Random, cap: int = 64) -> bytes:
    return rng.randbytes(rng.randrange(cap))


def _rand_entry(rng: random.Random) -> Entry:
    return Entry(
        type=rng.choice(list(EntryType)),
        index=rng.randrange(1 << 40),
        term=rng.randrange(1 << 30),
        key=rng.randrange(1 << 50),
        client_id=rng.randrange(1 << 50),
        series_id=rng.randrange(1 << 30),
        responded_to=rng.randrange(1 << 30),
        cmd=_rand_bytes(rng),
    )


def _rand_membership(rng: random.Random) -> Membership:
    return Membership(
        config_change_id=rng.randrange(1 << 30),
        addresses={
            rng.randrange(1, 64): f"h{rng.randrange(64)}:{rng.randrange(1, 65535)}"
            for _ in range(rng.randrange(4))
        },
        observers={rng.randrange(64, 96): "o:1" for _ in range(rng.randrange(2))},
        witnesses={rng.randrange(96, 128): "w:1" for _ in range(rng.randrange(2))},
        removed={rng.randrange(1 << 20): True for _ in range(rng.randrange(3))},
    )


def _rand_snapshot(rng: random.Random) -> Snapshot:
    return Snapshot(
        filepath=f"/snap/{rng.randrange(1 << 20)}",
        file_size=rng.randrange(1 << 40),
        index=rng.randrange(1 << 40),
        term=rng.randrange(1 << 30),
        cluster_id=rng.randrange(1 << 30),
        checksum=_rand_bytes(rng, 16),
        membership=_rand_membership(rng) if rng.random() < 0.8 else None,
        files=[
            SnapshotFile(
                file_id=rng.randrange(1 << 20),
                filepath=f"/f/{rng.randrange(100)}",
                file_size=rng.randrange(1 << 30),
                metadata=_rand_bytes(rng, 16),
            )
            for _ in range(rng.randrange(3))
        ],
        dummy=rng.random() < 0.1,
        witness=rng.random() < 0.1,
        imported=rng.random() < 0.1,
        on_disk_index=rng.randrange(1 << 30),
    )


def _rand_message(rng: random.Random) -> Message:
    return Message(
        type=rng.choice(list(MessageType)),
        to=rng.randrange(1 << 30),
        from_=rng.randrange(1 << 30),
        cluster_id=rng.randrange(1 << 40),
        term=rng.randrange(1 << 30),
        log_term=rng.randrange(1 << 30),
        log_index=rng.randrange(1 << 40),
        commit=rng.randrange(1 << 40),
        reject=rng.random() < 0.3,
        hint=rng.randrange(1 << 40),
        hint_high=rng.randrange(1 << 40),
        entries=[_rand_entry(rng) for _ in range(rng.randrange(4))],
        snapshot=_rand_snapshot(rng) if rng.random() < 0.2 else None,
    )


def _rand_batch(rng: random.Random) -> MessageBatch:
    return MessageBatch(
        deployment_id=rng.randrange(1 << 30),
        source_address=f"src{rng.randrange(100)}:1",
        bin_ver=rng.randrange(16),
        requests=[_rand_message(rng) for _ in range(rng.randrange(5))],
    )


def _mutate(rng: random.Random, data: bytes) -> bytes:
    """One random corruption: bit flip, byte splice, truncation, garbage
    insertion, or length-field-style overwrite."""
    if not data:
        return rng.randbytes(rng.randrange(1, 9))
    b = bytearray(data)
    op = rng.randrange(5)
    if op == 0:  # flip bits
        for _ in range(rng.randrange(1, 9)):
            i = rng.randrange(len(b))
            b[i] ^= 1 << rng.randrange(8)
    elif op == 1:  # truncate
        b = b[: rng.randrange(len(b))]
    elif op == 2:  # insert garbage
        i = rng.randrange(len(b) + 1)
        b[i:i] = rng.randbytes(rng.randrange(1, 17))
    elif op == 3:  # overwrite a run with 0xFF (inflates length prefixes)
        i = rng.randrange(len(b))
        n = min(rng.randrange(1, 9), len(b) - i)
        b[i : i + n] = b"\xff" * n
    else:  # duplicate a slice
        i = rng.randrange(len(b))
        j = min(len(b), i + rng.randrange(1, 33))
        b[i:i] = b[i:j]
    return bytes(b)


# ---------------------------------------------------------------------------
# campaigns
# ---------------------------------------------------------------------------


def fuzz_codec_roundtrip(rng: random.Random, iterations: int) -> int:
    """Valid objects must round-trip bit-exactly (fuzz.go:15-49 is the
    unmarshal-marshal echo check)."""
    n = 0
    for _ in range(iterations):
        b = _rand_batch(rng)
        data = codec.encode_message_batch(b)
        decoded, off = codec.decode_message_batch(data)
        assert off == len(data)
        again = codec.encode_message_batch(decoded)
        assert again == data, "round-trip mismatch"
        e = _rand_entry(rng)
        de, _ = codec.decode_entry(codec.encode_entry(e))
        assert codec.encode_entry(de) == codec.encode_entry(e)
        ss = _rand_snapshot(rng)
        dss, _ = codec.decode_snapshot(codec.encode_snapshot(ss))
        assert codec.encode_snapshot(dss) == codec.encode_snapshot(ss)
        n += 1
    return n


def fuzz_codec_mutations(rng: random.Random, iterations: int) -> int:
    """Corrupt encodings must decode-or-raise-CodecError, never crash or
    allocate unboundedly."""
    seeds = [codec.encode_message_batch(_rand_batch(rng)) for _ in range(32)]
    seeds += [codec.encode_snapshot(_rand_snapshot(rng)) for _ in range(16)]
    seeds += [codec.encode_entries([_rand_entry(rng) for _ in range(3)])]
    n = 0
    for _ in range(iterations):
        data = _mutate(rng, rng.choice(seeds))
        for dec in (
            codec.decode_message_batch,
            codec.decode_snapshot,
            codec.decode_entries,
            codec.decode_message,
            codec.decode_entry,
        ):
            try:
                dec(data)
            except ALLOWED_ERRORS:
                pass
            n += 1
    return n


def fuzz_tcp_frames(rng: random.Random, iterations: int) -> int:
    """Mutated frames through the real framed-socket decoder
    (cf. internal/transport/fuzz.go:68-77): FrameError or success."""
    import socket

    from .transport import tcp

    payloads = [codec.encode_message_batch(_rand_batch(rng)) for _ in range(8)]
    n = 0
    for _ in range(iterations):
        a, b = socket.socketpair()
        try:
            a.settimeout(2.0)
            b.settimeout(2.0)
            raw_payload = rng.choice(payloads)
            import struct
            import zlib

            hdr = tcp._HDR.pack(
                tcp.RAFT_TYPE, len(raw_payload), zlib.crc32(raw_payload), 0
            )
            hcrc = zlib.crc32(hdr[: tcp._HDR.size - 4])
            frame = (
                tcp.MAGIC
                + hdr[: tcp._HDR.size - 4]
                + struct.pack("<I", hcrc)
                + raw_payload
            )
            frame = _mutate(rng, frame)
            a.sendall(frame)
            a.shutdown(socket.SHUT_WR)
            try:
                method, payload = tcp._read_frame(b, max_size=1 << 24)
                if method == tcp.RAFT_TYPE:
                    try:
                        codec.decode_message_batch(payload)
                    except ALLOWED_ERRORS:
                        pass
            except (tcp.FrameError, socket.timeout, OSError):
                pass
        finally:
            a.close()
            b.close()
        n += 1
    return n


def fuzz_wal_recovery(rng: random.Random, iterations: int, tmpdir: str) -> int:
    """Mutated/truncated WAL tails must recover to the last intact record
    group: write N batches through a real WalKV, corrupt the tail region
    of wal.log, reopen, and require the recovered table to equal the state
    after some prefix of the committed batches (group atomicity: never a
    half-applied batch, never corrupt records accepted as data)."""
    import os
    import shutil

    from .storage.kv import WalKV, WriteBatch

    n = 0
    for it in range(iterations):
        d = os.path.join(tmpdir, f"walfuzz-{it}")
        shutil.rmtree(d, ignore_errors=True)
        kv = WalKV(d, fsync=False)
        # prefix states: state[k] = table contents after batch k
        state: dict = {}
        prefixes = [dict(state)]
        boundaries = [0]  # wal.log size at each group boundary
        path = os.path.join(d, "wal.log")
        for b in range(rng.randrange(2, 6)):
            wb = WriteBatch()
            for _ in range(rng.randrange(1, 5)):
                k = b"k%d" % rng.randrange(8)
                if rng.random() < 0.8:
                    v = _rand_bytes(rng, 24)
                    wb.put(k, v)
                    state[k] = v
                else:
                    wb.delete(k)
                    state.pop(k, None)
            kv.commit_write_batch(wb)
            prefixes.append(dict(state))
            kv._f.flush()
            boundaries.append(os.path.getsize(path))
        kv.close()
        # corrupt the TAIL: any byte range overlapping the last one or two
        # record groups (mid-file corruption truncates earlier — still a
        # prefix — but tail faults are the crash-consistency contract)
        data = bytearray(open(path, "rb").read())
        tail_from = boundaries[-3] if len(boundaries) > 2 else 0
        tail = bytes(data[tail_from:])
        mutated = _mutate(rng, tail)
        with open(path, "wb") as f:
            f.write(bytes(data[:tail_from]) + mutated)
        kv2 = WalKV(d)
        got: dict = {}
        kv2.iterate_value(
            b"", b"\xff" * 8, True, lambda k, v: (got.update({k: v}), True)[1]
        )
        kv2.close()
        assert any(got == p for p in prefixes), (
            f"WAL recovery produced a non-prefix state: {got!r} not in "
            f"{prefixes!r}"
        )
        shutil.rmtree(d, ignore_errors=True)
        n += 1
    return n


def fuzz_wal_garbage(rng: random.Random, iterations: int) -> int:
    """Arbitrary byte soup through the record-group decoder: must return
    a (possibly empty) WriteBatch, never crash or allocate unboundedly."""
    from .storage.kv import _decode_records

    n = 0
    for _ in range(iterations):
        _decode_records(rng.randbytes(rng.randrange(0, 512)))
        n += 1
    return n


def run(seconds: float = 10.0, seed: int = 0) -> dict:
    import tempfile

    rng = random.Random(seed or int(time.time()))
    deadline = time.monotonic() + seconds
    stats = {"roundtrip": 0, "mutations": 0, "frames": 0, "wal": 0, "wal_garbage": 0}
    with tempfile.TemporaryDirectory(prefix="walfuzz-") as td:
        while time.monotonic() < deadline:
            stats["roundtrip"] += fuzz_codec_roundtrip(rng, 20)
            stats["mutations"] += fuzz_codec_mutations(rng, 50)
            stats["frames"] += fuzz_tcp_frames(rng, 10)
            stats["wal"] += fuzz_wal_recovery(rng, 5, td)
            stats["wal_garbage"] += fuzz_wal_garbage(rng, 50)
    return stats


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=30.0)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    stats = run(args.seconds, args.seed)
    print(f"fuzz clean: {stats}")


if __name__ == "__main__":
    main()
