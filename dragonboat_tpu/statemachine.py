"""User state machine contracts.

This is the equivalent of the reference's `statemachine/` package: the three
state machine types users implement (cf. statemachine/rsm.go:184-275 for
IStateMachine, statemachine/concurrent.go:45 for IConcurrentStateMachine,
statemachine/disk.go:60 for IOnDiskStateMachine), plus the snapshot file
collection (statemachine/files.go) and sentinel errors.

TPU note: user state machines run host-side, exactly as in the reference —
the device kernel advances protocol state only. A state machine whose update
function is itself a JAX computation (e.g. a replicated learner state) can
batch its applies; see rsm/ for the batched apply path.
"""
from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import BinaryIO, List, Optional, Sequence, Tuple

# State machine type discriminators persisted in the bootstrap record
# (cf. internal/rsm StateMachineType).
SM_TYPE_UNKNOWN = 0
SM_TYPE_REGULAR = 1
SM_TYPE_CONCURRENT = 2
SM_TYPE_ONDISK = 3


class SnapshotStopped(Exception):
    """Raised inside save/recover when the node is being closed
    (cf. statemachine/rsm.go ErrSnapshotStopped)."""


class SnapshotStreamStopped(Exception):
    """The snapshot stream was aborted by the receiver."""


@dataclass(slots=True)
class Result:
    """Outcome of IStateMachine.update (cf. statemachine/rsm.go Result)."""

    value: int = 0
    data: bytes = b""

    def __eq__(self, other):
        return (
            isinstance(other, Result)
            and self.value == other.value
            and self.data == other.data
        )


@dataclass(slots=True)
class SnapshotFile:
    """An external file included in a snapshot (cf. statemachine/files.go)."""

    file_id: int = 0
    filepath: str = ""
    metadata: bytes = b""


class ISnapshotFileCollection(abc.ABC):
    """Collection the SM adds external files to during save
    (cf. statemachine/rsm.go ISnapshotFileCollection)."""

    @abc.abstractmethod
    def add_file(self, file_id: int, path: str, metadata: bytes) -> None: ...


class IStateMachine(abc.ABC):
    """The regular (mutex-serialized) in-memory state machine
    (cf. statemachine/rsm.go:184-275). All methods are invoked from the
    managed-SM layer; update/lookup never run concurrently."""

    @abc.abstractmethod
    def update(self, data: bytes) -> Result:
        """Apply one committed proposal; returns the Result delivered to the
        proposing client (at-most-once under a client session)."""

    @abc.abstractmethod
    def lookup(self, query: object) -> object:
        """Local read against the current state; only invoked after
        linearizability is established by ReadIndex."""

    @abc.abstractmethod
    def save_snapshot(
        self,
        w: BinaryIO,
        files: ISnapshotFileCollection,
        done: "AbortSignal",
    ) -> None:
        """Serialize the full state to w."""

    @abc.abstractmethod
    def recover_from_snapshot(
        self, r: BinaryIO, files: List[SnapshotFile], done: "AbortSignal"
    ) -> None:
        """Rebuild state from a snapshot previously written by
        save_snapshot."""

    def close(self) -> None:  # optional
        return None


class IConcurrentStateMachine(abc.ABC):
    """Concurrent-access SM: update(batch) runs serialized with other
    updates, but snapshotting runs concurrently with updates between
    prepare_snapshot and save_snapshot (cf. statemachine/concurrent.go:45)."""

    @abc.abstractmethod
    def update(self, entries: List["SMEntry"]) -> List["SMEntry"]: ...

    @abc.abstractmethod
    def lookup(self, query: object) -> object: ...

    @abc.abstractmethod
    def prepare_snapshot(self) -> object:
        """Capture a point-in-time identifier of the state; cheap, runs
        serialized with update."""

    @abc.abstractmethod
    def save_snapshot(
        self,
        ctx: object,
        w: BinaryIO,
        files: ISnapshotFileCollection,
        done: "AbortSignal",
    ) -> None: ...

    @abc.abstractmethod
    def recover_from_snapshot(
        self, r: BinaryIO, files: List[SnapshotFile], done: "AbortSignal"
    ) -> None: ...

    def close(self) -> None:
        return None


class IOnDiskStateMachine(abc.ABC):
    """State machine that persists its own state to disk and survives
    restarts without full snapshot replay (cf. statemachine/disk.go:60)."""

    @abc.abstractmethod
    def open(self, stopc: "AbortSignal") -> int:
        """Open existing state; returns the index of the last applied
        entry."""

    @abc.abstractmethod
    def update(self, entries: List["SMEntry"]) -> List["SMEntry"]: ...

    @abc.abstractmethod
    def lookup(self, query: object) -> object: ...

    @abc.abstractmethod
    def sync(self) -> None:
        """fsync all in-flight application state."""

    @abc.abstractmethod
    def prepare_snapshot(self) -> object: ...

    @abc.abstractmethod
    def save_snapshot(self, ctx: object, w: BinaryIO, done: "AbortSignal") -> None:
        """Stream the point-in-time state captured by prepare_snapshot; used
        only for streaming to lagging/new peers."""

    @abc.abstractmethod
    def recover_from_snapshot(self, r: BinaryIO, done: "AbortSignal") -> None: ...

    def close(self) -> None:
        return None


@dataclass(slots=True)
class SMEntry:
    """A committed entry handed to concurrent/on-disk SM update batches
    (cf. statemachine/rsm.go Entry)."""

    index: int = 0
    cmd: bytes = b""
    result: Result = field(default_factory=Result)


class AbortSignal:
    """Cooperative cancellation handle passed into snapshot operations; the
    reference models this as a <-chan struct{} (statemachine/rsm.go:248)."""

    __slots__ = ("_stopped",)

    def __init__(self) -> None:
        self._stopped = False

    def stop(self) -> None:
        self._stopped = True

    @property
    def stopped(self) -> bool:
        return self._stopped

    def check(self) -> None:
        """Raise SnapshotStopped if aborted; SMs call this periodically in
        long save/recover loops."""
        if self._stopped:
            raise SnapshotStopped()


def sm_type_of(sm: object) -> int:
    if isinstance(sm, IOnDiskStateMachine):
        return SM_TYPE_ONDISK
    if isinstance(sm, IConcurrentStateMachine):
        return SM_TYPE_CONCURRENT
    if isinstance(sm, IStateMachine):
        return SM_TYPE_REGULAR
    return SM_TYPE_UNKNOWN


__all__ = [
    "SM_TYPE_UNKNOWN",
    "SM_TYPE_REGULAR",
    "SM_TYPE_CONCURRENT",
    "SM_TYPE_ONDISK",
    "SnapshotStopped",
    "SnapshotStreamStopped",
    "Result",
    "SnapshotFile",
    "ISnapshotFileCollection",
    "IStateMachine",
    "IConcurrentStateMachine",
    "IOnDiskStateMachine",
    "SMEntry",
    "AbortSignal",
    "sm_type_of",
]
