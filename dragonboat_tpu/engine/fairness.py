"""Tick-fairness watchdog for co-scheduled engine loops.

Several engine loops commonly share one box: three NodeHosts in one test
process, or co-hosted replicas pinned to a small CPU host driving an
accelerator. When one loop's kernel step runs for multiple tick periods
(cold XLA compile, CPU contention), its peers' loop threads starve: their
tick backlogs balloon and, once they finally run, the burst replay used to
advance election timers by a whole election RTT in a single step —
synchronizing every follower's timeout into split-vote storms (the ROADMAP
seed flake; cf. the Podracer line of work on co-scheduled accelerator
loops, arXiv:2104.06272, which makes loop fairness a first-class concern).

The watchdog gives every engine loop three things:

  1. measurement — per-loop inter-iteration latency against the expected
     tick period, kept as a windowed maximum so a single stall stays
     visible for a while after it happens;
  2. a starvation gauge — `starvation_ratio` = recent max gap / tick
     period (1.0 = keeping up; 100 = a stall of 100 tick periods), which
     NodeHost exports through its MetricsRegistry;
  3. enforcement — after an iteration that overran the yield threshold
     while some co-scheduled peer loop made no progress, the loop cedes
     the CPU with a short sleep so the starved peer's thread gets a
     scheduling slice before the next kernel step is dispatched.

Watchdogs register in a process-global peer table; peers are discovered
automatically, so tests with three NodeHosts get fairness between their
three engine loops with zero configuration.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from ..trace import flight_recorder

_peers_mu = threading.Lock()
_peers: List["FairnessWatchdog"] = []


def _register(wd: "FairnessWatchdog") -> None:
    with _peers_mu:
        _peers.append(wd)


def _unregister(wd: "FairnessWatchdog") -> None:
    with _peers_mu:
        try:
            _peers.remove(wd)
        except ValueError:
            pass


def peer_count() -> int:
    with _peers_mu:
        return len(_peers)


class FairnessWatchdog:
    """Per-engine-loop fairness monitor; see module docstring.

    All hot-path methods (`iter_begin`/`iter_end`/`tick_burst`) run on the
    owning loop thread only and touch plain attributes — no locks beyond a
    snapshot read of the peer list. `stats()` may be called from any
    thread; it reads torn-safe scalars.
    """

    # gap window: how long a stall stays visible in the gauge (iterations)
    _WINDOW = 256

    def __init__(
        self,
        name: str,
        tick_period_s: float,
        yield_threshold_s: Optional[float] = None,
        yield_s: float = 0.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.name = name
        self.tick_period_s = max(tick_period_s, 1e-4)
        # auto: an iteration 4+ tick periods long is starving its peers
        self.yield_threshold_s = (
            yield_threshold_s
            if yield_threshold_s is not None
            else max(4 * self.tick_period_s, 0.02)
        )
        self._yield_s = yield_s
        self._clock = clock
        self._last_end = clock()
        self._max_gap_s = 0.0  # lifetime max
        self._recent_max_s = 0.0  # windowed max
        self._recent_left = self._WINDOW
        self._iters = 0
        self._steps = 0  # protocol steps covered by those iterations
        self._yields = 0
        self._tick_burst_max = 0
        self._tick_bursts_clamped = 0
        self._clock_anomalies = 0
        self._closed = False
        _register(self)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            _unregister(self)

    # ------------------------------------------------------------ hot path
    def iter_begin(self) -> float:
        return self._clock()

    def iter_end(self, t0: float, ticks: int = 0, steps: int = 1) -> bool:
        """Record one loop iteration; returns True when a fairness yield
        was enforced (the loop slept to cede CPU to a starved peer).
        ``steps`` is how many protocol steps the iteration advanced (K
        for a multi-step super-step): the yield decision stays
        per-ITERATION wall time — a K-step launch that hogs the core
        starves peers exactly like a long single step — but the stats
        expose steps-per-iteration so a high per-iteration latency under
        K>1 reads as amortization, not as starvation."""
        now = self._clock()
        gap = now - self._last_end
        self._last_end = now
        self._iters += 1
        self._steps += max(steps, 1)
        if gap > self._max_gap_s:
            self._max_gap_s = gap
        if gap >= self._recent_max_s:
            self._recent_max_s = gap
            self._recent_left = self._WINDOW
        else:
            self._recent_left -= 1
            if self._recent_left <= 0:
                self._recent_max_s = gap
                self._recent_left = self._WINDOW
        if ticks > self._tick_burst_max:
            self._tick_burst_max = ticks
        dur = now - t0
        if dur < self.yield_threshold_s:
            return False
        if not self._peer_starved(t0):
            return False
        self._yields += 1
        # cede proportionally to how long we hogged the core, bounded so a
        # pathological multi-second step never parks the loop for long
        pause = self._yield_s or min(0.02, max(0.001, dur * 0.05))
        flight_recorder().record(
            "fairness_yield", loop=self.name, iter_s=round(dur, 6),
            pause_s=round(pause, 6),
        )
        time.sleep(pause)
        return True

    def tick_burst_clamped(self) -> None:
        """A coalesced tick backlog exceeded the per-step replay clamp."""
        self._tick_bursts_clamped += 1
        flight_recorder().record("tick_burst_clamped", loop=self.name)

    def note_clock_anomaly(self) -> None:
        """The tick plane detected a clock anomaly (backward reading or a
        step-jump, see NodeHost._tick_worker_main): discard the current
        gap window and re-anchor the beat. The phantom gap a jumped
        clock mints is a CLOCK fault, not a scheduling stall — without
        the discard it would sit in the 256-iteration window and fail
        chaos runs' fairness_no_stall verdict for the wrong reason."""
        self._clock_anomalies += 1
        flight_recorder().record("clock_anomaly", loop=self.name)
        self.reset_window()

    def reset_window(self) -> None:
        """Forget the windowed maximum (NOT the lifetime max_gap_s).
        Chaos harnesses call this after bring-up so the cold-compile
        stall of the first kernel step does not sit in the 256-iteration
        window and mask the fault-phase measurement (the restart plane's
        graceful-degradation verdict). Cross-thread use is benign: the
        scalars are torn-safe and the loop thread re-establishes them on
        its next iteration."""
        self._recent_max_s = 0.0
        self._recent_left = self._WINDOW
        self._last_end = self._clock()

    # a peer whose beat is older than this is abandoned (an engine that
    # was never stop()ed), not starved: yielding to it helps nobody and
    # a single leaked watchdog must not slow every other loop forever
    _STALE_PEER_S = 60.0

    def _peer_starved(self, since: float) -> bool:
        with _peers_mu:
            peers = list(_peers)
        for p in peers:
            if p is self or p._closed:
                continue
            if since - p._last_end > self._STALE_PEER_S:
                continue  # abandoned, not starved
            if p._last_end < since:
                return True
        return False

    # ---------------------------------------------------------------- stats
    def stats(self) -> dict:
        return {
            "name": self.name,
            "tick_period_s": self.tick_period_s,
            "max_gap_s": self._max_gap_s,
            "recent_max_gap_s": self._recent_max_s,
            "starvation_ratio": self._recent_max_s / self.tick_period_s,
            "tick_burst_max": self._tick_burst_max,
            "tick_bursts_clamped": self._tick_bursts_clamped,
            "clock_anomalies": self._clock_anomalies,
            "fairness_yields": self._yields,
            "iterations": self._iters,
            "protocol_steps": self._steps,
            "steps_per_iteration": (
                self._steps / self._iters if self._iters else 0.0
            ),
            "co_scheduled_peers": peer_count() - (0 if self._closed else 1),
        }


__all__ = ["FairnessWatchdog", "peer_count"]
