"""Double-buffered producer/consumer queues between the API and the step
workers (cf. queue.go:24-252 and internal/server/message.go:24-172).

Producers append under a short lock; the step worker swaps the buffer out
and walks it lock-free. The MessageQueue additionally carries a dedicated
snapshot slot (an InstallSnapshot message bypasses capacity limits) and
coalesces LocalTick counts instead of queuing one message per tick.
"""
from __future__ import annotations

import threading
from typing import List, Optional, Tuple

from ..types import Entry, Message, MessageType, SystemCtx


class EntryQueue:
    """cf. queue.go:24-108."""

    def __init__(self, size: int = 2048) -> None:
        self._size = size
        self._mu = threading.Lock()
        self._left: List[Entry] = []
        self._right: List[Entry] = []
        self._use_left = True
        self.stopped = False
        self._paused = False

    def add(self, e: Entry) -> bool:
        with self._mu:
            if self.stopped or self._paused:
                return False
            buf = self._left if self._use_left else self._right
            if len(buf) >= self._size:
                self._paused = True
                return False
            buf.append(e)
            return True

    def has_pending(self) -> bool:
        """Lock-free emptiness probe for the engine's pack loop: a racy
        miss is safe because every producer marks the lane dirty AFTER
        enqueueing, so the next iteration drains what this one missed."""
        return bool(self._left or self._right)

    def fill(self) -> float:
        """Lock-free fill fraction in [0, 1] — the backpressure probe the
        serving front's SaturationMonitor polls (a full queue here is the
        ErrSystemBusy raise site one add() later). Torn reads under
        concurrent swaps cost at most one stale sample."""
        return min(
            (len(self._left) + len(self._right)) / self._size, 1.0
        )

    def pending_count(self) -> int:
        """Lock-free queued-item count (see fill for the torn-read
        contract) — feeds pressure_stats' staged_backlog."""
        return len(self._left) + len(self._right)

    def add_many(self, entries: List[Entry]) -> int:
        """Enqueue a batch under ONE lock acquisition; returns how many
        were accepted (the tail past capacity is refused and the queue
        pauses, exactly like a failed add)."""
        with self._mu:
            if self.stopped or self._paused:
                return 0
            buf = self._left if self._use_left else self._right
            room = self._size - len(buf)
            if room <= 0:
                self._paused = True
                return 0
            take = entries[:room]
            buf.extend(take)
            if len(take) < len(entries):
                self._paused = True
            return len(take)

    def get(self, paused: bool = False) -> List[Entry]:
        with self._mu:
            self._paused = paused
            buf = self._left if self._use_left else self._right
            self._use_left = not self._use_left
            tgt = self._left if self._use_left else self._right
            tgt.clear()
            out = list(buf)
            buf.clear()
            return out

    def close(self) -> None:
        with self._mu:
            self.stopped = True
            self._left.clear()
            self._right.clear()


class ReadIndexQueue:
    """cf. queue.go:110-176; carries opaque request objects the node binds
    to system contexts."""

    def __init__(self, size: int = 4096) -> None:
        self._size = size
        self._mu = threading.Lock()
        self._pending: List[object] = []
        self.stopped = False

    def add(self, req: object) -> bool:
        with self._mu:
            if self.stopped or len(self._pending) >= self._size:
                return False
            self._pending.append(req)
            return True

    def get(self) -> List[object]:
        with self._mu:
            out = self._pending
            self._pending = []
            return out

    def has_pending(self) -> bool:
        return bool(self._pending)

    def fill(self) -> float:
        """Lock-free fill fraction in [0, 1] (see EntryQueue.fill)."""
        return min(len(self._pending) / self._size, 1.0)

    def pending_count(self) -> int:
        """Lock-free queued-request count (see EntryQueue.pending_count)."""
        return len(self._pending)

    def close(self) -> None:
        with self._mu:
            self.stopped = True
            self._pending = []


class MessageQueue:
    """Receive queue with snapshot slot + tick coalescing
    (cf. internal/server/message.go:24-172, node.go:1152-1159)."""

    def __init__(self, size: int = 1024, max_bytes: int = 0) -> None:
        self._size = size
        self._max_bytes = max_bytes
        self._mu = threading.Lock()
        self._msgs: List[Message] = []
        self._snapshot: Optional[Message] = None
        self._tick_count = 0
        self.stopped = False

    def add(self, m: Message) -> bool:
        with self._mu:
            if self.stopped:
                return False
            if m.type == MessageType.LOCAL_TICK:
                self._tick_count += 1
                return True
            if len(self._msgs) >= self._size:
                return False
            self._msgs.append(m)
            return True

    def add_many(self, msgs: List[Message]) -> int:
        """Enqueue a batch under ONE lock acquisition; returns how many
        were consumed (capacity refuses the tail, exactly like a failed
        add — the caller routes the remainder through the wire path)."""
        with self._mu:
            if self.stopped:
                return 0
            n = 0
            buf = self._msgs
            size = self._size
            for m in msgs:
                if m.type == MessageType.LOCAL_TICK:
                    self._tick_count += 1
                elif len(buf) >= size:
                    break
                else:
                    buf.append(m)
                n += 1
            return n

    def add_snapshot(self, m: Message) -> bool:
        with self._mu:
            if self.stopped or self._snapshot is not None:
                return False
            self._snapshot = m
            return True

    def has_pending(self) -> bool:
        """Lock-free emptiness probe (see EntryQueue.has_pending)."""
        return bool(self._msgs or self._snapshot or self._tick_count)

    def get(self) -> Tuple[List[Message], int]:
        """Returns (messages, coalesced_tick_count); an InstallSnapshot
        message is delivered first."""
        with self._mu:
            out: List[Message] = []
            if self._snapshot is not None:
                out.append(self._snapshot)
                self._snapshot = None
            out.extend(self._msgs)
            self._msgs = []
            ticks = self._tick_count
            self._tick_count = 0
            return out, ticks

    def close(self) -> None:
        with self._mu:
            self.stopped = True
            self._msgs = []
            self._snapshot = None


class LeaderInfoQueue:
    """Dedicated queue for leader-change notifications to the user listener
    (cf. queue.go:213-252)."""

    def __init__(self, size: int = 2048) -> None:
        self._mu = threading.Lock()
        self._size = size
        self._q: List[object] = []
        self.notify = threading.Event()

    def add(self, info: object) -> None:
        with self._mu:
            if len(self._q) < self._size:
                self._q.append(info)
        self.notify.set()

    def get_all(self) -> List[object]:
        with self._mu:
            out = self._q
            self._q = []
            self.notify.clear()
            return out


__all__ = ["EntryQueue", "ReadIndexQueue", "MessageQueue", "LeaderInfoQueue"]
