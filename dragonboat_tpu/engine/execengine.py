"""Execution engine: fixed worker pools advancing many Raft groups.

cf. execengine.go:126-644 — the scheduler at the heart of multi-group
parallelism. Step workers run the protocol hot loop, task workers apply
committed entries to state machines, snapshot workers run save/recover/
stream. Groups are statically partitioned to workers by
cluster_id % worker_count (cf. internal/server/partition.go:22-41).

The hot loop preserves the reference's ordering invariants
(execengine.go:474-560):
  step -> fast-apply -> send Replicate (BEFORE fsync) -> SaveRaftState
  (fsync) -> stable-apply -> process update (append window, send rest)
  -> commit cursors
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple

from ..logger import get_logger
from ..profile import HOT_LANE_COUNTERS, DeviceCensus, phase_plane
from ..settings import hard, soft
from ..trace import LatencySampler, Profiler
from ..types import Update
from ..rsm.manager import From as OffloadFrom
from .fairness import FairnessWatchdog
from .node import Node

_plog = get_logger("execengine")

# Scalar twin of the kernel's counter plane. Mirrors ops.state.CTR_NAMES
# verbatim (pinned by a test) — duplicated here so the scalar engine stays
# importable without jax, which ops.state pulls in at module level.
_COUNTER_ATTRS = (
    "elections_started",
    "elections_won",
    "heartbeats_sent",
    "replicate_rejects",
    "commit_advances",
    "lease_served",
    "lease_fallback",
    "read_confirmations",
)


class _NullProfiler:
    """Zero-cost stand-in when profiling is disabled."""

    def new_iteration(self, n_groups: int = 0) -> None:
        pass

    def start(self) -> None:
        pass

    def end(self, stage: str) -> None:
        pass


_NULL_PROFILER = _NullProfiler()


class WorkReady:
    """Partitioned ready-channels (cf. execengine.go:82-124): producers mark
    a cluster ready; the owning worker drains its partition's set."""

    def __init__(self, partitions: int) -> None:
        self._n = partitions
        self._sets: List[Set[int]] = [set() for _ in range(partitions)]
        self._events = [threading.Event() for _ in range(partitions)]
        self._locks = [threading.Lock() for _ in range(partitions)]

    def partition(self, cluster_id) -> int:
        # hash() so composite keys work too (the shared VectorEngine keys
        # work by (host, cluster_id)); hash(int) == int keeps the scalar
        # engine's partition layout unchanged
        return hash(cluster_id) % self._n

    def notify(self, cluster_id: int) -> None:
        p = self.partition(cluster_id)
        with self._locks[p]:
            self._sets[p].add(cluster_id)
        self._events[p].set()

    def notify_all(self, cluster_ids) -> None:
        touched = set()
        for cid in cluster_ids:
            p = self.partition(cid)
            with self._locks[p]:
                self._sets[p].add(cid)
            touched.add(p)
        for p in touched:
            self._events[p].set()

    def wait_and_take(self, worker: int, timeout: float = 0.5) -> Set[int]:
        ev = self._events[worker]
        if not ev.wait(timeout):
            return set()
        with self._locks[worker]:
            out = self._sets[worker]
            self._sets[worker] = set()
            ev.clear()
        return out

    def wake_all(self) -> None:
        for ev in self._events:
            ev.set()


class ExecEngine:
    def __init__(
        self,
        logdb,
        num_step_workers: Optional[int] = None,
        num_task_workers: Optional[int] = None,
        num_snapshot_workers: int = 4,
        sample_ratio: Optional[int] = None,
        tick_period_s: float = 0.05,
        fairness_yield_ms: Optional[float] = None,
    ) -> None:
        self._logdb = logdb
        # tick-fairness watchdog (see engine/fairness.py): worker 0 is the
        # engine's heartbeat — it wakes at least once per tick period, so
        # an idle healthy engine reads starvation_ratio ~1.0 (same scale
        # as the vector loop) and a stale beat means this engine is being
        # starved of CPU by a co-scheduled peer loop (or is itself
        # starving them). fairness_yield_ms follows the EngineConfig
        # contract: None = auto threshold, 0 disables enforcement.
        self.watchdog = FairnessWatchdog(
            "exec-step",
            tick_period_s,
            yield_threshold_s=(
                float("inf") if fairness_yield_ms == 0
                else (fairness_yield_ms / 1000.0 if fairness_yield_ms else None)
            ),
        )
        self._wd_wait = min(0.5, max(tick_period_s, 1e-3))
        self._tick_period_s = max(tick_period_s, 1e-3)
        # Python threads contend on the GIL: default pools are smaller than
        # the Go engine's 16; protocol work is lock-striped the same way
        self._n_step = num_step_workers or min(hard.step_engine_worker_count, 8)
        self._n_task = num_task_workers or min(
            soft.step_engine_task_worker_count, 8
        )
        self._n_snap = num_snapshot_workers
        self._nodes: Dict[int, Node] = {}
        self._nodes_mu = threading.RLock()
        self._stopped = threading.Event()
        self.node_ready = WorkReady(self._n_step)
        self.task_ready = WorkReady(self._n_task)
        self.snapshot_ready = WorkReady(self._n_snap)
        # per-step-worker sampled profilers (cf. execengine.go:161-169);
        # ratio 0 (the default, cf. soft.latency_sample_ratio) disables
        # profiling entirely — no timing calls, no sample memory
        ratio = (
            sample_ratio if sample_ratio is not None
            else soft.latency_sample_ratio
        )
        self.profilers = (
            [Profiler(ratio) for _ in range(self._n_step)] if ratio > 0 else []
        )
        # sampled stage durations fan out to the shared phase plane
        # (engine_phase_seconds{engine="exec",phase=...}) so scalar and
        # vector step attribution read on one scale
        for p in self.profilers:
            p.attach_phase_plane(phase_plane(), "exec")
        # request-lifecycle latency sampling (see trace.LatencySampler):
        # same contract as the vector engine — a disabled stage profiler
        # still leaves the sparse 1-in-32 request sampler on, so latency
        # histograms exist in production without stage-timing overhead
        self.request_sampler = LatencySampler(ratio if ratio > 0 else 32)
        self._threads: List[threading.Thread] = []
        for i in range(self._n_step):
            t = threading.Thread(
                target=self._node_worker_main, args=(i,), name=f"step-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)
        for i in range(self._n_task):
            t = threading.Thread(
                target=self._task_worker_main, args=(i,), name=f"task-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)
        for i in range(self._n_snap):
            t = threading.Thread(
                target=self._snapshot_worker_main,
                args=(i,),
                name=f"snap-{i}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)

    # ------------------------------------------------------------- registry
    def add_node(self, node: Node) -> None:
        with self._nodes_mu:
            self._nodes[node.cluster_id] = node
        self.set_node_ready(node.cluster_id)

    def remove_node(self, cluster_id: int) -> None:
        with self._nodes_mu:
            self._nodes.pop(cluster_id, None)

    def get_node(self, cluster_id: int) -> Optional[Node]:
        with self._nodes_mu:
            return self._nodes.get(cluster_id)

    def drain(self, timeout: float = 30.0) -> None:
        """Seam parity with VectorEngine.drain(): registry removal is
        synchronous here (remove_node pops under the lock) and a worker
        mid-exec_nodes sees node.stopped and skips it, so the restart
        plane has nothing to wait for."""
        return

    # -------------------------------------------------------------- wakeups
    def set_node_ready(self, cluster_id: int) -> None:
        self.node_ready.notify(cluster_id)

    def set_task_ready(self, cluster_id: int) -> None:
        self.task_ready.notify(cluster_id)

    def set_snapshot_ready(self, cluster_id: int) -> None:
        self.snapshot_ready.notify(cluster_id)

    # ---------------------------------------------------------- step workers
    def _node_worker_main(self, worker: int) -> None:
        wd = self.watchdog if worker == 0 else None
        while not self._stopped.is_set():
            cids = self.node_ready.wait_and_take(
                worker, self._wd_wait if wd is not None else 0.5
            )
            if not cids:
                if wd is not None:  # heartbeat: records the idle gap only
                    wd.iter_end(wd.iter_begin())
                continue
            nodes = []
            with self._nodes_mu:
                for cid in cids:
                    n = self._nodes.get(cid)
                    if n is not None and not n.stopped:
                        nodes.append(n)
            if nodes:
                t0 = wd.iter_begin() if wd is not None else 0.0
                try:
                    self.exec_nodes(nodes, worker)
                except Exception:  # a group failure must not kill the worker
                    import traceback

                    traceback.print_exc()
                if wd is not None:
                    wd.iter_end(t0)

    def exec_nodes(self, nodes: List[Node], worker: int = 0) -> None:
        """THE hot loop (cf. execNodes execengine.go:474-560)."""
        prof = self.profilers[worker] if self.profilers else _NULL_PROFILER
        prof.new_iteration(len(nodes))
        prof.start()
        updates: List[Tuple[Node, Update]] = []
        for node in nodes:
            if not node.initialized.is_set():
                node.recover_initial_snapshot()
            ud = node.step_node()
            if ud is not None:
                node.process_dropped(ud)
                updates.append((node, ud))
        prof.end("step")
        if not updates:
            return
        # 1. fast-apply: committed entries reach the SM before the fsync when
        #    safe (peer.set_fast_apply decided per update)
        prof.start()
        for node, ud in updates:
            if ud.fast_apply:
                node.apply_raft_update(ud)
        prof.end("fast_apply")
        # 2. Replicate messages leave before the local fsync
        prof.start()
        for node, ud in updates:
            node.send_replicate_messages(ud)
        prof.end("send")
        # 3. one batched fsynced write for every group this worker stepped
        prof.start()
        self._logdb.save_raft_state([ud for _, ud in updates])
        prof.end("save")
        # 4. stable apply for the rest
        prof.start()
        for node, ud in updates:
            if not ud.fast_apply:
                node.apply_raft_update(ud)
        prof.end("apply")
        # 5. window append, remaining sends, snapshot triggers, cursors
        prof.start()
        for node, ud in updates:
            node.process_raft_update(ud)
            node.commit_raft_update(ud)
        prof.end("exec")

    # ---------------------------------------------------------- task workers
    def _task_worker_main(self, worker: int) -> None:
        batch: list = []
        apply: list = []
        while not self._stopped.is_set():
            cids = self.task_ready.wait_and_take(worker)
            if not cids:
                continue
            for cid in cids:
                node = self.get_node(cid)
                if node is None or node.stopped:
                    continue
                if not node.sm.loaded(OffloadFrom.COMMIT_WORKER):
                    continue  # lost the race with NodeHost close
                try:
                    node.handle_task(batch, apply)
                except Exception:
                    import traceback

                    traceback.print_exc()
                finally:
                    node.sm.offloaded(OffloadFrom.COMMIT_WORKER)
                if node.sm.task_queue.size() > 0:
                    self.set_task_ready(cid)

    # ------------------------------------------------------ snapshot workers
    def _snapshot_worker_main(self, worker: int) -> None:
        while not self._stopped.is_set():
            cids = self.snapshot_ready.wait_and_take(worker)
            if not cids:
                continue
            for cid in cids:
                node = self.get_node(cid)
                if node is None or node.stopped:
                    continue
                if not node.sm.loaded(OffloadFrom.SNAPSHOT_WORKER):
                    continue  # lost the race with NodeHost close
                try:
                    node.run_snapshot_work()
                except Exception:
                    import traceback

                    traceback.print_exc()
                finally:
                    node.sm.offloaded(OffloadFrom.SNAPSHOT_WORKER)

    # --------------------------------------------------------------- control
    def fairness_stats(self) -> dict:
        """Tick-fairness watchdog snapshot (see engine/fairness.py)."""
        return self.watchdog.stats()

    def lease_stats(self) -> dict:
        """Lease read counters, shape-compatible with
        VectorEngine.lease_stats(): 'local' / 'fallback' summed from each
        group's scalar core (plain int reads — a torn read costs one
        stale sample on an export path, never a protocol decision)."""
        local = fb = 0
        with self._nodes_mu:
            nodes = list(self._nodes.values())
        for node in nodes:
            r = getattr(node.peer, "raft", None)
            if r is not None:
                local += r.lease_served
                fb += r.lease_fallback
        return {"local": local, "fallback": fb}

    def lease_valid(self, cluster_id: int) -> bool:
        """Does this group's scalar core hold a live leader lease right
        now? Probe read for NodeHost.lease_read; the authoritative
        serve/fallback decision stays in the core's read path."""
        with self._nodes_mu:
            node = self._nodes.get(cluster_id)
        if node is None or node.stopped:
            return False
        r = getattr(node.peer, "raft", None)
        if r is None:
            return False
        with node._mu:
            return bool(r.lease_valid())

    def set_clock_suspect(self, hold_s: float) -> None:
        """Clock-anomaly report from the host's tick worker: revoke every
        group's lease and refuse re-grants for hold_s (converted to ticks
        at the engine tick period) — lease reads degrade to the ReadIndex
        quorum path until the tick plane has proven sane again."""
        ticks = max(1, int(hold_s / self._tick_period_s + 0.999))
        with self._nodes_mu:
            nodes = list(self._nodes.values())
        for node in nodes:
            if node.stopped:
                continue
            try:
                with node._mu:
                    node.peer.raft.set_clock_suspect(ticks)
            except Exception:
                continue  # racing a concurrent close

    def pressure_stats(self) -> dict:
        """Serving-front backpressure probe, shape-compatible with
        VectorEngine.pressure_stats(): worst incoming-queue fill across
        this engine's groups (the EntryQueue/ReadIndexQueue whose
        overflow IS the ErrSystemBusy raise site one add() later).
        staged_backlog is the total count of accepted-but-not-yet-stepped
        requests across those queues — the scalar analogue of the vector
        engine's staged-row backlog."""
        occ = 0.0
        backlog = 0
        with self._nodes_mu:
            nodes = list(self._nodes.values())
        for node in nodes:
            occ = max(
                occ,
                node.incoming_proposals.fill(),
                node.incoming_reads.fill(),
            )
            backlog += (
                node.incoming_proposals.pending_count()
                + node.incoming_reads.pending_count()
            )
        return {"inbox_occupancy": occ, "staged_backlog": backlog}

    def counter_stats(self) -> Dict[str, int]:
        """Engine-wide protocol-event counter totals, shape-compatible
        with VectorEngine.counter_stats() (names = ops.state.CTR_NAMES).
        Summed from each group's scalar core; plain-int reads off the
        cores (same torn-read contract as lease_stats)."""
        totals = {name: 0 for name in _COUNTER_ATTRS}
        with self._nodes_mu:
            nodes = list(self._nodes.values())
        for node in nodes:
            r = getattr(node.peer, "raft", None)
            if r is None:
                continue
            for name in _COUNTER_ATTRS:
                totals[name] += int(getattr(r, name, 0))
        return totals

    def lane_counters(self) -> Dict[int, Dict[str, int]]:
        """Per-group counter rows, cluster_id-keyed — the scalar side of
        VectorEngineHandle.lane_counters() for tools.top."""
        out: Dict[int, Dict[str, int]] = {}
        with self._nodes_mu:
            nodes = list(self._nodes.values())
        for node in nodes:
            if node.stopped:
                continue
            r = getattr(node.peer, "raft", None)
            if r is None:
                continue
            out[node.cluster_id] = {
                name: int(getattr(r, name, 0)) for name in _COUNTER_ATTRS
            }
        return out

    def device_census(self) -> dict:
        """Shape-compatible HBM census: the scalar engine holds no device
        memory, so every byte/fill key is present and zero — consumers
        (bench JSON, gauges, tools.top) need not branch per engine."""
        return DeviceCensus.empty()

    def lane_stats(self) -> Dict[int, dict]:
        """Per-group introspection, shape-compatible with
        VectorEngine.lane_stats(): cluster_id -> {node_id, leader_id,
        term, commit_gap, ticks_since_leader_change}. Feeds the same
        engine_lane_* gauges (NodeHost._export_health_gauges) and the
        bench JSON lane fold, so dashboards read identically whichever
        engine a host runs. Derived from each group's protocol core under
        its step lock — the scalar engine hosts few groups and the export
        cadence is ~1/s, so the per-group lock round-trip is noise here
        (the vector engine's zero-sync numpy mirrors exist because it
        hosts thousands)."""
        out: Dict[int, dict] = {}
        with self._nodes_mu:
            nodes = list(self._nodes.values())
        for node in nodes:
            if node.stopped or not node.initialized.is_set():
                continue
            try:
                st = node.local_status()
            except Exception:
                continue  # racing a concurrent close
            tick = node.clock.tick
            last = st.get("last_index", st["commit"])
            # resident CLIENT-payload bytes in the in-memory log tier
            # (config-change cmds excluded: protocol metadata reaches
            # witnesses intact) — the witness-lane zero-payload probe,
            # vector-parity key
            try:
                inmem = node.peer.raft.log.inmem
                payload = sum(
                    len(e.cmd)
                    for e in inmem.entries
                    if not e.is_config_change()
                )
            except Exception:
                payload = 0
            out[node.cluster_id] = {
                "node_id": st["node_id"],
                "leader_id": st["leader_id"],
                "term": st["term"],
                "commit_gap": max(int(last - st["commit"]), 0),
                # append high-water mark (vector-parity key: the
                # placement plane's ingest-rate delta signal)
                "last_index": int(last),
                "ticks_since_leader_change": max(
                    int(tick - getattr(node, "_leader_change_tick", 0)), 0
                ),
                "role": int(st["state"]),
                "payload_bytes": payload,
            }
        return out

    def hot_lane_stats(self, k: int):
        """The k hottest groups by commit gap + the total the cap hides,
        shape-compatible with VectorEngineHandle.hot_lane_stats():
        (cluster_id -> lane_stats row + HOT_LANE_COUNTERS columns,
        total). The scalar engine hosts few groups, so 'capped' is just
        a sort here — the shape parity is what matters: the history
        sampler and tools.top read one surface whichever engine runs."""
        stats = self.lane_stats()
        counters = self.lane_counters()
        total = len(stats)
        hottest = sorted(
            stats.items(), key=lambda kv: kv[1]["commit_gap"], reverse=True
        )[: max(1, int(k))]
        out = {}
        for cid, row in hottest:
            row = dict(row)
            c = counters.get(cid, {})
            row["counters"] = {
                name: int(c.get(name, 0)) for name in HOT_LANE_COUNTERS
            }
            out[cid] = row
        return out, total

    def stop(self) -> None:
        self.watchdog.close()
        self._stopped.set()
        self.node_ready.wake_all()
        self.task_ready.wake_all()
        self.snapshot_ready.wake_all()
        for t in self._threads:
            t.join(timeout=2)
        # dump sampled stage latencies (cf. execengine.go:197-211)
        for i, prof in enumerate(self.profilers):
            report = prof.report()
            if report:
                _plog.infof("step worker %d stage latencies:\n%s", i, report)


__all__ = ["ExecEngine", "WorkReady"]
