"""Quiesce manager: idle groups stop exchanging heartbeats.

cf. quiesce.go:23-123 — after threshold = 10x election ticks with no user
or protocol activity, the node enters quiesce: its peer receives
quiesced_tick() (clock advances, no elections/heartbeats fire). Any new
activity exits quiesce immediately. With thousands of mostly-idle groups
this is what keeps the tick fanout affordable.
"""
from __future__ import annotations


class QuiesceManager:
    THRESHOLD_FACTOR = 10  # cf. quiesce.go:84-86

    def __init__(self, enabled: bool, election_tick: int) -> None:
        self.enabled = enabled
        self.election_tick = election_tick
        self.threshold = election_tick * self.THRESHOLD_FACTOR
        self.current_tick = 0
        self.idle_since = 0
        self._quiesced = False
        self.exit_grace = 0

    def quiesced(self) -> bool:
        return self.enabled and self._quiesced

    def record_activity(self) -> None:
        self.idle_since = self.current_tick
        if self._quiesced:
            self._quiesced = False
            # brief grace window before re-entering (cf. quiesce.go newToQuiesce)
            self.exit_grace = self.current_tick + self.election_tick

    def try_enter_quiesce(self) -> None:
        """Peer announced quiesce (Quiesce message exchange)."""
        if self.enabled and not self._quiesced:
            self._quiesced = True
            self.idle_since = self.current_tick

    def wake_on_admit(self) -> bool:
        """Serving-front admission against this group: exit quiesce NOW
        (before the admitted op reaches the step loop) so the first
        proposal of a burst pays at most one tick of wake latency, not a
        full activity-detection round trip. Returns True when the group
        was actually quiesced — the serving plane counts real wakes, and
        an already-active group must not inflate the ledger. The normal
        re-quiesce path (threshold idle ticks after the burst drains)
        is untouched."""
        woke = self.quiesced()
        self.record_activity()
        return woke

    def tick(self) -> bool:
        """Advance; returns True when the peer should get a quiesced tick."""
        self.current_tick += 1
        if not self.enabled:
            return False
        if self._quiesced:
            return True
        if (
            self.current_tick - self.idle_since >= self.threshold
            and self.current_tick >= self.exit_grace
        ):
            self._quiesced = True
            return True
        return False


__all__ = ["QuiesceManager"]
