"""VectorEngine: the device-kernel-backed execution engine.

The scalar ExecEngine advances each group with a per-group Peer inside
worker threads (cf. reference execengine.go:474-560). This engine is the
TPU-first replacement: ALL groups hosted by a NodeHost live as lanes of one
(G, P) tensor state (ops/state.RaftTensors) and advance together in one
compiled kernel step (ops/kernel.step_batch). The host side of the engine

  1. packs per-group events (wire messages, proposals, reads, config
     changes, transfers) into the device Inbox,
  2. runs the jitted step,
  3. fans the StepOutput out with the reference's ordering invariants
     (cf. execengine.go:474-560): Replicate messages leave BEFORE the
     fsync; hard state + new entries are persisted in ONE batched
     save_raft_state call for every lane; responses (vote grants,
     ReplicateResp) leave only after persistence; committed entries are
     handed to the RSM task workers after persistence.

The host half is vectorized to match the device half: work is driven by a
dirty set (only lanes with pending host events are touched in Python),
ticks are a single engine-global counter folded into one device tick
array (replacing per-lane LocalTick messages, cf. node.go:1152-1159),
per-lane protocol mirrors live in whole-G numpy arrays refreshed from one
`jax.device_get` per step, and lane activation is batched into one
scatter per state field instead of per-lane device dispatches. Idle lanes
cost zero host work per step.

Columnar host dataflow (the step loop's host half stays O(active lanes),
never O(messages) Python):

  pack    - inbox rows are STAGED as column lists (_stage_row) and land in
            the numpy planes as one fancy-indexed scatter per plane
            (_flush_staged_rows), not ten scalar stores per message;
            per-lane mirror reads are gathered once per step as columns.
  fetch   - ONE consolidated device->host transfer of the StepOutput per
            step (_fetch_output, shared by the overlap/non-overlap paths).
            The planes ship together because on every backend the batched
            transfer beats per-plane masked fetches: the arrays are small
            (G- and GxP-sized) and per-dispatch overhead dominates.
  fan-out - each decode phase derives its (g, p)/(g, k) work list from one
            np.nonzero and gathers every needed field as whole columns
            (`arr[gs, ps].tolist()`), so the per-message Python is just
            tuple unpacking + Message construction at the transport
            boundary; batches leave through Node._send_messages ->
            NodeHost._send_messages -> VectorEngine.try_local_deliver_many
            (one queue lock + one wake per destination lane) or
            Transport.send_many (grouped per target address).
  save    - every lane's per-step save is ONE multi-group write wave:
            a single write-batch per touched logdb shard with the
            durability barrier deferred, then one parallel sync over all
            touched WALs (storage/logdb.save_raft_state_deferred +
            storage/kv.sync_all), so a step pays max(fsync) not sum.

This is what closed the 340x kernel-vs-e2e gap of the scalar-dispatch
host loop (BENCH_r05: 7.9M kernel proposals/s vs 23k e2e): the kernel
advances all groups in one compiled step, and the host now fans its
output out in whole-plane numpy instead of per-(group, peer) Python.

Payload bytes never touch the device: the kernel works on (index, term,
is_cc) metadata while the engine keeps an arena of Entry objects keyed by
(lane, real index). The kernel reports where each proposal/replicate landed
(StepOutput.prop_base / rep_base) so the host places payloads at the
device-assigned indexes without guessing.

Node identity on device is the peer slot (0..P-1). The canonical mapping is
rank-in-sorted-order of the member node ids, recomputed whenever membership
changes — a pure function of the (replicated) membership image, so every
replica derives the same mapping at the same applied index. The wire always
carries real node ids and real (un-rebased) indexes.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..config import Config, NodeHostConfig
from ..core.peer import PeerAddress, encode_config_change
from ..core.raft import _make_metadata_entries, _make_witness_snapshot
from ..core.rate import ENTRY_OVERHEAD_BYTES
from ..logger import get_logger
from ..ops.kernel import (
    make_multi_step_fn,
    make_sharded_multi_step_fn,
    make_step_fn,
)
from ..ops.state import (
    CTR,
    CTR_NAMES,
    MSG,
    NEED_SNAPSHOT,
    ROLE,
    RSTATE,
    SEND_HEARTBEAT,
    SEND_REPLICATE,
    SEND_TIMEOUT_NOW,
    SEND_VOTE_REQ,
    Inbox,
    KernelConfig,
    RaftTensors,
    init_state,
    lane_seed,
    make_empty_inbox,
    rebase,
)
from ..profile import (
    HOT_LANE_COUNTERS,
    DeviceCensus,
    compile_watch,
    note_engine_steps,
    note_seam_sync,
    phase_plane,
)
from ..requests import LogicalClock
from ..settings import soft
from ..storage.kv import sync_all as _kv_sync_all
from ..trace import LatencySampler, Profiler, flight_recorder
from ..types import (
    Entry,
    EntryType,
    Message,
    MessageType,
    ReadyToRead,
    Snapshot,
    State,
    SystemCtx,
    Update,
)
from ..rsm.manager import From as OffloadFrom
from .execengine import WorkReady
from .fairness import FairnessWatchdog
from .node import Node

_plog = get_logger("vectorengine")

# One sharded collective program in flight per process: the K>1 mesh
# kernel contains cross-shard exchanges (all-gather / Pallas ring), and
# concurrent launches from co-hosted engines interleave their rendezvous
# on the shared per-device executors — the CPU backend stalls its
# participant threads outright. Production runs one engine per host, so
# serializing launches costs nothing there; multi-NodeHost-in-process
# tests pay a fair round-robin. K=1 sharded and every unsharded path
# have no collectives and never take this lock.
_MESH_LAUNCH_MU = threading.Lock()


class _NoLock:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NO_LOCK = _NoLock()

MT = MessageType

# device index value guard: rebase once any lane's last index crosses this
_REBASE_THRESHOLD = 1 << 30

# ctx encoding over TWO int32 device planes: the low plane carries
# (origin_slot + 1) << 24 | ctx.low[0:24], the high plane ctx.low[24:55].
# 55 bits of the node's sequential read counter plus the origin slot are
# collision-free for any realistic pending window (the reference carries a
# 128-bit random SystemCtx in the message envelope, requests.go:365-381;
# the origin slot rides inside the hint so a leader can route confirmed
# forwarded reads back to the requesting replica, raft.go:1871-1898)
_CTX_LOW_MASK = 0xFFFFFF


def _enc_ctx(origin_slot: int, low: int) -> tuple:
    return (
        ((origin_slot + 1) << 24) | (low & _CTX_LOW_MASK),
        (low >> 24) & 0x7FFFFFFF,
    )


def _ctx_origin(enc_lo: int) -> int:
    return (enc_lo >> 24) - 1


import functools


@functools.lru_cache(maxsize=None)
def _make_activate_fn(cfg: KernelConfig, n: int):
    """Jitted bulk lane activation: scatter n lanes' bring-up values into
    the device state in ONE compiled call. Batches are padded to a few
    fixed bucket sizes (powers of 4) so each (cfg, n) compiles once —
    eagerly dispatched `.at[g].set` chains compile a fresh scatter per
    batch shape, which at fleet bring-up dominated wall clock."""
    P, W, R = cfg.peers, cfg.log_window, cfg.readindex_depth

    def apply(s: RaftTensors, gi, v):
        zi = jnp.zeros((n,), jnp.int32)
        zb = jnp.zeros((n,), bool)
        zip_ = jnp.zeros((n, P), jnp.int32)
        zbp = jnp.zeros((n, P), bool)
        zir = jnp.zeros((n, R), jnp.int32)
        return s._replace(
            active=s.active.at[gi].set(True),
            self_slot=s.self_slot.at[gi].set(v["self_slot"]),
            member=s.member.at[gi].set(v["member"]),
            voting=s.voting.at[gi].set(v["voting"]),
            observer=s.observer.at[gi].set(v["observer"]),
            witness=s.witness.at[gi].set(v["witness"]),
            term=s.term.at[gi].set(v["term"]),
            vote=s.vote.at[gi].set(v["vote"]),
            role=s.role.at[gi].set(v["role"]),
            leader=s.leader.at[gi].set(zi),
            tick_count=s.tick_count.at[gi].set(zi),
            election_tick=s.election_tick.at[gi].set(zi),
            heartbeat_tick=s.heartbeat_tick.at[gi].set(zi),
            election_timeout=s.election_timeout.at[gi].set(
                v["election_timeout"]
            ),
            heartbeat_timeout=s.heartbeat_timeout.at[gi].set(
                v["heartbeat_timeout"]
            ),
            rand_timeout=s.rand_timeout.at[gi].set(v["rand_timeout"]),
            check_quorum=s.check_quorum.at[gi].set(v["check_quorum"]),
            prevote_on=s.prevote_on.at[gi].set(v["prevote_on"]),
            lease_on=s.lease_on.at[gi].set(v["lease_on"]),
            lease_margin=s.lease_margin.at[gi].set(v["lease_margin"]),
            # a reused lane must not inherit its predecessor's lease
            lease_until=s.lease_until.at[gi].set(zi),
            hb_round_tick=s.hb_round_tick.at[gi].set(zi),
            hb_ack_bits=s.hb_ack_bits.at[gi].set(zi),
            first_index=s.first_index.at[gi].set(v["first_index"]),
            marker_term=s.marker_term.at[gi].set(v["marker_term"]),
            last_index=s.last_index.at[gi].set(v["last_index"]),
            committed=s.committed.at[gi].set(v["committed"]),
            processed=s.processed.at[gi].set(v["processed"]),
            applied=s.applied.at[gi].set(v["applied"]),
            unsaved_from=s.unsaved_from.at[gi].set(v["unsaved_from"]),
            log_term=s.log_term.at[gi].set(v["log_term"]),
            log_is_cc=s.log_is_cc.at[gi].set(v["log_is_cc"]),
            match=s.match.at[gi].set(zip_),
            next=s.next.at[gi].set(
                jnp.broadcast_to(v["next"][:, None], (n, P))
            ),
            rstate=s.rstate.at[gi].set(
                jnp.full((n, P), RSTATE.RETRY, jnp.int32)
            ),
            ract=s.ract.at[gi].set(zbp),
            snap_sent=s.snap_sent.at[gi].set(zip_),
            vresp=s.vresp.at[gi].set(zbp),
            vgrant=s.vgrant.at[gi].set(zbp),
            transfer_to=s.transfer_to.at[gi].set(zi),
            transfer_flag=s.transfer_flag.at[gi].set(zb),
            pending_cc=s.pending_cc.at[gi].set(zb),
            quiesce_on=s.quiesce_on.at[gi].set(v["quiesce_on"]),
            quiesce_threshold=s.quiesce_threshold.at[gi].set(
                v["quiesce_threshold"]
            ),
            quiesced=s.quiesced.at[gi].set(zb),
            idle_ticks=s.idle_ticks.at[gi].set(zi),
            ri_ctx=s.ri_ctx.at[gi].set(zir),
            ri_index=s.ri_index.at[gi].set(zir),
            ri_acks=s.ri_acks.at[gi].set(zir),
            ri_count=s.ri_count.at[gi].set(zi),
        )

    return compile_watch().register(
        f"activate[n{n}]", jax.jit(apply, donate_argnums=(0,))
    )


class _SharedClock(LogicalClock):
    """One logical clock shared by every lane of a VectorEngine. The engine
    loop gates the pending-queue gc pass with ONE should_gc() check per
    window (see _run_gc) — Pending*.gc() itself sweeps unconditionally."""


class VectorNode(Node):
    """A Node whose protocol core is a lane of the shared device state.

    The public request surface (propose/read/config-change/snapshot/
    transfer), the RSM manager, the snapshotter drivers and the pending
    notification machinery are all inherited; only the protocol stepping is
    different — there is no Peer, the VectorEngine advances every lane in
    one kernel call. Protocol status (leader/term/role/commit) is read from
    the engine's numpy mirror arrays, refreshed once per kernel step."""

    def _make_clock(self, engine):
        # all lanes share the engine's logical clock so request deadlines
        # are comparable across lanes and gc is one pass, not G passes
        return engine.clock

    def _launch_core(self, cfg, log_reader, peer_addresses, initial, new_node, rng):
        self._vec_initial = initial
        self._vec_new_node = new_node
        self._vec_addresses = list(peer_addresses)
        self._vec_lane = None  # bound by VectorEngine.add_node
        self._vec_wake_counted = False  # see notify_admission
        # snapshot record awaiting persistence on the snapshot worker
        # (handed off by _handle_install_snapshot; at most one in flight —
        # lane.recovering gates re-entry)
        self._vec_install_record = None
        return None  # no scalar Peer

    @property
    def _rate_limited(self) -> bool:
        """Per-lane Config.max_in_mem_log_size enforcement: the arena is
        this replica's in-memory log tier, and its tracked byte size gates
        new proposals (the scalar core additionally aggregates follower
        reports via RATE_LIMIT messages, cf. rate.go; lanes enforce the
        bound locally — device lanes carry no payload bytes to report)."""
        mx = self.config.max_in_mem_log_size
        if not mx:
            return False
        lane = self._vec_lane
        return lane is not None and lane.arena.unapplied_bytes > mx

    @_rate_limited.setter
    def _rate_limited(self, value) -> None:
        # derived live from the lane arena; the base class's cached-flag
        # writes (Node.__init__ / step_node) are meaningless here
        pass

    # ------------------------------------------------------------ status
    def get_leader_id(self) -> int:
        lane = self._vec_lane
        if lane is None or not lane.active:
            return 0
        eng = self.engine
        return lane.rev.get(int(eng._m_leader[lane.g]) - 1, 0)

    def local_status(self):
        lane = self._vec_lane
        if lane is None:
            return {
                "leader_id": 0,
                "term": 0,
                "state": ROLE.FOLLOWER,
                "commit": 0,
                "cluster_id": self.cluster_id,
                "node_id": self._node_id,
                "applied": self.sm.last_applied_index(),
            }
        eng = self.engine
        g = lane.g
        return {
            "leader_id": lane.rev.get(int(eng._m_leader[g]) - 1, 0),
            "term": int(eng._m_term[g]),
            "state": int(eng._m_role[g]),
            "commit": int(eng._m_base[g] + eng._m_commit[g]),
            "cluster_id": self.cluster_id,
            "node_id": self._node_id,
            "applied": self.sm.last_applied_index(),
        }

    def notify_admission(self) -> bool:
        """Serving-front first-admit wake (see Node.notify_admission).
        Vector quiesce lives in the kernel plane; the decode-maintained
        _m_quiesced mirror says whether this lane was quiesced as of its
        last step (zero device syncs). The admitted op's arrival stages
        the wake NOOP itself (_pack wakes quiesced lanes with fresh host
        work); marking the lane ready here just lets the loop turn
        immediately instead of waiting out the pump interval."""
        lane = self._vec_lane
        if lane is None or not lane.active:
            return False
        if not bool(self.engine._m_quiesced[lane.g]):
            self._vec_wake_counted = False
            return False
        # the mirror stays stale until the next decode clears it: a burst
        # of admits against one quiesced lane is ONE quiesced->active
        # transition, so only the first admit reports (and counts) a wake
        # — matching the scalar QuiesceManager.wake_on_admit semantics.
        # Later admits still nudge the loop (cheap, idempotent).
        self.engine.set_node_ready(self.cluster_id)
        if self._vec_wake_counted:
            return False
        self._vec_wake_counted = True
        return True

    def _leader_event(self, leader_id: int, term: int) -> None:
        """Engine loop: the lane's (leader, term) changed this step."""
        if self.events is not None:
            self.events.leader_updated(
                self.cluster_id, self._node_id, leader_id, term
            )

    # ------------------------------------------------- INodeProxy overrides
    def apply_config_change(self, cc) -> None:
        """A config change committed and passed the membership legality
        checks: reconcile the device lane (slot remap) on the engine loop.
        The new member's address registers host-wide first (base-class
        seam): the replicated entry is every replica's routing source."""
        self._register_cc_address(cc)
        self.engine.membership_changed(self)

    def config_change_processed(self, key: int, accepted: bool) -> None:
        self.pending_config_change.apply(key, rejected=not accepted)
        # the device's single-pending-config-change latch opens once the
        # change is applied or rejected (cf. raft.go:1242-1295; the scalar
        # core clears it through apply_config_change/reject_config_change)
        self.engine.cc_processed(self)

    # --------------------------------------------------- snapshot overrides
    def _recover_initial_snapshot_locked(self) -> None:
        from ..rsm import Task

        t = Task(
            cluster_id=self.cluster_id,
            node_id=self._node_id,
            snapshot_available=True,
        )
        self.sm.recover_from_snapshot(t)

    def _do_recover_snapshot(self, task) -> None:
        """InstallSnapshot arrived and the SM recovered from it on a
        snapshot worker; reconcile the device lane and ack the leader
        (cf. node.go:950-965 + raft.go handleInstallSnapshotMessage)."""
        try:
            # persist the snapshot record FIRST (restart safety: the
            # recovery below reads the image through this record) — on
            # THIS worker thread, not the engine loop: the record write is
            # an fsync, and a monolithic install must not stall the whole
            # fleet's super-step cadence (see _handle_install_snapshot)
            ss_rec = self._vec_install_record
            self._vec_install_record = None
            if ss_rec is not None:
                self.logdb.save_raft_state(
                    [
                        Update(
                            cluster_id=self.cluster_id,
                            node_id=self._node_id,
                            snapshot=ss_rec,
                        )
                    ]
                )
            idx = self.sm.recover_from_snapshot(task)
            if idx > 0:
                self.clear_install_aborted()
                ss = self.snapshotter.get_most_recent_snapshot()
                if ss is not None and not ss.is_empty():
                    with self._mu:
                        self.log_reader.apply_snapshot(ss)
                    self.engine.snapshot_restored(self, ss)
                    return
            self.engine.recover_done(self)
        finally:
            self.ss.clear_recovering_from_snapshot()

    def _notify_snapshot_status(self) -> None:
        # the engine loop owns this lane's protocol state (incl. the log
        # reader the finalization mutates): route completions there
        self.engine.snapshot_status_ready(self)


class _Arena:
    """Entry arena over the device window: a RING of W slots indexed by
    real index % W, so placement/lookup are list indexing (a dict per
    index was a measured hot spot across place/send/save/apply) and
    compaction is free — overwriting a slot IS the eviction, exactly when
    the device window has moved past it.

    Byte counters back per-lane Config.max_in_mem_log_size enforcement
    (cf. internal/server/rate.go + inmemory.go size accounting; the arena
    is the vector engine's in-memory log tier): mem_bytes is everything
    resident; unapplied_bytes covers only entries above the applied
    watermark — the real backpressure signal, because applied entries stay
    resident merely as the window's payload cache (the scalar inmem drops
    them instead, inmemory.go appliedLogTo)."""

    __slots__ = (
        "w", "buf", "mem_bytes", "unapplied_bytes", "payload_bytes", "applied"
    )

    def __init__(self, window: int) -> None:
        self.w = window
        self.buf: List[Optional[Entry]] = [None] * window
        self.mem_bytes = 0
        self.unapplied_bytes = 0
        # resident CLIENT-payload bytes only (no per-entry overhead, and
        # config-change entries excluded — their encoded membership cmd
        # is protocol metadata that legitimately reaches witnesses
        # intact, cf. raft.go:742-756): the witness-lane probe — a
        # witness replica must hold ZERO of these, asserted by
        # lane_stats/tests and the observer_witness_churn verdict
        self.payload_bytes = 0
        self.applied = 0

    def __setitem__(self, key: int, entry: Entry) -> None:
        slot = key % self.w
        old = self.buf[slot]
        sz = ENTRY_OVERHEAD_BYTES + len(entry.cmd)
        if old is not None:
            osz = ENTRY_OVERHEAD_BYTES + len(old.cmd)
            self.mem_bytes -= osz
            if old.type != EntryType.CONFIG_CHANGE:
                self.payload_bytes -= len(old.cmd)
            if old.index > self.applied:
                self.unapplied_bytes -= osz
        self.mem_bytes += sz
        if entry.type != EntryType.CONFIG_CHANGE:
            self.payload_bytes += len(entry.cmd)
        if key > self.applied:
            self.unapplied_bytes += sz
        self.buf[slot] = entry

    def get(self, key: int) -> Optional[Entry]:
        e = self.buf[key % self.w]
        return e if e is not None and e.index == key else None

    def __getitem__(self, key: int) -> Entry:
        e = self.buf[key % self.w]
        if e is None or e.index != key:
            raise KeyError(key)
        return e

    def get_run(self, lo: int, hi: int):
        """Entries [lo, hi] inclusive, or (None, missing_index) on a hole."""
        w, buf = self.w, self.buf
        out = []
        for i in range(lo, hi + 1):
            e = buf[i % w]
            if e is None or e.index != i:
                return None, i
            out.append(e)
        return out, -1

    def mark_applied(self, index: int) -> None:
        """Advance the applied watermark; entries in (applied, index] no
        longer count toward unapplied_bytes."""
        w, buf = self.w, self.buf
        dec = 0
        for i in range(self.applied + 1, index + 1):
            e = buf[i % w]
            if e is not None and e.index == i:
                dec += ENTRY_OVERHEAD_BYTES + len(e.cmd)
        self.unapplied_bytes -= dec
        if index > self.applied:
            self.applied = index


class _Lane:
    """Per-group host bookkeeping owned by the engine loop thread. Protocol
    mirrors (term/role/leader/commit/last/first/base) live in the engine's
    whole-G numpy arrays, not here."""

    __slots__ = (
        "g",
        "key",
        "node",
        "cfg",
        "slots",
        "rev",
        "arena",
        "staged_props",
        "staged_reads",
        "staged_ccs",
        "msg_backlog",
        "pack_info",
        "packed_pending",
        "ri_pending",
        "recovering",
        "adopted_term",
        "catchup",
        "snap_inflight",
        "active",
        "cc_inflight",
        "mem_sig",
        "wit_slots",
    )

    def __init__(self, g: int, node: VectorNode, key=None) -> None:
        self.g = g
        self.key = key if key is not None else node.cluster_id
        self.node = node
        self.cfg: Config = node.config
        self.slots: Dict[int, int] = {}  # node_id -> slot
        self.rev: Dict[int, int] = {}  # slot -> node_id
        # ring over the device window; real index -> Entry, size-tracked
        self.arena: _Arena = _Arena(node.engine.kcfg.log_window)
        self.staged_props: deque = deque()  # Entry
        self.staged_reads: deque = deque()  # RequestState
        self.staged_ccs: deque = deque()  # (Entry, key)
        self.msg_backlog: deque = deque()  # wire Messages awaiting a slot
        self.pack_info: Dict[int, tuple] = {}
        self.packed_pending = 0  # entries packed into not-yet-decoded steps
        self.ri_pending: Dict[Tuple[int, int], SystemCtx] = {}  # (lo,hi)->ctx
        self.recovering = False
        # term adopted from an InstallSnapshot sender; the restore ack must
        # carry it or the leader drops the ack as stale. Kept on the lane
        # because the engine's _m_term mirror is rebound from device state
        # every step (the device never saw the snapshot message).
        self.adopted_term = 0
        # slot -> [next_to_send, goal, match_at_progress, progress_tick]
        self.catchup: Dict[int, list] = {}
        # snapshot-status feedback (cf. feedback.go:38-128): slot ->
        # (sent_tick, snapshot_index); a peer that does not ack the
        # snapshot within the retry window gets a synthetic
        # SNAPSHOT_STATUS reject so the kernel un-parks it and the
        # leader retries — a lost InstallSnapshot must not wedge the
        # remote in SNAPSHOT state forever
        self.snap_inflight: Dict[int, Tuple[int, int]] = {}
        self.active = False
        self.cc_inflight = False
        # (members, observers, witnesses) snapshot of the last membership
        # image reconciled onto the device — config changes that restate
        # the same image (e.g. bootstrap CCs) skip the device remap
        self.mem_sig: Optional[tuple] = None
        # peer slots holding WITNESS members: replication toward these is
        # payload-stripped (metadata entries / witness-shaped snapshots,
        # cf. raft.go:742-756) at every host sender site. Maintained by
        # the same three reconcile paths that maintain mem_sig.
        self.wit_slots: frozenset = frozenset()

    # ------------------------------------------------------- slot mapping
    def set_slots(self, member_ids) -> Dict[int, int]:
        """Canonical mapping: rank in sorted member-id order. Returns the
        old->new slot permutation for device remap."""
        new = {nid: i for i, nid in enumerate(sorted(member_ids))}
        perm = {}
        for nid, old_slot in self.slots.items():
            if nid in new:
                perm[old_slot] = new[nid]
        self.slots = new
        self.rev = {s: nid for nid, s in new.items()}
        return perm

    def slot_of(self, node_id: int, provisional: bool = False) -> int:
        s = self.slots.get(node_id)
        if s is not None:
            return s
        if not provisional:
            return -1
        # a sender we have not learned through membership yet (join path):
        # park it on a free slot; the canonical remap fixes it at apply time
        P = self.node.engine.kcfg.peers
        used = set(self.slots.values())
        for s in range(P):
            if s not in used:
                self.slots[node_id] = s
                self.rev[s] = node_id
                return s
        return -1

    def self_slot(self) -> int:
        return self.slots.get(self.node.node_id(), -1)

    def has_staged(self) -> bool:
        return bool(
            self.msg_backlog
            or self.staged_props
            or self.staged_reads
            or self.staged_ccs
        )


# wire type for each device response-plane type (phase-3 fan-out)
_RESP_WIRE = {
    int(MSG.REPLICATE_RESP): MT.REPLICATE_RESP,
    int(MSG.REQUEST_VOTE_RESP): MT.REQUEST_VOTE_RESP,
    int(MSG.REQUEST_PREVOTE_RESP): MT.REQUEST_PREVOTE_RESP,
    int(MSG.HEARTBEAT_RESP): MT.HEARTBEAT_RESP,
    int(MSG.NOOP): MT.NOOP,
}


# ---------------------------------------------------------------------------
# Columnar fan-out: StepOutput planes -> wire Messages.
#
# Each builder derives its work list from ONE np.nonzero over the relevant
# mask, gathers every field it needs as whole columns (`arr[gs, ps]`), and
# only then iterates plain python values — Message objects materialize at
# the transport boundary and nowhere earlier. These are module-level pure
# readers (they mutate no engine state) so the differential test can drive
# them directly against a per-element reference (tests/test_fanout_columnar).
# ---------------------------------------------------------------------------


def _send_target(lane_by_g, g: int, p: int):
    """The fan-out builders' shared skip rules: (lane, to_nid), or None
    when the lane is unoccupied or the peer slot has no known node id.
    One place to extend when a new skip rule applies to every send kind."""
    lane = lane_by_g[g]
    if lane is None:
        return None
    to_nid = lane.rev.get(p)
    if to_nid is None:
        return None
    return lane, to_nid


def gather_replicate_sends(
    o: dict, base, lane_by_g, fetch_from_log=None
) -> List[Tuple[_Lane, Message]]:
    """Phase-1 Replicate materialization (these leave BEFORE the fsync)."""
    sends: List[Tuple[_Lane, Message]] = []
    gs, ps = np.nonzero(o["send_flags"] & SEND_REPLICATE)
    if not gs.size:
        return sends
    cols = zip(
        gs.tolist(),
        ps.tolist(),
        base[gs].tolist(),
        o["term"][gs].tolist(),
        o["send_prev_index"][gs, ps].tolist(),
        o["send_prev_term"][gs, ps].tolist(),
        o["send_n_entries"][gs, ps].tolist(),
        o["send_commit"][gs, ps].tolist(),
    )
    for g, p, b, term, prev, prev_term, n, commit in cols:
        tgt = _send_target(lane_by_g, g, p)
        if tgt is None:
            continue
        lane, to_nid = tgt
        ents, _missing = lane.arena.get_run(b + prev + 1, b + prev + n)
        if ents is None:
            ents = (
                fetch_from_log(lane, b + prev + 1, b + prev + n)
                if fetch_from_log is not None
                else None
            )
            if ents is None:
                _plog.errorf(
                    "%s missing entries for replicate [%d..%d]",
                    lane.node.describe(), b + prev + 1, b + prev + n,
                )
                continue
        if p in lane.wit_slots:
            # witness peers replicate metadata only — payload bytes never
            # leave this host toward a witness
            ents = _make_metadata_entries(ents)
        # causal trace: a sampled entry's trace id rides the Message (and
        # the Entry codec) so the follower stamps the same key. Scanning
        # is bounded by max_entries_per_msg; only the 1-in-N sampled case
        # records anything.
        trace_id = 0
        for e in ents:
            if e.trace_id:
                trace_id = e.trace_id
        if trace_id:
            flight_recorder().record(
                "replicate_send", cluster=lane.node.cluster_id,
                node=lane.node.node_id(), to=to_nid, trace=trace_id,
            )
        sends.append(
            (
                lane,
                Message(
                    type=MT.REPLICATE,
                    cluster_id=lane.node.cluster_id,
                    to=to_nid,
                    from_=lane.node.node_id(),
                    term=term,
                    log_index=b + prev,
                    log_term=prev_term,
                    commit=b + commit,
                    trace_id=trace_id,
                    entries=ents,
                ),
            )
        )
    return sends


def gather_post_sends(o: dict, base, lane_by_g) -> List[Tuple[_Lane, Message]]:
    """Phase-3 broadcast-plane sends (vote requests, heartbeats,
    TimeoutNow), in the same per-kind order the scalar fan-out used."""
    sends: List[Tuple[_Lane, Message]] = []
    send_flags = o["send_flags"]
    term_plane = o["term"]
    role_plane = o["role"]
    gs, ps = np.nonzero(send_flags & SEND_VOTE_REQ)
    if gs.size:
        for g, p, b, term, role, vli, vlt, hint in zip(
            gs.tolist(),
            ps.tolist(),
            base[gs].tolist(),
            term_plane[gs].tolist(),
            role_plane[gs].tolist(),
            o["vote_last_index"][gs].tolist(),
            o["vote_last_term"][gs].tolist(),
            o["send_hint"][gs, ps].tolist(),
        ):
            tgt = _send_target(lane_by_g, g, p)
            if tgt is None:
                continue
            lane, to_nid = tgt
            # the shared vote plane serves both election phases: a
            # PRE_CANDIDATE lane polls with REQUEST_PREVOTE at the
            # PROSPECTIVE term (its own term stays untouched)
            pre = role == ROLE.PRE_CANDIDATE
            sends.append(
                (
                    lane,
                    Message(
                        type=MT.REQUEST_PREVOTE if pre else MT.REQUEST_VOTE,
                        cluster_id=lane.node.cluster_id,
                        to=to_nid,
                        from_=lane.node.node_id(),
                        term=term + 1 if pre else term,
                        log_index=b + vli,
                        log_term=vlt,
                        hint=hint,
                    ),
                )
            )
    gs, ps = np.nonzero(send_flags & SEND_HEARTBEAT)
    if gs.size:
        for g, p, b, term, hb_commit, hint, hint2, lease_round in zip(
            gs.tolist(),
            ps.tolist(),
            base[gs].tolist(),
            term_plane[gs].tolist(),
            o["send_hb_commit"][gs, ps].tolist(),
            o["send_hint"][gs, ps].tolist(),
            o["send_hint2"][gs, ps].tolist(),
            o["lease_round"][gs].tolist(),
        ):
            tgt = _send_target(lane_by_g, g, p)
            if tgt is None:
                continue
            lane, to_nid = tgt
            sends.append(
                (
                    lane,
                    Message(
                        type=MT.HEARTBEAT,
                        cluster_id=lane.node.cluster_id,
                        to=to_nid,
                        from_=lane.node.node_id(),
                        term=term,
                        # lease round tag: an opaque tick stamp the follower
                        # echoes back, NOT an index — no +b translation
                        log_index=lease_round,
                        commit=b + hb_commit,
                        hint=hint,
                        hint_high=hint2,
                    ),
                )
            )
    gs, ps = np.nonzero(send_flags & SEND_TIMEOUT_NOW)
    if gs.size:
        for g, p, term in zip(
            gs.tolist(), ps.tolist(), term_plane[gs].tolist()
        ):
            tgt = _send_target(lane_by_g, g, p)
            if tgt is None:
                continue
            lane, to_nid = tgt
            sends.append(
                (
                    lane,
                    Message(
                        type=MT.TIMEOUT_NOW,
                        cluster_id=lane.node.cluster_id,
                        to=to_nid,
                        from_=lane.node.node_id(),
                        term=term,
                    ),
                )
            )
    return sends


def gather_resp_sends(o: dict, base, lane_by_g) -> List[Tuple[_Lane, Message]]:
    """Phase-3 response-plane sends: one reply per consumed inbox slot."""
    sends: List[Tuple[_Lane, Message]] = []
    resp_type = o["resp_type"]
    gs, ks = np.nonzero(resp_type != MSG.NONE)
    if not gs.size:
        return sends
    cols = zip(
        gs.tolist(),
        base[gs].tolist(),
        resp_type[gs, ks].tolist(),
        o["resp_to"][gs, ks].tolist(),
        o["resp_term"][gs, ks].tolist(),
        o["resp_log_index"][gs, ks].tolist(),
        o["resp_reject"][gs, ks].tolist(),
        o["resp_hint"][gs, ks].tolist(),
        o["resp_hint2"][gs, ks].tolist(),
    )
    for g, b, t, to_slot, term, log_index, reject, hint, hint2 in cols:
        tgt = _send_target(lane_by_g, g, to_slot)
        if tgt is None:
            continue
        lane, to_nid = tgt
        if to_nid == lane.node.node_id():
            continue  # self-addressed (e.g. local election artifacts)
        wire = _RESP_WIRE.get(t)
        if wire is None:
            continue
        trace_id = 0
        if wire == MT.REPLICATE_RESP:
            log_index += b
            hint += b
            # ack hop of the causal chain: if the ACCEPTED index is a
            # sampled entry this follower placed, carry its trace id back
            # (one arena ring probe; records only on the 1-in-N case).
            # Best-effort by design: a sampled entry that is not the last
            # of its acked run goes unprobed, and rejected acks never
            # probe — a reject's hint index can land on a stale
            # conflicting arena entry and would misattribute an unrelated
            # proposal's chain.
            if not reject:
                te = lane.arena.get(log_index)
                if te is not None:
                    trace_id = te.trace_id
            if trace_id:
                flight_recorder().record(
                    "replicate_ack", cluster=lane.node.cluster_id,
                    node=lane.node.node_id(), to=to_nid, trace=trace_id,
                    index=log_index,
                )
        sends.append(
            (
                lane,
                Message(
                    type=wire,
                    cluster_id=lane.node.cluster_id,
                    to=to_nid,
                    from_=lane.node.node_id(),
                    term=term,
                    log_index=log_index,
                    reject=bool(reject),
                    hint=hint,
                    hint_high=hint2,
                    trace_id=trace_id,
                ),
            )
        )
    return sends


def build_save_updates(o: dict, base, lane_by_g):
    """Phase-2 hard-state/entry persistence as (updates, lane_saves): the
    whole step's saves gathered columnar, written downstream as ONE
    multi-group write wave."""
    updates: List[Update] = []
    lane_saves: List[Tuple[_Lane, List[Entry], State]] = []
    gs = np.nonzero((o["save_from"] > 0) | o["hard_changed"])[0]
    if not gs.size:
        return updates, lane_saves
    cols = zip(
        gs.tolist(),
        base[gs].tolist(),
        o["save_from"][gs].tolist(),
        o["save_to"][gs].tolist(),
        o["vote"][gs].tolist(),
        o["term"][gs].tolist(),
        o["commit_index"][gs].tolist(),
        o["hard_changed"][gs].tolist(),
    )
    for g, b, sf, st_, vote_slot, term, commit, hard_changed in cols:
        lane = lane_by_g[g]
        if lane is None or not lane.active:
            continue
        ents: List[Entry] = []
        if sf > 0:
            ents, missing_at = lane.arena.get_run(b + sf, b + st_)
            if ents is None:
                _plog.errorf(
                    "%s missing arena entry %d for save",
                    lane.node.describe(), missing_at,
                )
                ents = []
        state = State(
            term=term,
            vote=lane.rev.get(vote_slot - 1, 0) if vote_slot > 0 else 0,
            commit=b + commit,
        )
        if ents or hard_changed:
            updates.append(
                Update(
                    cluster_id=lane.node.cluster_id,
                    node_id=lane.node.node_id(),
                    state=state,
                    entries_to_save=ents,
                )
            )
            lane_saves.append((lane, ents, state))
    return updates, lane_saves


class VectorEngine:
    """Engine-compatible facade (add/remove/set_*_ready/stop) around the
    single-stepper loop that advances all lanes per kernel call."""

    def __init__(
        self,
        logdb,
        nh_config: Optional[NodeHostConfig] = None,
        num_task_workers: Optional[int] = None,
        num_snapshot_workers: int = 2,
    ) -> None:
        self._logdb = logdb
        ecfg = nh_config.engine if nh_config is not None else None
        self.kcfg = KernelConfig(
            groups=ecfg.max_groups if ecfg else 64,
            peers=ecfg.max_peers if ecfg else 8,
            log_window=ecfg.log_window if ecfg else 128,
            inbox_depth=ecfg.inbox_depth if ecfg else 8,
            max_entries_per_msg=(
                getattr(ecfg, "max_entries_per_msg", 8) if ecfg else 8
            ),
            readindex_depth=ecfg.readindex_depth if ecfg else 4,
        )
        if self.kcfg.max_entries_per_msg > self.kcfg.log_window:
            # the kernel's ring-slot scatter maps each written index to a
            # unique slot only while a message's span fits the window
            raise ValueError(
                f"max_entries_per_msg ({self.kcfg.max_entries_per_msg}) must "
                f"not exceed log_window ({self.kcfg.log_window})"
            )
        # multi-device: shard the group axis over every visible device
        # (SURVEY §2.9.1 — groups are independent Raft instances, so the
        # kernel partitions along G with zero collectives on the hot path)
        self._sharding = None
        self._inbox_shardings = None  # cached pytree; shapes never change
        self._multi_shardings = None  # K>1 twin: (inbox, ticks, route, rdelta)
        self._mesh = None
        self._mesh_devices = 0  # 0 = unsharded single-device engine
        groups_requested = self.kcfg.groups
        if (
            ecfg is not None
            and getattr(ecfg, "shard_over_mesh", False)
            and jax.device_count() > 1
        ):
            from jax.sharding import Mesh, NamedSharding, PartitionSpec

            devs = jax.devices()
            n = len(devs)
            if self.kcfg.groups % n:
                # round UP to a device multiple so every shard holds the
                # same block. NOT silent: the shortfall is stamped in
                # step_stats (padded_groups/mesh_devices -> engine_step_*
                # gauges + bench JSON) and the ghost lanes are never
                # handed out by the allocator, so lane_stats never
                # reports them
                self.kcfg = self.kcfg._replace(
                    groups=((self.kcfg.groups + n - 1) // n) * n
                )
            mesh = Mesh(np.array(devs), ("groups",))
            self._mesh = mesh
            self._mesh_devices = n

            def _shard_for(x, _mesh=mesh, _NS=NamedSharding, _P=PartitionSpec):
                # canonical spec: trailing dims replicate implicitly. An
                # explicit trailing None is the SAME placement but a
                # DIFFERENT jit cache key than the normalized spec jit
                # outputs carry, so a fresh device_put state would re-trace
                # every activation bucket once — and whether that second
                # trace lands before or after a compile-audit mark depends
                # on how lane-add batches happen to coalesce
                return _NS(_mesh, _P("groups"))

            self._sharding = _shard_for
        self._groups_requested = groups_requested
        self._padded_groups = self.kcfg.groups - groups_requested
        self.clock = _SharedClock()
        # device-resident multi-step: K protocol steps per kernel launch
        # (EngineConfig.steps_per_sync). K=1 keeps the classic one-step
        # loop byte-identical; K>1 runs the scanned super-step path.
        self._multi = (
            max(1, int(getattr(ecfg, "steps_per_sync", 1) or 1))
            if ecfg
            else 1
        )
        ov = getattr(ecfg, "overlap_decode", None) if ecfg else None
        if ov is None:
            ov = jax.default_backend() != "cpu"  # auto: see EngineConfig
        if self._multi > 1:
            # the super-step IS the pipelining: dispatch/fetch amortize
            # over K steps, and the pack path needs the PREVIOUS fetch's
            # residual-inbox occupancy (overlap would make it two steps
            # stale and clobber device-routed residual rows)
            ov = False
        self._overlap = bool(ov)
        self._pending = None  # in-flight (work, packs, StepOutput future)
        self._rebase_due = False
        # stage profiler for the hot loop (cf. reference execengine.go
        # :197-211 + trace.go:98-162). Sparse sampling by default (1/32):
        # per-step full sampling is pure hot-loop overhead in production;
        # benches and debugging opt into every-step recording through
        # EngineConfig.profile_sample_ratio=1.
        ratio = (getattr(ecfg, "profile_sample_ratio", 0) or 0) if ecfg else 0
        self.profiler = Profiler(sample_ratio=ratio if ratio > 0 else 32)
        # sampled stage durations also land in the process-global phase
        # plane (engine_phase_seconds{engine="vector",phase=...} + flight-
        # recorder spans); unsampled steps never reach it
        self.profiler.attach_phase_plane(phase_plane(), "vector")
        # request-lifecycle latency sampling shares the profiler's ratio
        # knob: 1-in-N proposals/reads carry a LatencyTrace into the
        # proposal_commit/apply and readindex latency histograms; the
        # other N-1 stay allocation-free (see trace.LatencySampler)
        self.request_sampler = LatencySampler(ratio if ratio > 0 else 32)
        # per-step counters accumulated inline by the decode phases on
        # objects they already materialize (no extra device syncs, no
        # extra numpy reductions); exported via step_stats() and folded
        # into NodeHost._export_health_gauges as engine_step_* gauges
        self._sstats = {
            "steps": 0,
            "msgs_replicate": 0,  # phase-1 Replicate messages out
            "msgs_broadcast": 0,  # phase-3 votes/heartbeats/TimeoutNow out
            "msgs_resp": 0,  # phase-3 response-plane messages out
            "lanes_commit_advanced": 0,  # lanes handing commits to the RSM
            "leader_changes": 0,  # (leader, term) transitions observed
            "elections_started": 0,  # lanes that went leaderless
            "entries_applied": 0,  # entries handed to the RSM
            # multi-step engine: co-hosted messages routed ON DEVICE
            # between inner steps (zero host Message objects each)
            "msgs_routed_device": 0,
            # sharded mesh: ghost lanes added by the device-multiple
            # round-up (never allocated) and the mesh width — static
            # stamps, not counters, so bench JSON and gauges can tell a
            # padded sharded run from an exact one
            "padded_groups": self._padded_groups,
            "mesh_devices": self._mesh_devices,
        }
        # ---- tick-fairness watchdog (ROADMAP seed flake) -----------------
        # Inter-iteration latency vs the host's tick period, a starvation
        # gauge, and an enforced yield when a long kernel step starved a
        # co-scheduled peer loop (see engine/fairness.py).
        tick_s = (
            (nh_config.rtt_millisecond or 50) / 1000.0
            if nh_config is not None
            else 0.05
        )
        yield_ms = getattr(ecfg, "fairness_yield_ms", None) if ecfg else None
        self.watchdog = FairnessWatchdog(
            "vec-step",
            tick_s,
            # 0 disables enforcement (measurement stays on); None = auto
            yield_threshold_s=(
                float("inf") if yield_ms == 0
                else (yield_ms / 1000.0 if yield_ms else None)
            ),
        )
        # per-step replay clamp for coalesced tick backlogs: replaying a
        # stall's whole backlog at election-RTT granularity expires every
        # follower's randomized timer in the same step (synchronized
        # split-vote storms after any multi-second stall — the seed
        # flake); 0 = auto: clamp at each lane's heartbeat RTT
        self._catchup_tick_cap = (
            getattr(ecfg, "max_catchup_ticks", 0) or 0 if ecfg else 0
        )
        self._last_tick_burst = 0
        self._step_fn = make_step_fn(self.kcfg, donate=True)
        # runtime retrace attribution: the step kernel's trace cache is
        # watched per function; a steady-state compile shows up in
        # engine_compile_events_total and fails the perf tier-1 assertion
        compile_watch().install().register(
            f"step_batch[g{self.kcfg.groups}]", self._step_fn
        )
        # ---- multi-step (K>1) state --------------------------------------
        # the device route table (lane index of the co-hosted replica
        # behind each peer slot, -1 = host path) + window-base deltas,
        # rebuilt on the loop thread whenever lane topology changes; the
        # device-resident residual inbox (the last inner step's routed
        # messages, consumed by the next super-step's inner step 0) and
        # its fetched per-lane occupancy; and the routed-Replicate
        # payload placements awaiting their acceptance report.
        G = self.kcfg.groups
        self._m_resid = np.zeros(G, np.int32)
        self._pending_rep_copies: list = []
        self._routes_dirty = True
        if self._multi > 1:
            if self._mesh is not None:
                # K-step kernel over the mesh: cross-shard lane traffic
                # moves device-to-device inside the launch (Pallas ring
                # on TPU, all-gather elsewhere); the host path stays the
                # fallback for lanes the route table marks -1
                self._multi_fn = make_sharded_multi_step_fn(
                    self.kcfg, self._multi, self._mesh
                )
                name = f"multi_step[g{G}.k{self._multi}.d{self._mesh_devices}]"
            else:
                self._multi_fn = make_multi_step_fn(self.kcfg, self._multi)
                name = f"multi_step[g{G}.k{self._multi}]"
            # no comma in the name: it becomes a Prometheus label value
            compile_watch().register(name, self._multi_fn)
            self._np_route = np.full((G, self.kcfg.peers), -1, np.int32)
            self._np_rdelta = np.zeros((G, self.kcfg.peers), np.int32)
            resid = make_empty_inbox(self.kcfg)
            if self._sharding is not None:
                # the residual inbox must live on the mesh like the rest
                # of the lane state, or every launch would reshard it
                self._resid = jax.device_put(
                    resid, jax.tree_util.tree_map(self._sharding, resid)
                )
            else:
                self._resid = jax.device_put(resid)
        self._state: RaftTensors = init_state(self.kcfg)
        if self._sharding is not None:
            self._state = jax.tree.map(
                lambda x: jax.device_put(x, self._sharding(x)), self._state
            )
        # lanes keyed by (host, cluster_id): a SHARED core hosts replicas
        # from several NodeHosts (hosts = handle ids), so cluster_id alone
        # does not identify a lane
        self._lanes: Dict[tuple, _Lane] = {}
        # (cluster_id, node_id) -> lane, for in-core message short-circuit
        self._route: Dict[tuple, _Lane] = {}
        # ghost lanes from the sharded round-up are NOT capacity: the
        # allocator only hands out the lanes the caller configured, so
        # padded lanes never reach _lanes / lane_stats / gauges
        self._free = list(range(self._groups_requested - 1, -1, -1))
        self._lanes_mu = threading.RLock()
        self._reconq: deque = deque()  # host->device ops, loop-applied
        self._stopped = threading.Event()
        self._ready = threading.Event()
        # crash teardown flag (stop(flush=False)): the loop discards its
        # un-decoded in-flight step instead of landing it
        self._discard_pending = False
        # ---- host sharing (handles) --------------------------------------
        self._hosts_mu = threading.Lock()
        self._host_refs: Set[int] = set()
        self._next_host = 0
        self._blocked_hosts: Set[int] = set()  # partitioned NodeHosts
        # per-host clock-suspect deadlines (monotonic seconds): a host
        # whose tick worker reported a clock anomaly loses lease rights
        # (clock_ok=False) on all its lanes until the hold expires.
        # Written by tick workers under _dirty_mu, reconciled onto the
        # device clock_ok plane by the loop thread on transitions only.
        self._clock_suspect: Dict[int, float] = {}
        # cumulative lease read counters (loop-thread writes, lock-free
        # int reads via lease_stats)
        self._lease_local = 0
        self._lease_fb = 0
        # chaos hook over co-hosted delivery (the analogue of the
        # transport's pre-send hook for traffic that never touches the
        # wire): return True to drop the message
        self._local_drop_hook = None
        # ---- host-event staging (producers: API/transport threads) -------
        self._dirty_mu = threading.Lock()
        self._dirty: Set[tuple] = set()  # lane keys with host events
        self._gc_set: Set[tuple] = set()  # lane keys with pending requests
        self._pending_ticks: Dict[int, int] = {}  # host -> coalesced ticks
        # ---- serving-plane backpressure mirrors --------------------------
        # refreshed once per _pack from data the pack pass already touches
        # (zero device syncs); read lock-free by pressure_stats — a torn
        # read costs one stale sample, never a wrong decision stream
        self._p_inbox_rows = 0
        self._p_inbox_lanes = 0
        self._p_staged_backlog = 0
        # ---- loop-thread-only work sets ----------------------------------
        self._carry: Set[_Lane] = set()  # lanes with leftover staged work
        self._catchups: Set[_Lane] = set()  # lanes replaying host log
        self._snapfb: Set[_Lane] = set()  # lanes with in-flight snapshots
        # nodes with completed snapshot work awaiting finalization on this
        # loop (cf. node.go processSaveStatus; scalar nodes do this in
        # step_node)
        self._snap_status: Set[VectorNode] = set()
        self._snap_status_mu = threading.Lock()
        self._alloc_buffers()
        self._alloc_mirrors()
        # HBM census (profile.DeviceCensus): plane bytes are STATIC
        # tensor metadata (shapes never change over the engine's life),
        # reported once here from `.nbytes` — device_census() later folds
        # the logical log fill from the decode-maintained mirrors, so
        # reading the census costs zero device syncs at any point
        self.census = DeviceCensus()
        planes = {
            f"state.{name}": int(arr.nbytes)
            for name, arr in self._state._asdict().items()
        }
        if self._multi > 1:
            for name, arr in self._resid._asdict().items():
                planes[f"resid.{name}"] = int(arr.nbytes)
        staging = sum(
            int(plane.nbytes)
            for buf, ticks, _inbox in self._bufsets
            for plane in list(buf.values()) + [ticks]
        )
        self.census.set_planes(
            planes,
            log_planes=("state.log_term", "state.log_is_cc"),
            devices=max(1, self._mesh_devices),
            log_window=self.kcfg.log_window,
            host_staging_bytes=staging,
        )
        # worker pools for apply + snapshot work (same split as ExecEngine)
        self._n_task = num_task_workers or min(
            soft.step_engine_task_worker_count, 4
        )
        self._n_snap = num_snapshot_workers
        self.task_ready = WorkReady(self._n_task)
        self.snapshot_ready = WorkReady(self._n_snap)
        self._threads: List[threading.Thread] = []
        t = threading.Thread(target=self._loop, name="vec-step", daemon=True)
        t.start()
        self._threads.append(t)
        for i in range(self._n_task):
            t = threading.Thread(
                target=self._task_worker_main, args=(i,), name=f"vtask-{i}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        for i in range(self._n_snap):
            t = threading.Thread(
                target=self._snapshot_worker_main, args=(i,), name=f"vsnap-{i}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)

    def _alloc_buffers(self) -> None:
        # numpy staging buffers for the inbox. TWO sets: with overlapped
        # decode, step t's buffers must stay untouched while the device may
        # still be reading them, so pack alternates between the sets.
        G, K = self.kcfg.groups, self.kcfg.inbox_depth
        E = self.kcfg.max_entries_per_msg

        def mk():
            return {
                "mtype": np.full((G, K), MSG.NONE, np.int32),
                "from_slot": np.zeros((G, K), np.int32),
                "term": np.zeros((G, K), np.int32),
                "log_index": np.zeros((G, K), np.int32),
                "log_term": np.zeros((G, K), np.int32),
                "commit": np.zeros((G, K), np.int32),
                "reject": np.zeros((G, K), bool),
                "hint": np.zeros((G, K), np.int32),
                "hint_high": np.zeros((G, K), np.int32),
                "n_entries": np.zeros((G, K), np.int32),
                "entry_terms": np.zeros((G, K, E), np.int32),
                "entry_cc": np.zeros((G, K, E), bool),
            }

        self._bufsets = []
        for _ in range(2 if self._overlap else 1):
            buf = mk()
            ticks = np.zeros((G,), np.int32)
            inbox = Inbox(**{f: buf[f] for f in Inbox._fields})
            self._bufsets.append((buf, ticks, inbox))
        self._buf_idx = 0
        self._buf, self._ticks, self._host_inbox = self._bufsets[0]
        # columnar row staging for _pack: rows accumulate as python column
        # lists and land in the numpy planes as ONE fancy-indexed scatter
        # per plane (_flush_staged_rows) — list appends are ~4x cheaper
        # than per-row scalar numpy stores across ten planes
        self._rows = {
            "g": [], "k": [], "mtype": [], "from_slot": [], "term": [],
            "log_index": [], "log_term": [], "commit": [], "reject": [],
            "hint": [], "hint_high": [], "n_entries": [], "ents": [],
        }
        if self._sharding is not None:
            # shapes identical across the sets: one sharding pytree serves
            self._inbox_shardings = (
                jax.tree_util.tree_map(self._sharding, self._host_inbox),
                self._sharding(self._ticks),
            )
            if self._multi > 1:
                # the K>1 transfer also ships the route/delta planes
                self._multi_shardings = self._inbox_shardings + (
                    self._sharding(self._np_route),
                    self._sharding(self._np_rdelta),
                )

    def _alloc_mirrors(self) -> None:
        """Whole-G numpy mirrors of per-lane protocol state, refreshed from
        the StepOutput once per step (device units where applicable)."""
        G = self.kcfg.groups
        self._lane_by_g: List[Optional[_Lane]] = [None] * G
        self._m_base = np.zeros(G, np.int64)  # real = device + base
        self._m_devfirst = np.ones(G, np.int64)  # device-units first index
        self._m_term = np.zeros(G, np.int32)
        self._m_role = np.full(G, ROLE.FOLLOWER, np.int32)
        self._m_leader = np.zeros(G, np.int32)  # slot+1, 0=none
        self._m_commit = np.zeros(G, np.int64)  # device units
        self._m_last = np.zeros(G, np.int64)  # device units
        self._m_tick_cap = np.ones(G, np.int32)  # election_rtt per lane
        self._m_active = np.zeros(G, bool)
        self._m_snap_every = np.zeros(G, np.int64)  # cfg.snapshot_entries
        self._m_applied_since = np.zeros(G, np.int64)
        self._m_snap_pending = np.zeros(G, bool)
        self._m_quiesced = np.zeros(G, bool)
        self._m_host = np.zeros(G, np.int32)  # owning handle id per lane
        self._m_clock_ok = np.ones(G, bool)  # mirror of device clock_ok
        # lease validity after the last decoded step (StepOutput.lease_ok):
        # read by the lease-only probe (NodeHost.lease_read) with zero
        # device syncs; a stale read is inherent to probing and safe — the
        # serve itself is decided by the kernel, not this mirror
        self._m_lease_ok = np.zeros(G, bool)
        # engine-clock tick of the lane's last LEADER transition: feeds the
        # per-lane ticks_since_leader_change gauge (lane_stats) with zero
        # device syncs — updated only for lanes the decode phase already
        # iterates as changed
        self._m_leader_change_tick = np.zeros(G, np.int64)
        # cumulative per-lane protocol-event counters: the kernel's
        # per-step u32 deltas (StepOutput.counters, one CTR.* column per
        # event) summed here by the decode fold — loop-thread writes,
        # lock-free reads via counter_stats/lane_counters (a torn read
        # costs one stale sample on an export path, never a decision)
        self._ctr = np.zeros((G, CTR.COUNT), np.uint64)

    # ------------------------------------------------------- mirror helpers
    def _committed_real(self, g: int) -> int:
        return int(self._m_base[g] + self._m_commit[g])

    def _last_real(self, g: int) -> int:
        return int(self._m_base[g] + self._m_last[g])

    # --------------------------------------------------------- registration
    def add_node(self, node: VectorNode, host: int = 0) -> None:
        key = (host, node.cluster_id)
        lane = None
        for attempt in range(2):
            with self._lanes_mu:
                if self._free:
                    g = self._free.pop()
                    lane = _Lane(g, node, key=key)
                    self._lanes[key] = lane
                    self._lane_by_g[g] = lane
                    self._route[(node.cluster_id, node.node_id())] = lane
                    self._m_host[g] = host
                    break
            if attempt == 0:
                # the free list can be momentarily empty while freed lanes
                # sit in the reconcile queue (stop_cluster immediately
                # followed by restart_cluster): drain the loop once so a
                # restart is never failed by its own predecessor's
                # not-yet-reaped lane
                self.drain(10.0)
        if lane is None:
            raise RuntimeError(
                f"vector engine lane capacity ({self.kcfg.groups}) exhausted"
            )
        node._vec_lane = lane
        self._reconq.append(("activate", lane))
        self.set_node_ready(key)

    def remove_node(self, key) -> None:
        with self._lanes_mu:
            lane = self._lanes.pop(key, None)
            if lane is not None:
                rk = (lane.node.cluster_id, lane.node.node_id())
                if self._route.get(rk) is lane:
                    del self._route[rk]
        if lane is not None:
            self._reconq.append(("deactivate", lane))
            self._ready.set()

    def get_node(self, key):
        with self._lanes_mu:
            lane = self._lanes.get(key)
        return lane.node if lane is not None else None

    def lease_valid(self, key) -> bool:
        """Did this lane hold a live leader lease after the last decoded
        step? Mirror read (no device sync) for the lease-only probe;
        the authoritative serve/fallback decision stays in the kernel."""
        with self._lanes_mu:
            lane = self._lanes.get(key)
        return lane is not None and bool(self._m_lease_ok[lane.g])

    # -------------------------------------------------------------- wakeups
    def set_node_ready(self, key) -> None:
        with self._dirty_mu:
            self._dirty.add(key)
            self._gc_set.add(key)
        self._ready.set()

    def _wake(self, key) -> None:
        """Like set_node_ready but without arming request GC — the hot path
        for message delivery (messages alone never need a timeout sweep)."""
        with self._dirty_mu:
            self._dirty.add(key)
        self._ready.set()

    def global_tick(self, host: int = 0) -> None:
        """One logical tick for every lane of `host` (replaces per-lane
        LocalTick messages; the loop folds counts into the device tick
        array, per owning host)."""
        with self._dirty_mu:
            self._pending_ticks[host] = self._pending_ticks.get(host, 0) + 1
        self._ready.set()

    def set_task_ready(self, key) -> None:
        self.task_ready.notify(key)

    def set_snapshot_ready(self, key) -> None:
        self.snapshot_ready.notify(key)

    # ------------------------------------------------------ local delivery
    def try_local_deliver(self, m: Message) -> bool:
        """Deliver a wire message directly to a co-hosted lane of this core
        (same engine => same process), skipping the transport and codec
        entirely. This is the host half of SURVEY §7's 'co-hosted replica
        exchange': replicas that advance in one kernel step exchange their
        protocol traffic through the shared inbox, not the network.
        InstallSnapshot is excluded — snapshot images move through the
        streaming path so the receiver owns its on-disk copy."""
        if m.type == MT.INSTALL_SNAPSHOT:
            return False
        lane = self._route.get((m.cluster_id, m.to))
        if lane is None:
            return False
        if lane.key[0] in self._blocked_hosts:
            # the receiving NodeHost simulates a partition: co-hosted
            # traffic must drop exactly like the wire path does
            # (nodehost.handle_message_batch returns early when
            # partitioned)
            return True
        hook = self._local_drop_hook
        if hook is not None and hook(m):
            return True  # dropped by chaos hook
        node = lane.node
        if node.stopped or not node.mq.add(m):
            return False
        self._wake(lane.key)
        return True

    def try_local_deliver_many(self, msgs: List[Message]) -> List[Message]:
        """Bulk co-hosted delivery: group the batch by destination lane,
        enqueue each lane's messages under ONE queue lock, mark every
        receiver dirty under ONE engine lock and wake the loop once.
        Returns the messages that must ride the wire instead (no co-hosted
        lane, stopped node, or a full receive queue — the same per-message
        fallthrough try_local_deliver reports with False)."""
        rest: List[Message] = []
        by_lane: Dict[_Lane, List[Message]] = {}
        route = self._route
        blocked = self._blocked_hosts
        hook = self._local_drop_hook
        for m in msgs:
            if m.type == MT.INSTALL_SNAPSHOT:
                rest.append(m)
                continue
            lane = route.get((m.cluster_id, m.to))
            if lane is None:
                rest.append(m)
                continue
            if lane.key[0] in blocked:
                continue  # partitioned receiver: drop like the wire path
            if hook is not None and hook(m):
                continue  # dropped by chaos hook
            lst = by_lane.get(lane)
            if lst is None:
                lst = by_lane[lane] = []
            lst.append(m)
        if not by_lane:
            return rest
        woke = []
        for lane, ms in by_lane.items():
            node = lane.node
            if node.stopped:
                rest.extend(ms)
                continue
            taken = node.mq.add_many(ms)
            if taken < len(ms):
                rest.extend(ms[taken:])
            if taken:
                woke.append(lane.key)
        if woke:
            with self._dirty_mu:
                self._dirty.update(woke)
            self._ready.set()
        return rest

    def set_host_partitioned(self, host: int, partitioned: bool) -> None:
        if partitioned:
            self._blocked_hosts.add(host)
        else:
            self._blocked_hosts.discard(host)
        # multi-step: a partitioned host's lanes must drop out of the
        # on-device routing table (its traffic falls back to the host
        # path, where the partition drop applies)
        self._routes_dirty = True

    def set_clock_suspect(self, host: int, hold_s: float) -> None:
        """Clock-anomaly report from a host's tick worker (backward
        reading / backlog past the catch-up cap): every lane owned by
        `host` loses lease rights (clock_ok=False) until the hold
        expires — lease reads degrade to the ReadIndex quorum path,
        never to staleness. Cheap to call; the loop thread touches the
        device only on suspect-set transitions."""
        deadline = time.monotonic() + max(float(hold_s), 0.0)
        with self._dirty_mu:
            cur = self._clock_suspect.get(host, 0.0)
            self._clock_suspect[host] = max(cur, deadline)
        self._ready.set()

    def _apply_clock_suspect(self) -> None:
        """Loop-thread reconcile of the per-host suspect deadlines onto
        the per-lane clock_ok plane. No-op (one dict probe) while no host
        is suspect; while one is, a G-bool compare per iteration and a
        device write only when the lane set actually changes — including
        the final restore when the last hold expires."""
        if not self._clock_suspect and self._m_clock_ok.all():
            return
        now = time.monotonic()
        with self._dirty_mu:
            for h in [
                h for h, d in self._clock_suspect.items() if d <= now
            ]:
                del self._clock_suspect[h]
            bad = list(self._clock_suspect)
        if bad:
            want = ~np.isin(self._m_host, np.asarray(bad, np.int32))
        else:
            want = np.ones(self.kcfg.groups, bool)
        if not np.array_equal(want, self._m_clock_ok):
            self._m_clock_ok = want
            arr = jnp.asarray(want)
            if self._sharding is not None:
                arr = jax.device_put(arr, self._sharding(arr))
            self._state = self._state._replace(clock_ok=arr)

    def set_local_drop_hook(self, hook) -> None:
        """Install a chaos drop predicate over co-hosted delivery
        (hook(message) -> True drops it). None clears. While a hook is
        installed the multi-step engine disables on-device routing
        entirely: every co-hosted message must pass the hook, which only
        the host path can evaluate."""
        self._local_drop_hook = hook
        self._routes_dirty = True

    # ------------------------------------------------- host->device bridges
    def membership_changed(self, node: VectorNode) -> None:
        """Called on a task worker when a config change applies; the loop
        recomputes the canonical slot mapping from the SM membership."""
        self._reconq.append(("membership", node))
        self._ready.set()

    def snapshot_restored(self, node: VectorNode, ss: Snapshot) -> None:
        self._reconq.append(("restore", node, ss))
        self._ready.set()

    def cc_processed(self, node: VectorNode) -> None:
        self._reconq.append(("cc_done", node))
        self._ready.set()

    def recover_done(self, node: VectorNode) -> None:
        self._reconq.append(("recover_done", node))
        self._ready.set()

    # ---------------------------------------------------------------- loop
    def _loop(self) -> None:
        period = 0.002
        wd = self.watchdog
        while not self._stopped.is_set():
            self._ready.wait(period)
            self._ready.clear()
            if self._stopped.is_set():
                break
            t0 = wd.iter_begin()
            self._last_tick_burst = 0
            try:
                self._run_once()
            except Exception:
                import traceback

                traceback.print_exc()
            wd.iter_end(t0, ticks=self._last_tick_burst, steps=self._multi)
        try:
            if self._discard_pending:
                # crash teardown (stop(flush=False)): the un-decoded
                # in-flight step dies undecoded — a SIGKILL'd process
                # would never have fanned it out or saved it, and chaos
                # restarts must not silently grant that durability
                self._pending = None
            else:
                self._flush_pending()  # the last step's saves must land
        except Exception:
            import traceback

            traceback.print_exc()

    def snapshot_status_ready(self, node) -> None:
        with self._snap_status_mu:
            self._snap_status.add(node)
        self._ready.set()

    def _run_once(self) -> None:
        # reconciles, snapshot finalization and rebase rewrite per-group
        # mirrors (_m_base/_m_last/_lane_by_g); an undecoded in-flight step
        # would later clobber them with stale device output, so these rare
        # paths drain the pipeline first
        if self._reconq or self._snap_status or self._rebase_due:
            self._flush_pending()
            if self._rebase_due:
                self._rebase_due = False
                self._do_rebase()
        self._apply_reconciles()
        self._apply_clock_suspect()
        with self._snap_status_mu:
            snap_done, self._snap_status = self._snap_status, set()
        for node in snap_done:
            # lint: allow(locks/lock-in-hot-loop) snapshot completions:
            # empty ~every step, bounded by in-flight snapshot workers
            with node._mu:
                node._process_snapshot_status()
        if self._multi > 1 and self._routes_dirty:
            self._rebuild_routes()
        with self._dirty_mu:
            dirty = self._dirty
            self._dirty = set()
            tick_counts = self._pending_ticks
            self._pending_ticks = {}
            ticks = max(tick_counts.values()) if tick_counts else 0
            gc_cids = list(self._gc_set) if ticks else ()
        if ticks:
            for _ in range(ticks):
                self.clock.increase_tick()
            self._run_gc(gc_cids)
        work = self._carry
        self._carry = set()
        if dirty:
            with self._lanes_mu:
                for cid in dirty:
                    lane = self._lanes.get(cid)
                    if lane is not None and lane.active:
                        work.add(lane)
        work |= self._catchups
        prof = self.profiler
        prof.new_iteration(len(work))
        # swap to the idle buffer set BEFORE packing: the other set may
        # still be read by the in-flight step
        if self._overlap:
            self._buf_idx = 1 - self._buf_idx
            self._buf, self._ticks, self._host_inbox = self._bufsets[
                self._buf_idx
            ]
        prof.start()
        had, packs = self._pack(work)
        prof.end("pack")
        if not had:
            skip = False
            if ticks == 0:
                skip = True
            else:
                # no active lanes: ticks have nobody to advance
                act = self._m_active
                if not act.any():
                    skip = True
                # a fully-quiesced fleet needs no kernel step for ticks:
                # every timer is frozen, so the step would be a no-op (this
                # is what makes 10k+ idle lanes cost zero host AND device
                # work)
                elif bool(np.all(~act | self._m_quiesced)):
                    skip = True
            if skip and self._m_resid.any():
                # device-routed messages from the previous super-step's
                # last inner step are parked in the residual inbox: they
                # must be consumed even with no fresh host work
                skip = False
            if skip:
                # nothing new dispatched: the pipeline must not sit on an
                # undecoded step indefinitely
                self._flush_pending()
                return
        if ticks:
            # per-lane tick counts come from the OWNING host's counter (a
            # shared core serves several NodeHosts, each with its own tick
            # thread); clamped per lane at its catch-up burst cap, and the
            # EXCESS backlog is shed — not deferred — so a stall charges
            # at most one small burst to each timer and the randomized
            # election spread survives (see _catchup_tick_cap)
            if self._next_host <= 1:
                per_lane = ticks
            else:
                hv = np.zeros(self._next_host + 1, np.int32)
                for h, c in tick_counts.items():
                    hv[h] = c
                per_lane = hv[self._m_host]
            np.minimum(self._m_tick_cap, per_lane, out=self._ticks)
            self._ticks *= self._m_active
            self._last_tick_burst = ticks
            if ticks > 1 and bool(
                np.any((per_lane > self._m_tick_cap) & self._m_active)
            ):
                # some ACTIVE lane's own host backlog exceeded its cap
                # (per_lane broadcasts: scalar for a single host, the
                # owning host's column otherwise)
                self.watchdog.tick_burst_clamped()
        else:
            self._ticks.fill(0)
        # ONE device_put over the (inbox, ticks) pytree: 12 small host
        # arrays ship in a single batched transfer instead of 12 dispatch
        # round-trips (per-call overhead dominates at these sizes); the
        # Inbox views and sharding pytree were built once at allocation
        prof.start()
        if self._multi > 1:
            # K protocol steps per launch: the route/delta planes ride
            # the same batched transfer (small G x P arrays; rebuilt
            # host-side only when lane topology changes)
            payload = (
                self._host_inbox, self._ticks,
                self._np_route, self._np_rdelta,
            )
            mu = _MESH_LAUNCH_MU if self._mesh is not None else _NO_LOCK
            with mu:
                if self._multi_shardings is not None:
                    inbox, tarr, route, rdelta = jax.device_put(
                        payload, self._multi_shardings
                    )
                else:
                    inbox, tarr, route, rdelta = jax.device_put(payload)
                self._state, outs, plans, self._resid, resid_count = (
                    self._multi_fn(
                        self._state, inbox, tarr, self._resid, route, rdelta
                    )
                )
                prof.end("dispatch")
                o, pl, rc = self._fetch_super(outs, plans, resid_count)
            self._m_resid = rc
            self._decode_super(work, packs, o, pl)
            return
        if self._sharding is not None:
            inbox, tarr = jax.device_put(
                (self._host_inbox, self._ticks), self._inbox_shardings
            )
        else:
            inbox, tarr = jax.device_put((self._host_inbox, self._ticks))
        self._state, out = self._step_fn(self._state, inbox, tarr)
        prof.end("dispatch")
        if self._overlap:
            # pipeline: decode step t-1 while the device computes step t
            # (jax dispatch is async — `out` is a future). Ordering
            # invariants live inside each step's decode, so pipelining
            # steps preserves them; pack staleness is accounted for by the
            # per-lane packed_pending window tracking. Swap FIRST so a
            # decode exception cannot also lose the just-dispatched step.
            pending, self._pending = self._pending, (work, packs, out)
            self._flush_one(pending)
        else:
            self._decode(work, packs, self._fetch_output(out))

    def _fetch_output(self, out) -> dict:
        """ONE consolidated device->host transfer for the whole StepOutput,
        shared by the overlap and non-overlap paths. The planes ship as a
        single batched fetch rather than per-plane masked gets: every plane
        is G- or GxP-sized, so per-dispatch overhead dominates transfer
        cost, and each decode phase masks its own work list host-side from
        send_flags/dirty lanes."""
        prof = self.profiler
        prof.start()
        o = jax.device_get(out)._asdict()
        note_seam_sync()  # runtime sync audit: the ONE blessed transfer
        prof.end("fetch")
        return o

    def _fetch_super(self, outs, plans, resid_count):
        """The multi-step twin of _fetch_output: ONE consolidated
        device->host transfer for the whole K-step super-step (the
        stacked per-step StepOutput planes, the per-step route plans and
        the residual-inbox occupancy ship together). This is the other
        blessed sync seam — it fires once per K protocol steps."""
        prof = self.profiler
        prof.start()
        o, pl, rc = jax.device_get((outs, plans, resid_count))
        note_seam_sync()  # runtime sync audit: one transfer per K steps
        prof.end("fetch")
        return o._asdict(), pl._asdict(), np.array(rc, np.int32)

    def _flush_pending(self) -> None:
        pending, self._pending = self._pending, None
        self._flush_one(pending)

    def _flush_one(self, pending) -> None:
        if pending is None:
            return
        work, packs, out = pending
        self._decode(work, packs, self._fetch_output(out))

    def _run_gc(self, gc_cids) -> None:
        """Request-timeout pass over lanes with outstanding requests only
        (the reference runs four gc calls per node per tick; idle lanes
        here cost nothing)."""
        if not self.clock.should_gc():
            return
        drop = []
        for cid in gc_cids:
            with self._lanes_mu:
                lane = self._lanes.get(cid)
            if lane is None:
                drop.append(cid)
                continue
            node = lane.node
            node.pending_proposals.gc()
            node.pending_read_indexes.gc()
            node.pending_config_change.gc()
            node.pending_snapshot.gc()
            node.gc_batches()
            if lane.ri_pending:
                # engine-side ctx routing entries die with their batches
                # (timed-out forwarded reads would otherwise leak here)
                pri = node.pending_read_indexes
                dead = [
                    enc
                    for enc, ctx in lane.ri_pending.items()
                    if not pri.has_ctx(ctx)
                ]
                for enc in dead:
                    del lane.ri_pending[enc]
            if not (
                node.pending_proposals.has_pending()
                or node.pending_read_indexes.has_pending()
                or node.pending_config_change.has_pending()
                or node.pending_snapshot.has_pending()
                or node._batches
            ):
                drop.append(cid)
        if drop:
            with self._dirty_mu:
                # a request registered concurrently re-adds its cid to
                # _dirty AND _gc_set (set_node_ready); keep those — else
                # the new request's timeout gc would never run
                self._gc_set.difference_update(set(drop) - self._dirty)

    # ---------------------------------------------------------------- pack
    def _pack(self, lanes: Set[_Lane]):
        K = self.kcfg.inbox_depth
        E = self.kcfg.max_entries_per_msg
        W = self.kcfg.log_window
        buf = self._buf
        buf["mtype"].fill(MSG.NONE)
        buf["n_entries"].fill(0)
        buf["entry_cc"].fill(False)
        # self-healing like the old direct writes: rows staged by an
        # iteration that died mid-pack (loop catches and continues) must
        # not replay into this step's planes as phantom kernel messages
        for col in self._rows.values():
            col.clear()
        had = bool(self._catchups)
        packs: Dict[_Lane, Dict[int, tuple]] = {}
        # per-lane mirror reads gathered ONCE as columns (per-element
        # int(arr[g]) reads were a measured hot spot at fleet widths)
        work = list(lanes)
        if work:
            w_gs = [lane.g for lane in work]
            cols = zip(
                work,
                self._m_quiesced[w_gs].tolist(),
                self._m_role[w_gs].tolist(),
                self._m_leader[w_gs].tolist(),
                self._m_last[w_gs].tolist(),
                self._m_devfirst[w_gs].tolist(),
                self._m_base[w_gs].tolist(),
                # multi-step: device-routed residual messages occupy the
                # low inbox slots of the NEXT super-step; host rows pack
                # after them (all-zero at K=1)
                self._m_resid[w_gs].tolist(),
            )
        else:
            cols = ()
        for (
            lane, g_quiesced, g_role, g_leader, g_last, g_devfirst, b, g_resid,
        ) in cols:
            node = lane.node
            g = lane.g
            lane.pack_info = {}
            # queue drains gated on lock-free emptiness probes: producers
            # mark the lane dirty AFTER enqueueing, so a racy miss is
            # re-delivered next iteration; most dirty lanes carry only ONE
            # kind of event and skip the other queues' lock round-trips
            if node.mq.has_pending():
                msgs, _ = node.mq.get()
                lane.msg_backlog.extend(msgs)
            if lane.recovering:
                # an InstallSnapshot recover is in flight: hold everything
                # until the device lane is reconciled (cf. node.go:1199)
                if lane.has_staged():
                    self._carry.add(lane)
                continue
            # drain API queues into the staging deques
            if node.incoming_proposals.has_pending():
                lane.staged_props.extend(node.incoming_proposals.get())
            if node.incoming_reads.has_pending():
                lane.staged_reads.extend(node.incoming_reads.get())
            if node._cc_queue:
                # lint: allow(locks/lock-in-hot-loop) config changes: the
                # lock-free emptiness probe above keeps steady-state lanes
                # off this lock; only lanes with a queued cc pay it
                with node._mu:
                    ccs, node._cc_queue = node._cc_queue, []
                for cc, key in ccs:
                    ce = Entry(
                        type=EntryType.CONFIG_CHANGE,
                        cmd=encode_config_change(cc),
                        key=key,
                    )
                    lane.staged_ccs.append((ce, key))
            k = g_resid
            # a quiesced lane with fresh host work gets a wake NOOP (the
            # kernel exits quiesce on any non-heartbeat inbox message; the
            # reference wakes through exitQuiesce on activity, quiesce.go)
            if (
                g_quiesced
                and k < K
                and (lane.has_staged() or node.pending_leader_transfer.peek())
            ):
                self._stage_row(
                    g, k, MSG.NOOP, from_slot=max(lane.self_slot(), 0)
                )
                had = True
                k += 1
            # 1. wire/protocol messages first
            while lane.msg_backlog and k < K:
                m = lane.msg_backlog.popleft()
                k_used = self._pack_wire(lane, m, k, b)
                if k_used:
                    had = True
                    k += 1
            is_leader = g_role == ROLE.LEADER
            leader_nid = lane.rev.get(g_leader - 1)
            # 2. one config change per step (lone message; host invariant)
            if k < K and lane.staged_ccs and not lane.cc_inflight:
                if is_leader:
                    ce, key = lane.staged_ccs.popleft()
                    self._stage_row(
                        g, k, MSG.PROPOSE, from_slot=lane.self_slot(),
                        n_entries=1,
                    )
                    self._rows["ents"].append((g, k, None, (True,)))
                    lane.pack_info[k] = ("cc", ce, key)
                    lane.cc_inflight = True
                    lane.packed_pending += 1
                    had = True
                    k += 1
                elif leader_nid is not None and leader_nid != node.node_id():
                    while lane.staged_ccs:
                        ce, key = lane.staged_ccs.popleft()
                        node._send_message(
                            Message(
                                type=MT.PROPOSE,
                                cluster_id=node.cluster_id,
                                to=leader_nid,
                                from_=node.node_id(),
                                entries=[ce],
                            )
                        )
            # 3. proposals — throttled to the device window's free space so
            # the kernel never has to drop for lack of room (minus 1 slot
            # of slack for a concurrent new-leader noop append); what
            # doesn't fit stays staged and re-packs after compaction
            if lane.staged_props:
                if is_leader:
                    free = (
                        W - 1 - (g_last - g_devfirst + 1)
                        - lane.packed_pending
                    )
                    while lane.staged_props and k < K and free > 0:
                        ents = []
                        cap = min(E, free)
                        while lane.staged_props and len(ents) < cap:
                            ents.append(lane.staged_props.popleft())
                        free -= len(ents)
                        lane.packed_pending += len(ents)
                        self._stage_row(
                            g, k, MSG.PROPOSE, from_slot=lane.self_slot(),
                            n_entries=len(ents),
                        )
                        lane.pack_info[k] = ("prop", ents)
                        had = True
                        k += 1
                elif leader_nid is not None and leader_nid != node.node_id():
                    ents = list(lane.staged_props)
                    lane.staged_props.clear()
                    for i in range(0, len(ents), 64):
                        node._send_message(
                            Message(
                                type=MT.PROPOSE,
                                cluster_id=node.cluster_id,
                                to=leader_nid,
                                from_=node.node_id(),
                                entries=ents[i : i + 64],
                            )
                        )
            # 4. reads
            if lane.staged_reads:
                if is_leader and lane.self_slot() >= 0:
                    if k < K:
                        states = list(lane.staged_reads)
                        lane.staged_reads.clear()
                        ctx = node.pending_read_indexes.next_ctx()
                        if node.pending_read_indexes.bind_queued_states(
                            states, ctx
                        ):
                            enc = _enc_ctx(lane.self_slot(), ctx.low)
                            lane.ri_pending[enc] = ctx
                            self._stage_row(
                                g, k, MSG.READ_INDEX,
                                from_slot=lane.self_slot(), hint=enc[0],
                                hint_high=enc[1],
                            )
                            had = True
                            k += 1
                elif leader_nid is not None and leader_nid != node.node_id():
                    states = list(lane.staged_reads)
                    lane.staged_reads.clear()
                    ctx = node.pending_read_indexes.next_ctx()
                    if node.pending_read_indexes.bind_queued_states(states, ctx):
                        enc = _enc_ctx(lane.self_slot(), ctx.low)
                        lane.ri_pending[enc] = ctx
                        node._send_message(
                            Message(
                                type=MT.READ_INDEX,
                                cluster_id=node.cluster_id,
                                to=leader_nid,
                                from_=node.node_id(),
                                hint=enc[0],
                                hint_high=enc[1],
                            )
                        )
            # 5. leadership transfer
            target = node.pending_leader_transfer.get()
            if target is not None and k < K:
                tslot = lane.slots.get(target, -1)
                if tslot >= 0:
                    self._stage_row(
                        g, k, MSG.LEADER_TRANSFER,
                        from_slot=lane.self_slot(), hint=tslot + 1,
                    )
                    had = True
                    k += 1
            # lanes with leftover staged work re-pack next iteration
            # (K exhausted, or a leaderless lane waiting for an election)
            if lane.has_staged():
                self._carry.add(lane)
            if lane.pack_info:
                packs[lane] = lane.pack_info
        # serving backpressure mirrors: rows packed vs this step's lane
        # capacity, and the staged backlog the carry set drags into the
        # next step (leftover staged work means the inbox could not drain
        # the offered load — the engine-side saturation signal). Row
        # count captured BEFORE the flush clears the staging columns.
        self._p_inbox_rows = len(self._rows["g"])
        self._p_inbox_lanes = len(work)
        backlog = 0
        for lane in self._carry:
            backlog += (
                len(lane.staged_props)
                + len(lane.staged_reads)
                + len(lane.staged_ccs)
            )
        self._p_staged_backlog = backlog
        self._flush_staged_rows()
        return had, packs

    def _stage_row(
        self, g: int, k: int, mtype: int, from_slot: int = 0, term: int = 0,
        log_index: int = 0, log_term: int = 0, commit: int = 0,
        reject: bool = False, hint: int = 0, hint_high: int = 0,
        n_entries: int = 0,
    ) -> None:
        """Stage one inbox row as column appends; _flush_staged_rows lands
        the whole step's rows with one scatter per plane."""
        r = self._rows
        r["g"].append(g)
        r["k"].append(k)
        r["mtype"].append(mtype)
        r["from_slot"].append(max(from_slot, 0))
        r["term"].append(term)
        r["log_index"].append(log_index)
        r["log_term"].append(log_term)
        r["commit"].append(commit)
        r["reject"].append(reject)
        r["hint"].append(hint)
        r["hint_high"].append(hint_high)
        r["n_entries"].append(n_entries)

    def _flush_staged_rows(self) -> None:
        rows = self._rows
        gs = rows["g"]
        if gs:
            buf = self._buf
            ks = rows["k"]
            buf["mtype"][gs, ks] = rows["mtype"]
            buf["from_slot"][gs, ks] = rows["from_slot"]
            buf["term"][gs, ks] = rows["term"]
            buf["log_index"][gs, ks] = rows["log_index"]
            buf["log_term"][gs, ks] = rows["log_term"]
            buf["commit"][gs, ks] = rows["commit"]
            buf["reject"][gs, ks] = rows["reject"]
            buf["hint"][gs, ks] = rows["hint"]
            buf["hint_high"][gs, ks] = rows["hint_high"]
            buf["n_entries"][gs, ks] = rows["n_entries"]
            ents = rows["ents"]
            if ents:
                terms_buf = buf["entry_terms"]
                cc_buf = buf["entry_cc"]
                for g, k, terms, ccs in ents:
                    if terms is not None:
                        terms_buf[g, k, : len(terms)] = terms
                    cc_buf[g, k, : len(ccs)] = ccs
        for col in rows.values():
            col.clear()

    def _pack_wire(self, lane: _Lane, m: Message, k: int, b: int) -> bool:
        """Convert one wire message into a staged inbox row (b = the lane's
        device window base, gathered once per step by _pack). Returns False
        when the message was consumed host-side (snapshot, propose
        staging)."""
        g = lane.g
        t = m.type
        if t == MT.INSTALL_SNAPSHOT:
            self._handle_install_snapshot(lane, m)
            return False
        if t == MT.PROPOSE:
            for e in m.entries:
                if e.type == EntryType.CONFIG_CHANGE:
                    lane.staged_ccs.append((e, e.key))
                else:
                    lane.staged_props.append(e)
            return False
        if t == MT.QUIESCE:
            return False
        from_slot = lane.slot_of(m.from_, provisional=t == MT.REPLICATE or t == MT.HEARTBEAT or t == MT.REQUEST_VOTE or t == MT.REQUEST_PREVOTE or t == MT.TIMEOUT_NOW or t == MT.READ_INDEX_RESP)
        if from_slot < 0 and m.from_ != 0:
            return False  # unknown sender and no room to learn it
        if t == MT.REPLICATE:
            n = len(m.entries)
            E = self.kcfg.max_entries_per_msg
            if n > E:
                # split: re-queue the tail as a chained Replicate
                head, tail = m.entries[:E], m.entries[E:]
                rest = Message(
                    type=MT.REPLICATE, cluster_id=m.cluster_id, to=m.to,
                    from_=m.from_, term=m.term, commit=m.commit,
                    log_index=head[-1].index, log_term=head[-1].term,
                    entries=tail,
                )
                lane.msg_backlog.appendleft(rest)
                m.entries = head
                n = E
            # causal trace: the receive hop of a sampled entry's chain
            # (after the split so a trace in the requeued tail records
            # when ITS chunk packs)
            trace_id = 0
            for e in m.entries:
                if e.trace_id:
                    trace_id = e.trace_id
            if trace_id:
                flight_recorder().record(
                    "replicate_recv", cluster=lane.node.cluster_id,
                    node=lane.node.node_id(), from_node=m.from_,
                    trace=trace_id,
                )
            self._stage_row(
                g, k, MSG.REPLICATE, from_slot=from_slot, term=m.term,
                log_index=m.log_index - b, log_term=m.log_term,
                commit=max(m.commit - b, 0), n_entries=n,
            )
            self._rows["ents"].append(
                (
                    g, k,
                    [e.term for e in m.entries],
                    [e.is_config_change() for e in m.entries],
                )
            )
            lane.pack_info[k] = ("rep", list(m.entries))
            return True
        if t == MT.HEARTBEAT:
            self._stage_row(
                g, k, MSG.HEARTBEAT, from_slot=from_slot, term=m.term,
                # log_index is the lease round tag (opaque tick stamp,
                # 0 when leases off) — staged raw, no -b translation
                log_index=m.log_index,
                commit=max(m.commit - b, 0), hint=m.hint,
                hint_high=m.hint_high,
            )
            return True
        if t == MT.REQUEST_VOTE:
            self._stage_row(
                g, k, MSG.REQUEST_VOTE, from_slot=from_slot, term=m.term,
                log_index=m.log_index - b, log_term=m.log_term, hint=m.hint,
            )
            return True
        if t == MT.REQUEST_VOTE_RESP:
            self._stage_row(
                g, k, MSG.REQUEST_VOTE_RESP, from_slot=from_slot, term=m.term,
                reject=m.reject,
            )
            return True
        if t == MT.REQUEST_PREVOTE:
            self._stage_row(
                g, k, MSG.REQUEST_PREVOTE, from_slot=from_slot, term=m.term,
                log_index=m.log_index - b, log_term=m.log_term, hint=m.hint,
            )
            return True
        if t == MT.REQUEST_PREVOTE_RESP:
            self._stage_row(
                g, k, MSG.REQUEST_PREVOTE_RESP, from_slot=from_slot,
                term=m.term, reject=m.reject,
            )
            return True
        if t == MT.REPLICATE_RESP:
            if m.reject and m.hint < b and from_slot >= 0:
                # the follower's log ends BELOW our device window: the
                # kernel cannot back off past its own first_index, so a
                # clamped hint would loop rejects forever. Serve the gap
                # host-side (log replay or snapshot) and park the device
                # remote until the follower crosses the window base.
                self._below_window_reject(lane, from_slot, m)
                return False
            self._stage_row(
                g, k, MSG.REPLICATE_RESP, from_slot=from_slot, term=m.term,
                log_index=m.log_index - b, reject=m.reject,
                hint=max(m.hint - b, 0),
            )
            return True
        if t == MT.HEARTBEAT_RESP:
            self._stage_row(
                g, k, MSG.HEARTBEAT_RESP, from_slot=from_slot, term=m.term,
                # echoed lease round tag, raw (see MT.HEARTBEAT above)
                log_index=m.log_index,
                hint=m.hint, hint_high=m.hint_high,
            )
            return True
        if t == MT.READ_INDEX:
            self._stage_row(
                g, k, MSG.READ_INDEX, from_slot=from_slot, term=m.term,
                hint=m.hint, hint_high=m.hint_high,
            )
            return True
        if t == MT.READ_INDEX_RESP:
            self._stage_row(
                g, k, MSG.READ_INDEX_RESP, from_slot=from_slot, term=m.term,
                log_index=m.log_index - b, hint=m.hint,
                hint_high=m.hint_high,
            )
            return True
        if t == MT.TIMEOUT_NOW:
            self._stage_row(
                g, k, MSG.TIMEOUT_NOW, from_slot=from_slot, term=m.term
            )
            return True
        if t == MT.UNREACHABLE:
            self._stage_row(g, k, MSG.UNREACHABLE, from_slot=from_slot)
            return True
        if t == MT.SNAPSHOT_STATUS:
            self._stage_row(
                g, k, MSG.SNAPSHOT_STATUS, from_slot=from_slot, reject=m.reject
            )
            return True
        if t == MT.NOOP:
            self._stage_row(g, k, MSG.NOOP, from_slot=from_slot, term=m.term)
            return True
        return False

    def _handle_install_snapshot(self, lane: _Lane, m: Message) -> None:
        ss = m.snapshot
        node = lane.node
        if ss is None or ss.is_empty():
            return
        applied = node.sm.last_applied_index()
        if ss.index <= applied:
            # stale snapshot: ACK it (etcd TestRestoreIgnores semantics —
            # the scalar core does the same). A silent drop wedges the
            # sender: its remote stays parked in SNAPSHOT state waiting for
            # match >= snapshot index, it resends the same snapshot on the
            # feedback retry, and we'd drop that too, forever.
            node._send_message(
                Message(
                    type=MT.REPLICATE_RESP,
                    cluster_id=node.cluster_id,
                    to=m.from_,
                    from_=node.node_id(),
                    term=max(m.term, int(self._m_term[lane.g]),
                             lane.adopted_term),
                    log_index=applied,
                )
            )
            return
        if lane.recovering:
            return  # a restore is already in flight; the retry re-delivers
        lane.recovering = True
        # multi-step: a recovering lane leaves the on-device routing
        # table — routed traffic would advance kernel state the restore
        # is about to overwrite; the host path holds its messages instead
        self._routes_dirty = True
        # the restore ack must carry a term the sender will not drop as
        # stale; the kernel never sees this message (it is consumed host-
        # side), so remember the sender's term for the ack path
        # (cf. raft.go:1415-1449 term preamble)
        lane.adopted_term = max(lane.adopted_term, m.term)
        # the snapshot record is persisted (fsync) on the snapshot worker
        # right before recovery, NOT here: this is the engine loop thread,
        # and a monolithic install must not stall every other lane's
        # super-step cadence (the streamed-install watchdog bound)
        node._vec_install_record = ss
        lane.node._push_install_snapshot(ss)

    # --------------------------------------------------------------- decode
    def _decode(self, worked: Set[_Lane], packs, o: dict) -> None:
        """One engine step's host fan-out (the K=1 path): the decode
        phases run in the reference ordering over a single StepOutput.
        The phase bodies live in the _decode_* subfunctions so the
        multi-step super-step (_decode_super) can orchestrate the same
        code with its masked, per-inner-step inputs."""
        self.last_output = o  # numpy snapshot for diagnostics/tools
        note_engine_steps(1)
        prof = self.profiler
        prof.start()
        self._decode_place(o, packs)
        self._refresh_mirrors(o)
        prof.end("place")
        # ---- phase 1: Replicate messages leave BEFORE the fsync ----------
        prof.start()
        self._decode_send_rep(o)
        prof.end("send_rep")
        # ---- phase 2: one batched fsynced write for every lane -----------
        prof.start()
        updates, lane_saves = build_save_updates(
            o, self._m_base, self._lane_by_g
        )
        self._commit_saves(updates, lane_saves)
        prof.end("save")
        # ---- phase 3: post-fsync sends (votes, responses, heartbeats) ----
        prof.start()
        self._decode_send_post(o)
        prof.end("send_resp")
        # ---- phase 4: hand committed entries to the RSM ------------------
        prof.start()
        self._decode_apply(o)
        prof.end("apply")
        # ---- phase 5: confirmed reads ------------------------------------
        prof.start()
        self._decode_reads(o)
        prof.end("reads")
        # ---- phase 6: maintenance ----------------------------------------
        prof.start()
        self._maintain(o)
        prof.end("maintain")

    def _decode_super(self, worked: Set[_Lane], packs, o: dict, pl: dict) -> None:
        """Decode one K-step super-step (the multi-step path): the
        host-only residue of every inner step, with device-routed
        traffic masked out of the send/response planes and its
        Replicate payload bytes replayed into the destination arenas.

        Phase ordering across the window:
          * place + phase-1 Replicates run per inner step IN ORDER (a
            cross-host Replicate of step t materializes its payload
            BEFORE step t+1's placements can conflict-truncate it);
          * the WAL save is ONE merged wave: every inner step's updates
            land in step order inside a single batched write + barrier,
            so responses of EVERY inner step leave only after the
            window's final — maximal — hard state is durable (the
            persist-before-ack invariant holds against a state at least
            as new as what each response reflects);
          * post-fsync sends, RSM apply and confirmed reads then run per
            inner step in order.
        """
        K = self._multi
        steps = []
        for t in range(K):
            ot = {k: v[t] for k, v in o.items()}
            plt = {k: v[t] for k, v in pl.items()}
            steps.append((ot, plt))
        self.last_output = steps[-1][0]
        note_engine_steps(K)
        prof = self.profiler
        st = self._sstats
        base = self._m_base
        lane_by_g = self._lane_by_g
        # ---- place + phase 1, per inner step in order --------------------
        for t, (ot, plt) in enumerate(steps):
            prof.start()
            # routed Replicates consumed by THIS inner step: acceptance
            # (rep_base) is in ot; the candidate plan was staged by the
            # previous inner step (or the previous super-step's last one)
            self._place_routed_reps(ot)
            self._decode_place(ot, packs if t == 0 else None)
            self._pending_rep_copies = self._routed_rep_plan(ot, plt)
            for kind in ("rep", "vote", "hb", "tn", "resp", "rir"):
                st["msgs_routed_device"] += int(plt[kind].sum())
            self._mask_routed(ot, plt)
            prof.end("place")
            prof.start()
            self._decode_send_rep(ot)
            prof.end("send_rep")
        self._refresh_mirrors(steps[-1][0])
        # ---- phase 2: ONE merged save wave for the whole window ----------
        prof.start()
        updates: List[Update] = []
        lane_saves: List[Tuple[_Lane, List[Entry], State]] = []
        for ot, _plt in steps:
            u, ls = build_save_updates(ot, base, lane_by_g)
            updates.extend(u)
            lane_saves.extend(ls)
        self._commit_saves(updates, lane_saves)
        prof.end("save")
        # ---- phases 3-5 per inner step in order --------------------------
        prof.start()
        for ot, _plt in steps:
            self._decode_send_post(ot)
        prof.end("send_resp")
        prof.start()
        for ot, _plt in steps:
            self._decode_apply(ot)
        prof.end("apply")
        prof.start()
        for ot, plt in steps:
            self._decode_reads(ot, skip_routed=plt["rir"])
        prof.end("reads")
        # ---- phase 6: maintenance on the window's final state ------------
        prof.start()
        self._maintain(steps[-1][0])
        prof.end("maintain")

    # ------------------------------------------------ multi-step routing
    def _rebuild_routes(self) -> None:
        """Recompute the on-device routing table (multi-step engine):
        for every active lane and peer slot, the co-hosted destination
        lane index and the window-base delta the kernel adds to
        index-valued fields. Conservative by construction — any
        condition the host delivery path special-cases (chaos drop
        hook, partitioned host, stopped node, in-flight snapshot
        restore, unknown peer) routes -1, so that traffic falls back to
        the host path and its exact semantics."""
        self._routes_dirty = False
        if self._multi <= 1:
            return
        route = self._np_route
        rdelta = self._np_rdelta
        route.fill(-1)
        rdelta.fill(0)
        if self._local_drop_hook is not None:
            return  # every co-hosted message must pass the chaos hook
        P = self.kcfg.peers
        base = self._m_base
        blocked = self._blocked_hosts
        with self._lanes_mu:
            lanes = list(self._lanes.values())
            rt = dict(self._route)
        for lane in lanes:
            if not lane.active or lane.node.stopped:
                continue
            if lane.key[0] in blocked:
                continue  # partitioned host: neither sends nor receives
            g = lane.g
            self_slot = lane.self_slot()
            for p, nid in lane.rev.items():
                if p == self_slot or p < 0 or p >= P:
                    continue
                if p in lane.wit_slots:
                    # witness peers stay on the host path: its senders
                    # strip payloads to METADATA (the zero-payload
                    # witness contract); the device route would copy
                    # full entries into the witness arena
                    continue
                dst = rt.get((lane.node.cluster_id, nid))
                if (
                    dst is None
                    or not dst.active
                    or dst.recovering
                    or dst.node.stopped
                    or dst.key[0] in blocked
                ):
                    continue
                route[g, p] = dst.g
                rdelta[g, p] = int(base[g] - base[dst.g])

    def _routed_rep_plan(self, o: dict, plan: dict) -> list:
        """Replay the kernel's deterministic inbox-slot assignment for
        this step's device-routed Replicates: [(dst_g, slot, src_lane,
        dst_lane, lo_real, hi_real)]. Replicate candidates come FIRST in
        the kernel's kind-major candidate order, so their per-destination
        slots are simply their rank among routed Replicates to the same
        destination in row-major (g, p) order — exactly what np.nonzero
        yields. The payload copy waits for the CONSUMING step's
        acceptance report (_place_routed_reps)."""
        rep = plan["rep"]
        gs, ps = np.nonzero(rep)
        if not gs.size:
            return []
        route = self._np_route
        base = self._m_base
        lane_by_g = self._lane_by_g
        out = []
        counts: Dict[int, int] = {}
        cols = zip(
            gs.tolist(),
            route[gs, ps].tolist(),
            base[gs].tolist(),
            o["send_prev_index"][gs, ps].tolist(),
            o["send_n_entries"][gs, ps].tolist(),
        )
        for g, d, b, prev, n in cols:
            slot = counts.get(d, 0)
            counts[d] = slot + 1
            if n <= 0:
                continue  # empty commit-refresh Replicate: no payload
            src = lane_by_g[g]
            dst = lane_by_g[d]
            if src is None or dst is None:
                continue
            lo = b + prev + 1
            out.append((d, slot, src, dst, lo, lo + n - 1))
        return out

    def _place_routed_reps(self, o: dict) -> None:
        """Payload placement for device-routed Replicates consumed by
        this inner step: the destination ACCEPTED the entries iff its
        rep_base for the (lane, slot) the kernel routed them into is
        nonzero — the same acceptance gate the host wire path applies
        before placing a Replicate's entries into the arena."""
        pend, self._pending_rep_copies = self._pending_rep_copies, []
        if not pend:
            return
        lane_by_g = self._lane_by_g
        rep_base = o["rep_base"]
        for d, slot, src, dst, lo, hi in pend:
            if lane_by_g[d] is not dst or not dst.active:
                continue  # lane recycled between super-steps
            if rep_base[d, slot] <= 0:
                continue  # rejected (or consumed by a stale-term drop)
            arena = dst.arena
            sa = src.arena
            for i in range(lo, hi + 1):
                e = sa.get(i)
                if e is not None:
                    arena[e.index] = e

    def _mask_routed(self, o: dict, plan: dict) -> None:
        """Clear device-routed candidates out of the send/response
        planes so the host fan-out only materializes Messages for
        traffic the kernel could NOT route (cross-host, overflowed,
        below-window). Builds new arrays — the fetched planes can be
        read-only views."""
        clr = (
            np.where(plan["rep"], SEND_REPLICATE, 0)
            | np.where(plan["vote"], SEND_VOTE_REQ, 0)
            | np.where(plan["hb"], SEND_HEARTBEAT, 0)
            | np.where(plan["tn"], SEND_TIMEOUT_NOW, 0)
        )
        o["send_flags"] = o["send_flags"] & ~clr
        o["resp_type"] = np.where(
            plan["resp"], np.int32(MSG.NONE), o["resp_type"]
        )

    # ----------------------------------------------------- decode phases
    def _decode_place(self, o: dict, packs) -> None:
        """Phase 0: payloads at device-assigned indexes (host-packed
        rows when ``packs`` is given, plus new-leader noop entries) and
        the per-step stats base count."""
        lane_by_g = self._lane_by_g
        base = self._m_base
        # lease read counters: per-step deltas from the kernel, folded
        # into the engine totals (numpy sums over planes the decode
        # already fetched — zero extra device syncs)
        self._lease_local += int(o["lease_served"].sum())
        self._lease_fb += int(o["lease_fallback"].sum())
        # on-device event-counter plane: one (G, CTR.COUNT) u32 delta
        # block per protocol step, accumulated where the events happened
        # (inside step_batch / the K-step scan) and folded here into the
        # cumulative per-lane totals — the K>1 / device-routed regime
        # counts exactly like K=1 because the kernel counted it, not the
        # host decode
        self._ctr += o["counters"]
        self._m_lease_ok = np.asarray(o["lease_ok"])
        # ---- phase 0: place payloads at device-assigned indexes ----------
        # columnar: ONE gather per StepOutput plane over every packed row,
        # then plain-python iteration (no per-element device_get reads)
        if packs:
            pk_lanes: List[_Lane] = []
            pk_ks: List[int] = []
            pk_infos: List[tuple] = []
            for lane, pack_info in packs.items():
                for k, info in pack_info.items():
                    pk_lanes.append(lane)
                    pk_ks.append(k)
                    pk_infos.append(info)
            pk_gs = [lane.g for lane in pk_lanes]
            place_cols = zip(
                pk_lanes,
                pk_infos,
                base[pk_gs].tolist(),
                o["prop_base"][pk_gs, pk_ks].tolist(),
                o["rep_base"][pk_gs, pk_ks].tolist(),
                o["resp_term"][pk_gs, pk_ks].tolist(),
                o["dropped_cc"][pk_gs].tolist(),
            )
            for lane, info, b, pbase, rbase, rterm, dcc in place_cols:
                kind = info[0]
                if kind == "prop":
                    ents = info[1]
                    if pbase > 0:
                        arena = lane.arena
                        for i, e in enumerate(ents):
                            e.index = b + pbase + i
                            e.term = rterm
                            arena[e.index] = e
                    else:
                        node = lane.node
                        for e in ents:
                            node.proposal_dropped(e)
                    lane.packed_pending = max(
                        0, lane.packed_pending - len(ents)
                    )
                elif kind == "cc":
                    ce, key = info[1], info[2]
                    if pbase > 0 and not dcc:
                        ce.index = b + pbase
                        ce.term = rterm
                        lane.arena[ce.index] = ce
                    else:
                        if pbase > 0:
                            # the kernel appended the entry with its cc bit
                            # stripped (single-pending invariant): it lives
                            # on as an empty noop entry (raft.go:1587-1606)
                            lane.arena[b + pbase] = Entry(
                                type=EntryType.APPLICATION,
                                index=b + pbase,
                                term=rterm,
                            )
                        lane.cc_inflight = False
                        lane.node.pending_config_change.apply(
                            key, rejected=True
                        )
                    lane.packed_pending = max(0, lane.packed_pending - 1)
                elif kind == "rep":
                    if rbase > 0:
                        arena = lane.arena
                        for e in info[1]:
                            arena[e.index] = e
        # new-leader noop entries can appear on ANY lane (tick elections)
        noop_gs = np.nonzero(o["noop_appended"])[0]
        if noop_gs.size:
            for g, noop_at, noop_term, b in zip(
                noop_gs.tolist(),
                o["noop_appended"][noop_gs].tolist(),
                o["noop_term"][noop_gs].tolist(),
                base[noop_gs].tolist(),
            ):
                lane = lane_by_g[g]
                if lane is None:
                    continue
                lane.arena[b + noop_at] = Entry(
                    type=EntryType.APPLICATION,
                    term=noop_term,
                    index=b + noop_at,
                )
        # ---- per-step stats: steps counter (the rest accumulates inline
        # on objects each phase already materializes — len() of the send
        # batches, counts inside loops that already run — so the stats
        # plane adds ZERO numpy reductions to the step)
        st = self._sstats
        st["steps"] += 1

    def _refresh_mirrors(self, o: dict) -> None:
        """Rebind the whole-G numpy protocol mirrors from a StepOutput
        and emit leader-change events for lanes whose (leader, term)
        moved. The multi-step path calls this ONCE per super-step with
        the window's final state: intermediate transitions inside the
        window collapse into one observed change (the mirrors are a
        per-sync snapshot plane, not a per-protocol-step event log)."""
        lane_by_g = self._lane_by_g
        st = self._sstats
        # ---- mirror refresh + leader-change events -----------------------
        new_leader = o["leader"]
        new_term = o["term"]
        changed = np.nonzero(
            ((new_leader != self._m_leader) | (new_term != self._m_term))
            & self._m_active
        )[0]
        # old leader column for the changed lanes, captured before the
        # rebind: distinguishes true LEADER transitions (which arm the
        # ticks_since_leader_change gauge) from term-only churn
        old_leader_changed = self._m_leader[changed]
        # device_get arrays can be read-only views: mirrors are mutated by
        # the activation/reconcile paths, so copy on rebind
        self._m_leader = np.array(new_leader)
        self._m_term = np.array(new_term)
        self._m_role = np.array(o["role"])
        self._m_quiesced = np.array(o["quiesced"])
        self._m_commit = o["commit_index"].astype(np.int64)
        self._m_last = o["last_index"].astype(np.int64)
        if changed.size:
            lead_n = elect_n = 0
            chg_tick = self.clock.tick
            for g, lslot, old_lslot, term in zip(
                changed.tolist(),
                new_leader[changed].tolist(),
                old_leader_changed.tolist(),
                new_term[changed].tolist(),
            ):
                lane = lane_by_g[g]
                if lane is None or not lane.active:
                    continue
                lead_n += 1
                if lslot != old_lslot:
                    # real leader transition (not term-only churn)
                    self._m_leader_change_tick[g] = chg_tick
                if lslot == 0:
                    # lane went leaderless: an election is underway
                    elect_n += 1
                lane.node._leader_event(lane.rev.get(lslot - 1, 0), term)
            st["leader_changes"] += lead_n
            st["elections_started"] += elect_n

    def _decode_send_rep(self, o: dict) -> None:
        """Phase 1: Replicate messages leave BEFORE the fsync."""
        st = self._sstats
        base = self._m_base
        lane_by_g = self._lane_by_g
        rep_sends = gather_replicate_sends(
            o, base, lane_by_g, self._fetch_from_log
        )
        st["msgs_replicate"] += len(rep_sends)
        self._dispatch_sends(rep_sends)

    def _commit_saves(self, updates, lane_saves) -> None:
        """Phase 2: one batched fsynced write wave + log-reader mirror
        append, in update order (a multi-step window passes every inner
        step's updates through ONE call, so conflict-truncation rewrites
        apply sequentially inside a single barrier)."""
        if updates:
            self._save_updates(updates, lane_saves)
        for lane, ents, state in lane_saves:
            if ents:
                lane.node.log_reader.append(ents)
            lane.node.log_reader.set_state(state)

    def _decode_send_post(self, o: dict) -> None:
        """Phase 3: post-fsync sends (votes, responses, heartbeats) plus
        the snapshot path for peers that fell behind the device window."""
        st = self._sstats
        base = self._m_base
        lane_by_g = self._lane_by_g
        post = gather_post_sends(o, base, lane_by_g)
        st["msgs_broadcast"] += len(post)
        resp_sends = gather_resp_sends(o, base, lane_by_g)
        st["msgs_resp"] += len(resp_sends)
        post.extend(resp_sends)
        self._dispatch_sends(post)
        # snapshot path for peers that fell behind the device window
        snap_gs, snap_ps = np.nonzero(o["send_flags"] & NEED_SNAPSHOT)
        if snap_gs.size:
            for g, p in zip(snap_gs.tolist(), snap_ps.tolist()):
                lane = lane_by_g[g]
                if lane is not None:
                    self._start_catchup(lane, p, o)

    def _decode_apply(self, o: dict) -> None:
        """Phase 4: hand committed entries to the RSM task workers."""
        st = self._sstats
        base = self._m_base
        lane_by_g = self._lane_by_g
        from ..rsm import Task

        apply_gs = np.nonzero(o["apply_from"])[0]
        if apply_gs.size:
            applied_n = lanes_n = 0
            t_commit = time.monotonic()  # one clock read for the step
            for g, b, af, at in zip(
                apply_gs.tolist(),
                base[apply_gs].tolist(),
                o["apply_from"][apply_gs].tolist(),
                o["apply_to"][apply_gs].tolist(),
            ):
                lane = lane_by_g[g]
                if lane is None or not lane.active:
                    continue
                ents, missing_at = lane.arena.get_run(b + af, b + at)
                if ents is None:
                    # the ring only spans the device window; a restart
                    # replays the WHOLE committed log through the SM, whose
                    # early entries live in the host log alone
                    ents = self._fetch_from_log(lane, b + af, b + at)
                    if ents is None:
                        _plog.errorf(
                            "%s missing entry %d for apply (arena+log)",
                            lane.node.describe(), missing_at,
                        )
                        continue
                if not ents:
                    continue
                lane.node.sm.task_queue.add(
                    Task(
                        cluster_id=lane.node.cluster_id,
                        node_id=lane.node.node_id(),
                        entries=ents,
                    )
                )
                self._m_applied_since[g] += len(ents)
                applied_n += len(ents)
                lanes_n += 1  # this lane really handed work to the RSM
                # committed + dispatched to the RSM: no longer mem pressure
                lane.arena.mark_applied(b + at)
                has_cc = False
                for e in ents:
                    if e.type == EntryType.CONFIG_CHANGE:
                        has_cc = True
                    lt = e.lat
                    if lt is not None and lt.t_commit == 0.0:
                        # sampled proposal reached quorum commit this step
                        lt.t_commit = t_commit
                        if lt.trace_id:
                            flight_recorder().record(
                                "quorum_commit",
                                cluster=lane.node.cluster_id,
                                node=lane.node.node_id(),
                                trace=lt.trace_id, index=e.index,
                            )
                if has_cc:
                    lane.cc_inflight = False
                self.set_task_ready(lane.key)
            st["entries_applied"] += applied_n
            st["lanes_commit_advanced"] += lanes_n

    def _decode_reads(self, o: dict, skip_routed=None) -> None:
        """Phase 5: confirmed reads. ``skip_routed`` (multi-step) marks
        ready-queue slots whose READ_INDEX_RESP the kernel already
        routed to the forwarding origin's co-hosted lane — the host
        must not send a duplicate."""
        base = self._m_base
        lane_by_g = self._lane_by_g
        rc = o["ready_count"]
        ready_gs = np.nonzero(rc)[0]
        if ready_gs.size:
            # flatten the (lane, slot<count) pairs, then gather columns
            ridx = np.arange(o["ready_ctx"].shape[1])
            rrow, ris = np.nonzero(ridx[None, :] < rc[ready_gs, None])
            sel = ready_gs[rrow]
            read_sends: List[Tuple[_Lane, Message]] = []
            applied_lanes: Dict[_Lane, None] = {}
            for g, _slot, b, enc_lo, enc_hi, dev_idx, term in zip(
                sel.tolist(),
                ris.tolist(),
                base[sel].tolist(),
                o["ready_ctx"][sel, ris].tolist(),
                o["ready_ctx2"][sel, ris].tolist(),
                o["ready_index"][sel, ris].tolist(),
                # the confirming lane's own end-of-step term (== the
                # refreshed _m_term mirror on the K=1 path)
                o["term"][sel].tolist(),
            ):
                lane = lane_by_g[g]
                if lane is None or not lane.active:
                    continue
                node = lane.node
                applied_lanes[lane] = None
                if skip_routed is not None and skip_routed[g, _slot]:
                    continue  # kernel already routed this response
                enc = (enc_lo, enc_hi)
                idx = b + dev_idx
                origin = _ctx_origin(enc_lo)
                if origin == lane.self_slot():
                    ctx = lane.ri_pending.pop(enc, None)
                    if ctx is not None:
                        node.pending_read_indexes.add_ready_to_read(
                            [ReadyToRead(index=idx, system_ctx=ctx)]
                        )
                else:
                    to_nid = lane.rev.get(origin)
                    if to_nid is not None:
                        read_sends.append(
                            (
                                lane,
                                Message(
                                    type=MT.READ_INDEX_RESP,
                                    cluster_id=node.cluster_id,
                                    to=to_nid,
                                    from_=node.node_id(),
                                    term=term,
                                    log_index=idx,
                                    hint=enc_lo,
                                    hint_high=enc_hi,
                                ),
                            )
                        )
            self._dispatch_sends(read_sends)
            for lane in applied_lanes:
                lane.node.pending_read_indexes.applied(
                    lane.node.sm.last_applied_index()
                )

    def _dispatch_sends(self, sends: List[Tuple["_Lane", Message]]) -> None:
        """Hand a decode phase's (lane, Message) batch to each owning
        node's bulk send path: one co-hosted delivery pass plus one grouped
        wire send per node, instead of a queue hop per message. Relative
        order within the batch is preserved per destination."""
        if not sends:
            return
        # "deliver" sub-span: the bulk send/deliver seam's share of the
        # enclosing send/apply/reads phase (sampled iterations only — the
        # off path pays no clock reads)
        prof = self.profiler
        t0 = time.monotonic() if prof.sampling else 0.0
        by_node: Dict[object, List[Message]] = {}
        for lane, m in sends:
            node = lane.node
            lst = by_node.get(node)
            if lst is None:
                lst = by_node[node] = []
            lst.append(m)
        for node, msgs in by_node.items():
            many = node._send_messages
            if many is not None:
                many(msgs)
            else:
                send = node._send_message
                for m in msgs:
                    send(m)
        if prof.sampling:
            prof.add("deliver", time.monotonic() - t0)

    def _save_updates(self, updates: List[Update], lane_saves) -> None:
        """One multi-group write wave per step: a single write-batch per
        touched logdb shard with the durability barrier deferred, then one
        parallel sync over every touched WAL — group commit across shards
        AND across co-hosted NodeHosts' logdbs (a shared core hosts lanes
        from several hosts, each with its own WAL)."""
        if self._next_host <= 1:
            self._logdb.save_raft_state(updates)
            return
        if len(lane_saves) == 1:
            lane_saves[0][0].node.logdb.save_raft_state(updates)
            return
        by_db: Dict[int, tuple] = {}
        for (lane, _e, _s), ud in zip(lane_saves, updates):
            db = lane.node.logdb
            ent = by_db.get(id(db))
            if ent is None:
                ent = by_db[id(db)] = (db, [])
            ent[1].append(ud)
        pending = []
        for db, uds in by_db.values():
            deferred = getattr(db, "save_raft_state_deferred", None)
            if deferred is not None:
                pending.extend(deferred(uds))
            else:
                db.save_raft_state(uds)
        _kv_sync_all(pending)

    def _fetch_from_log(self, lane: _Lane, lo: int, hi: int):
        """Contiguous [lo, hi] from the host log (the arena ring's backing
        tier); None if the log cannot serve the whole range."""
        try:
            ents = lane.node.log_reader.entries(lo, hi + 1, 1 << 30)
        except Exception:
            return None
        if (
            len(ents) != hi - lo + 1
            or (ents and (ents[0].index != lo or ents[-1].index != hi))
        ):
            return None
        return ents
    # ------------------------------------------------------ catchup path
    def _below_window_reject(self, lane: _Lane, p: int, m: Message) -> None:
        """A reject whose hint is below the device window base: replicate
        the gap from the host log (or ship a snapshot), with the device
        remote parked so it stops probing indexes the follower cannot
        match. The park watermark is base+1: the first ack at or above the
        window base un-parks it and device replication takes over."""
        g = lane.g
        if p in lane.catchup or p in lane.snap_inflight:
            return  # recovery already running for this peer
        if int(self._m_role[g]) != ROLE.LEADER:
            return
        b = int(self._m_base[g])
        s = self._state
        self._state = s._replace(
            rstate=s.rstate.at[g, p].set(RSTATE.SNAPSHOT),
            snap_sent=s.snap_sent.at[g, p].set(1),
        )
        start = m.hint + 1
        goal = self._last_real(g)
        first, last = lane.node.log_reader.get_range()
        if start >= first and start <= last + 1:
            lane.catchup[p] = [start, goal, m.hint, self.clock.tick]
            self._catchups.add(lane)
        else:
            self._send_snapshot(lane, p)

    def _start_catchup(self, lane: _Lane, p: int, o) -> None:
        """A peer's next index fell behind the device window. If the host
        log still has the entries, replicate them host-side (the device has
        parked the peer in SNAPSHOT state; ReplicateResps move match and the
        kernel un-parks it once caught). Otherwise stream a real snapshot
        (cf. raft.go:774-785)."""
        if p in lane.catchup:
            return
        g = lane.g
        b = int(self._m_base[g])
        goal = b + int(o["last_index"][g])
        match = b + int(o["match"][g, p])
        start = match + 1
        first, last = lane.node.log_reader.get_range()
        if start >= first and start <= last + 1:
            # [next_to_send, goal, match_at_progress, progress_tick]
            lane.catchup[p] = [start, goal, match, self.clock.tick]
            self._catchups.add(lane)
        else:
            # the follower needs entries the host log no longer has
            # (compacted behind a snapshot): only a snapshot can help
            self._send_snapshot(lane, p)

    def _send_snapshot(self, lane: _Lane, p: int) -> None:
        to_nid = lane.rev.get(p)
        if to_nid is None:
            return
        ss = lane.node.snapshotter.get_most_recent_snapshot()
        if ss is None or ss.is_empty():
            ss = lane.node.log_reader.snapshot()
        if ss is None or ss.is_empty():
            _plog.warningf(
                "%s peer %d needs a snapshot but none exists",
                lane.node.describe(), to_nid,
            )
            # still arm the feedback timer: the synthetic reject will
            # un-park the peer so host-log replication retries instead of
            # wedging it in SNAPSHOT state
            lane.snap_inflight[p] = (self.clock.tick, 0)
            self._snapfb.add(lane)
            return
        if p in lane.wit_slots:
            # witnesses get a real (non-dummy) snapshot record with the
            # data payload stripped (cf. raft.go:699-707)
            ss = _make_witness_snapshot(ss)
        lane.node._send_message(
            Message(
                type=MT.INSTALL_SNAPSHOT,
                cluster_id=lane.node.cluster_id,
                to=to_nid,
                from_=lane.node.node_id(),
                term=int(self._m_term[lane.g]),
                snapshot=ss,
            )
        )
        # reconcile the device's parked-peer watermark to the snapshot
        # ACTUALLY sent (the kernel parked it at the leader's last index):
        # the remote un-parks once match >= snap_sent (remote.go:62-69,
        # 145-153), so the watermark must be reachable by restoring this
        # snapshot or the peer wedges in SNAPSHOT state forever
        g = lane.g
        dev_idx = max(int(ss.index - self._m_base[g]), 0)
        s = self._state
        self._state = s._replace(
            snap_sent=s.snap_sent.at[g, p].set(dev_idx)
        )
        lane.snap_inflight[p] = (self.clock.tick, ss.index)
        self._snapfb.add(lane)

    def _run_catchups(self, lane: _Lane, o) -> None:
        if not lane.catchup:
            self._catchups.discard(lane)
            return
        g = lane.g
        b = int(self._m_base[g])
        # a follower that stops acking for two election timeouts is treated
        # as lost (the same silence bound the protocol uses to declare a
        # leader dead) and falls back to the snapshot path
        stall_ticks = max(2 * lane.cfg.election_rtt, 8)
        done = []
        for p, cu in lane.catchup.items():
            nxt, goal, last_match, progress_tick = cu
            match = b + int(o["match"][g, p])
            if match >= goal or int(self._m_role[g]) != ROLE.LEADER:
                done.append(p)
                continue
            if match > last_match:
                cu[2], cu[3] = match, self.clock.tick
            elif self.clock.tick - progress_tick > stall_ticks:
                done.append(p)
                self._send_snapshot(lane, p)
                continue
            if match + 1 > nxt:
                nxt = match + 1
            first, last = lane.node.log_reader.get_range()
            if nxt < first:
                done.append(p)
                self._send_snapshot(lane, p)
                continue
            if nxt > last:
                continue  # wait for the follower to ack what's in flight
            hi = min(nxt + self.kcfg.max_entries_per_msg - 1, last, goal)
            try:
                ents = lane.node.log_reader.entries(nxt, hi + 1, 1 << 20)
                prev = nxt - 1
                prev_term = (
                    lane.node.log_reader.term(prev) if prev > 0 else 0
                )
            except Exception:
                done.append(p)
                self._send_snapshot(lane, p)
                continue
            if not ents:
                done.append(p)
                continue
            to_nid = lane.rev.get(p)
            if to_nid is None:
                done.append(p)
                continue
            if p in lane.wit_slots:
                # host catchup honors the witness shape too
                ents = _make_metadata_entries(ents)
            lane.node._send_message(
                Message(
                    type=MT.REPLICATE,
                    cluster_id=lane.node.cluster_id,
                    to=to_nid,
                    from_=lane.node.node_id(),
                    term=int(self._m_term[g]),
                    log_index=prev,
                    log_term=prev_term,
                    commit=min(self._committed_real(g), ents[-1].index),
                    entries=ents,
                )
            )
            cu[0] = ents[-1].index + 1
        for p in done:
            lane.catchup.pop(p, None)
        if not lane.catchup:
            self._catchups.discard(lane)

    def _run_snapshot_feedback(self, lane: _Lane, o) -> None:
        """Delayed snapshot-status retry (cf. feedback.go:38-128): an
        InstallSnapshot that is not acked within the retry window gets a
        synthetic SNAPSHOT_STATUS reject queued to the local lane. The
        kernel then moves the remote SNAPSHOT->WAIT (next=match+1); the
        following HeartbeatResp probes it, and replication — or another
        snapshot — retries. Without this, a snapshot lost to a partition
        wedges the remote in SNAPSHOT state forever."""
        if not lane.snap_inflight:
            self._snapfb.discard(lane)
            return
        g = lane.g
        b = int(self._m_base[g])
        retry_ticks = max(4 * lane.cfg.election_rtt, 16)
        is_leader = int(self._m_role[g]) == ROLE.LEADER
        done = []
        for p, (sent_tick, ss_index) in lane.snap_inflight.items():
            match = b + int(o["match"][g, p])
            if not is_leader or (ss_index > 0 and match >= ss_index):
                done.append(p)  # acked (or leadership moved on)
                continue
            if self.clock.tick - sent_tick > retry_ticks:
                done.append(p)
                from_nid = lane.rev.get(p)
                if from_nid is not None:
                    lane.node.mq.add(
                        Message(
                            type=MT.SNAPSHOT_STATUS,
                            cluster_id=lane.node.cluster_id,
                            to=lane.node.node_id(),
                            from_=from_nid,
                            reject=True,
                        )
                    )
                    self.set_node_ready(lane.key)
        for p in done:
            lane.snap_inflight.pop(p, None)
        if not lane.snap_inflight:
            self._snapfb.discard(lane)

    # --------------------------------------------------------- maintenance
    def _maintain(self, o) -> None:
        W = self.kcfg.log_window
        lane_by_g = self._lane_by_g
        for lane in list(self._catchups):
            self._run_catchups(lane, o)
        for lane in list(self._snapfb):
            self._run_snapshot_feedback(lane, o)
        # parked-peer watchdog: a remote in SNAPSHOT state whose host-side
        # recovery (catchup or snapshot feedback) is no longer tracked is
        # permanently wedged — the kernel only reports NEED_SNAPSHOT for
        # UNpaused peers, so nothing would ever re-arm it. Leadership races
        # (a catchup exiting on a stale goal, a feedback entry fast-acked
        # against an older snapshot watermark) can drop the tracker; this
        # sweep re-enters the recovery path. (cf. the reference's
        # unconditional snapshot-status feedback loop, feedback.go:38-128)
        parked = (o["rstate"] == RSTATE.SNAPSHOT) & (
            (o["role"] == ROLE.LEADER)[:, None]
        )
        for g, p in zip(*np.nonzero(parked)):
            lane = lane_by_g[g]
            if (
                lane is None
                or not lane.active
                or p in lane.catchup
                or p in lane.snap_inflight
            ):
                continue
            self._start_catchup(lane, int(p), o)
        # periodic snapshot by applied-entry count (node.go:585-601); a
        # wedged window forces one regardless of config. Candidates are
        # found vectorized; only triggering lanes cost Python.
        log_full = o["log_full"]
        snap_due = (
            self._m_active
            & ~self._m_snap_pending
            & (
                log_full
                | (
                    (self._m_snap_every > 0)
                    & (self._m_applied_since >= self._m_snap_every)
                )
            )
        )
        for g in np.nonzero(snap_due)[0].tolist():
            lane = lane_by_g[g]
            if lane is None or lane.node.snapshotter is None:
                continue
            applied, _ = lane.node.sm.get_last_applied()
            if applied > 0 and not lane.cfg.is_witness:
                self._m_snap_pending[g] = True
                self._m_applied_since[g] = 0
                from ..rsm import SSRequest

                lane.node.push_take_snapshot_request(SSRequest())
        # device window compaction: advance first_index once the window is
        # half full; applied entries are recoverable from the host log
        # (catchup path) or a snapshot, so the device needs neither
        used = o["last_index"].astype(np.int64) - self._m_devfirst + 1
        compact_due = self._m_active & ((used > W // 2) | log_full)
        adv_mask = np.zeros(self.kcfg.groups, bool)
        adv_first = np.zeros(self.kcfg.groups, np.int32)
        adv_term = np.zeros(self.kcfg.groups, np.int32)
        for g in np.nonzero(compact_due)[0].tolist():
            lane = lane_by_g[g]
            if lane is None:
                continue
            b = int(self._m_base[g])
            applied, applied_term = lane.node.sm.get_last_applied()
            target = min(applied, self._committed_real(g))
            if target + 1 > b + int(self._m_devfirst[g]):
                first_new = target - b + 1
                self._m_devfirst[g] = first_new
                adv_mask[g] = True
                adv_first[g] = first_new
                adv_term[g] = applied_term
        if adv_mask.any():
            # FIXED-SHAPE masked update: an .at[gs].set scatter would
            # recompile for every distinct batch length (observed as
            # 300-700ms step spikes under load — long enough to pile ticks
            # and trigger spurious elections); whole-G where() compiles once
            s = self._state
            m = jnp.asarray(adv_mask)
            self._state = s._replace(
                first_index=jnp.where(
                    m, jnp.asarray(adv_first), s.first_index
                ),
                marker_term=jnp.where(m, jnp.asarray(adv_term), s.marker_term),
            )
        if bool(np.any(o["last_index"] > _REBASE_THRESHOLD)):
            # never rebase under an in-flight step: the mirrors and the
            # pending output would disagree by the rebase delta. The
            # threshold leaves orders of magnitude more headroom than the
            # one extra step this defers by.
            self._rebase_due = True

    def _do_rebase(self) -> None:
        """Shift device indexes down so they never near 2**31. The delta is
        a multiple of W (ring-slot invariant, cf. ops/state.rebase)."""
        W = self.kcfg.log_window
        G = self.kcfg.groups
        delta = np.zeros((G,), np.int32)
        with self._lanes_mu:
            lanes = [ln for ln in self._lanes.values() if ln.active]
        for lane in lanes:
            g = lane.g
            d = int((self._m_devfirst[g] - 1) // W) * W
            if d > 0:
                delta[g] = d
                self._m_base[g] += d
                self._m_devfirst[g] -= d
                self._m_commit[g] -= d
                self._m_last[g] -= d
        if delta.any():
            self._state = rebase(self._state, jnp.asarray(delta))
            # window bases moved: the routing table's per-peer base
            # deltas must be recomputed before the next dispatch
            self._routes_dirty = True
            if self._multi > 1 and self._m_resid.any():
                # the device-resident residual inbox carries indexes in
                # DESTINATION units: shift the index-valued fields of
                # each parked message by its destination lane's delta
                # (type-aware, mirroring which fields _pack_wire stages
                # per message type). Rare path — eager device ops.
                r = self._resid
                d = jnp.asarray(delta)[:, None]
                mt = r.mtype
                idx_t = (
                    (mt == MSG.REPLICATE)
                    | (mt == MSG.REPLICATE_RESP)
                    | (mt == MSG.READ_INDEX_RESP)
                    | (mt == MSG.REQUEST_VOTE)
                )
                commit_t = (mt == MSG.REPLICATE) | (mt == MSG.HEARTBEAT)
                hint_t = mt == MSG.REPLICATE_RESP
                self._resid = r._replace(
                    log_index=jnp.where(
                        idx_t, r.log_index - d, r.log_index
                    ),
                    commit=jnp.where(
                        commit_t, jnp.maximum(r.commit - d, 0), r.commit
                    ),
                    hint=jnp.where(
                        hint_t, jnp.maximum(r.hint - d, 0), r.hint
                    ),
                )

    # ----------------------------------------------------------- reconciles
    def _apply_reconciles(self) -> None:
        batch: List[_Lane] = []
        cc_clear: List[int] = []
        while True:
            try:
                op = self._reconq.popleft()
            except IndexError:
                break
            if op[0] == "activate":
                batch.append(op[1])
                continue
            if op[0] == "cc_done":
                # batched below: one fixed-shape mask op instead of a
                # per-lane scatter (bootstrap emits one per cluster)
                lane = self._lane_of(op[1])
                if lane is not None and lane.active:
                    cc_clear.append(lane.g)
                    lane.cc_inflight = False
                continue
            if batch:
                self._activate_batch(batch)
                batch = []
            try:
                kind = op[0]
                if kind == "barrier":
                    op[1].set()
                elif kind == "deactivate":
                    self._deactivate(op[1])
                elif kind == "membership":
                    self._reconcile_membership(op[1])
                elif kind == "restore":
                    self._reconcile_restore(op[1], op[2])
                elif kind == "recover_done":
                    lane = self._lane_of(op[1])
                    if lane is not None:
                        lane.recovering = False
                        self._routes_dirty = True
            except Exception:
                import traceback

                traceback.print_exc()
        if batch:
            self._activate_batch(batch)
        if cc_clear:
            mask = np.zeros((self.kcfg.groups,), bool)
            mask[cc_clear] = True
            s = self._state
            self._state = s._replace(
                pending_cc=s.pending_cc & jnp.asarray(~mask)
            )

    def _lane_of(self, node) -> Optional[_Lane]:
        lane = node._vec_lane
        if lane is None:
            return None
        with self._lanes_mu:
            return lane if self._lanes.get(lane.key) is lane else None

    def _compute_activation(self, lane: _Lane) -> Optional[dict]:
        """Host-side half of lane bring-up: bootstrap (initial start),
        restart replay, or join-as-empty. Mirrors Peer.launch +
        node.replayLog (cf. core/peer.py:75-94, node.go:553-583). Returns
        the per-field device values for the batched scatter."""
        node = lane.node
        node.recover_initial_snapshot()
        cfg = lane.cfg
        g = lane.g
        W = self.kcfg.log_window
        P = self.kcfg.peers
        # membership sources: SM image (restart w/ snapshot) else bootstrap
        mem = node.sm.get_membership()
        member_ids = set(mem.addresses) | set(mem.observers) | set(mem.witnesses)
        if not member_ids:
            member_ids = {a.node_id for a in node._vec_addresses}
        bootstrap = node._vec_initial and node._vec_new_node
        lane.set_slots(member_ids)
        self_slot = lane.self_slot()
        if self_slot < 0 and node.node_id() not in lane.slots:
            # join path: self not yet in membership; park on a free slot
            self_slot = lane.slot_of(node.node_id(), provisional=True)
        obs_ids = set(mem.observers)
        wit_ids = set(mem.witnesses)
        if not mem.addresses and bootstrap:
            obs_ids, wit_ids = set(), set()
        lane.mem_sig = (
            frozenset(member_ids), frozenset(obs_ids), frozenset(wit_ids)
        )
        # persisted protocol state
        st = self._logdb_state(node)
        snap = node.snapshotter.get_most_recent_snapshot() if node.snapshotter else None
        snap_index = snap.index if snap is not None and not snap.is_empty() else 0
        first, last = node.log_reader.get_range()
        ents: List[Entry] = []
        if last >= first and last > 0:
            try:
                ents = node.log_reader.entries(first, last + 1, 1 << 30)
            except Exception:
                ents = []
        term = st.term
        vote_nid = st.vote
        committed = st.commit
        if bootstrap and not ents:
            # initial start: membership enters the log as config-change
            # entries at term 1, committed immediately (core/peer.py:273-294)
            addrs = sorted(node._vec_addresses, key=lambda a: a.node_id)
            from ..types import ConfigChange, ConfigChangeType

            for i, pa in enumerate(addrs):
                cc = ConfigChange(
                    type=ConfigChangeType.ADD_NODE,
                    node_id=pa.node_id,
                    initialize=True,
                    address=pa.address,
                )
                ents.append(
                    Entry(
                        type=EntryType.CONFIG_CHANGE,
                        term=1,
                        index=i + 1,
                        cmd=encode_config_change(cc),
                    )
                )
            committed = len(ents)
            term = max(term, 1)
        elif node._vec_new_node and not cfg.is_observer and not cfg.is_witness:
            term = max(term, 1)
        b = snap_index
        last_real = ents[-1].index if ents else max(snap_index, last if last else 0)
        dev_last = max(last_real - b, 0)
        dev_first = max(dev_last - W + 1, 1)
        committed = max(committed, snap_index)
        # ring metadata from the replayed entries
        ring_terms = np.zeros((W,), np.int32)
        ring_cc = np.zeros((W,), bool)
        for e in ents:
            lane.arena[e.index] = e
            di = e.index - b
            if dev_first <= di <= dev_last:
                ring_terms[di % W] = e.term
                ring_cc[di % W] = e.type == EntryType.CONFIG_CHANGE
        # arena holds nothing at or below the snapshot: seed the applied
        # watermark there directly (no entries below it to discount) so
        # the first phase-4 mark_applied walks the window, not the whole
        # history from zero
        lane.arena.applied = max(snap_index, lane.arena.applied)
        marker = dev_first - 1
        if marker == 0:
            marker_term = snap.term if snap_index and b == snap_index else 0
        else:
            try:
                marker_term = node.log_reader.term(b + marker)
            except Exception:
                marker_term = 0
        member = np.zeros((P,), bool)
        voting = np.zeros((P,), bool)
        observer = np.zeros((P,), bool)
        witness = np.zeros((P,), bool)
        for nid, slot in lane.slots.items():
            if slot >= P or nid not in member_ids:
                # provisional parkings (the join path parks self and
                # learned senders on free slots) are NOT members: marking
                # them voting would let an empty-membership join lane
                # self-elect as a one-node group and poison its log
                continue
            member[slot] = True
            if nid in obs_ids:
                observer[slot] = True
            elif nid in wit_ids:
                witness[slot] = True
                voting[slot] = True
            else:
                voting[slot] = True
        lane.wit_slots = frozenset(np.nonzero(witness)[0].tolist())
        role = (
            ROLE.OBSERVER if cfg.is_observer
            else ROLE.WITNESS if cfg.is_witness
            else ROLE.FOLLOWER
        )
        vote_slot = lane.slots.get(vote_nid, -1)
        et = max(cfg.election_rtt, 3)
        hb = max(cfg.heartbeat_rtt, 1)
        from ..ops.state import _mix

        rand_to = et + _mix(lane_seed(g), term, max(self_slot, 0)) % et
        # quiesce threshold: 10x the election timeout (cf. quiesce.go:84-86)
        quiesce_on = bool(cfg.quiesce)
        quiesce_threshold = 10 * et
        # ---- numpy mirrors ------------------------------------------------
        self._m_base[g] = b
        self._m_devfirst[g] = dev_first
        self._m_term[g] = term
        self._m_role[g] = role
        self._m_leader[g] = 0
        self._m_commit[g] = committed - b
        self._m_last[g] = dev_last
        # catch-up burst cap: at most this many coalesced ticks apply in
        # one kernel step; the rest of a stall's backlog is shed. The old
        # cap (election RTT) let a single post-stall step add
        # `election_rtt` ticks — every follower lane crossed rand_timeout
        # ∈ [et, 2et) within two steps simultaneously, collapsing the
        # randomized election spread into synchronized split-vote storms
        # (the ROADMAP seed flake). Capping at the heartbeat RTT keeps
        # timers advancing while a live leader's next heartbeat can still
        # land between bursts.
        burst = self._catchup_tick_cap or hb
        self._m_tick_cap[g] = max(1, min(cfg.election_rtt, burst))
        self._m_active[g] = True
        self._m_snap_every[g] = cfg.snapshot_entries
        self._m_applied_since[g] = 0
        self._ctr[g] = 0  # a reused lane must not inherit event counters
        self._m_snap_pending[g] = False
        self._m_quiesced[g] = False  # a reused lane must not inherit this
        self._m_leader_change_tick[g] = self.clock.tick
        return dict(
            self_slot=max(self_slot, 0),
            member=member,
            voting=voting,
            observer=observer,
            witness=witness,
            term=term,
            vote=vote_slot + 1 if vote_slot >= 0 else 0,
            role=role,
            election_timeout=et,
            heartbeat_timeout=hb,
            rand_timeout=rand_to,
            check_quorum=cfg.check_quorum,
            prevote_on=bool(cfg.pre_vote),
            lease_on=bool(cfg.lease_read),
            lease_margin=cfg.lease_margin_ticks() if cfg.lease_read else 0,
            first_index=dev_first,
            marker_term=marker_term,
            last_index=dev_last,
            committed=committed - b,
            processed=max(snap_index - b, 0),
            applied=max(snap_index - b, 0),
            unsaved_from=1 if bootstrap else dev_last + 1,
            log_term=ring_terms,
            log_is_cc=ring_cc,
            next=dev_last + 1,
            quiesce_on=quiesce_on,
            quiesce_threshold=quiesce_threshold,
        )

    # per-lane value keys forwarded into the jitted activation scatter
    _ACT_COLS = (
        ("self_slot", np.int32),
        ("term", np.int32),
        ("vote", np.int32),
        ("role", np.int32),
        ("election_timeout", np.int32),
        ("heartbeat_timeout", np.int32),
        ("rand_timeout", np.int32),
        ("check_quorum", bool),
        ("prevote_on", bool),
        ("lease_on", bool),
        ("lease_margin", np.int32),
        ("first_index", np.int32),
        ("marker_term", np.int32),
        ("last_index", np.int32),
        ("committed", np.int32),
        ("processed", np.int32),
        ("applied", np.int32),
        ("unsaved_from", np.int32),
        ("next", np.int32),
        ("quiesce_on", bool),
        ("quiesce_threshold", np.int32),
    )
    _ACT_MATS = (
        ("member", bool),
        ("voting", bool),
        ("observer", bool),
        ("witness", bool),
        ("log_term", np.int32),
        ("log_is_cc", bool),
    )

    def _activate_batch(self, lanes: List[_Lane]) -> None:
        """Activate many lanes with ONE jitted scatter call — the engine
        analogue of ops/state.configure_groups_uniform. Batches pad to
        power-of-4 buckets so the compile caches hit."""
        vals: List[dict] = []
        gs: List[int] = []
        for lane in lanes:
            try:
                v = self._compute_activation(lane)
            except Exception:
                import traceback

                traceback.print_exc()
                continue
            if v is not None:
                vals.append(v)
                gs.append(lane.g)
                lane.active = True
        if not vals:
            return
        n = len(vals)
        bucket = 1
        while bucket < n:
            bucket *= 4
        bucket = min(bucket, self.kcfg.groups)
        pad = bucket - n
        # padding repeats the last lane (duplicate scatter indexes with
        # identical values are order-independent)
        gi = np.asarray(gs + [gs[-1]] * pad, np.int32)
        v = {}
        for key, dtype in self._ACT_COLS:
            a = np.asarray([x[key] for x in vals], dtype)
            if pad:
                a = np.concatenate([a, np.repeat(a[-1:], pad, 0)])
            v[key] = jnp.asarray(a)
        for key, dtype in self._ACT_MATS:
            a = np.stack([x[key] for x in vals]).astype(dtype)
            if pad:
                a = np.concatenate([a, np.repeat(a[-1:], pad, 0)])
            v[key] = jnp.asarray(a)
        fn = _make_activate_fn(self.kcfg, bucket)
        self._state = fn(self._state, jnp.asarray(gi), v)
        self._routes_dirty = True
        self._ready.set()

    def _logdb_state(self, node) -> State:
        st, _ = node.log_reader.node_state()
        return st if st is not None else State()

    def _deactivate(self, lane: _Lane) -> None:
        g = lane.g
        with self._lanes_mu:
            if self._lane_by_g[g] is not lane:
                # already reaped (a double remove_node, or a crash path
                # racing a graceful stop): freeing g twice would hand the
                # same lane index to two tenants
                return
        s = self._state
        self._state = s._replace(active=s.active.at[g].set(False))
        lane.active = False
        # zero the freed lane's host planes so nothing leaks into the next
        # tenant of g: the inbox staging rows of BOTH buffer sets (the
        # overlap pipeline alternates sets; the next occupant must never
        # see a stale row where _pack left data the kernel has already
        # consumed), the pending-tick row, and every protocol mirror
        # (lane_stats/decode gate on _m_active, but stale bases would
        # corrupt the first reads after a mis-gated access)
        for buf, ticks, _inbox in self._bufsets:
            for name, plane in buf.items():
                plane[g] = MSG.NONE if name == "mtype" else 0
            ticks[g] = 0
        self._m_base[g] = 0
        self._m_devfirst[g] = 1
        self._m_term[g] = 0
        self._m_role[g] = ROLE.FOLLOWER
        self._m_leader[g] = 0
        self._m_commit[g] = 0
        self._m_last[g] = 0
        self._m_tick_cap[g] = 1
        self._m_active[g] = False
        self._m_snap_every[g] = 0
        self._m_applied_since[g] = 0
        self._m_snap_pending[g] = False
        self._m_quiesced[g] = False
        self._m_host[g] = 0
        self._m_leader_change_tick[g] = 0
        self._ctr[g] = 0
        self._carry.discard(lane)
        self._catchups.discard(lane)
        self._snapfb.discard(lane)
        # multi-step: the freed lane must not hand its device-routed
        # residual rows or pending payload copies to the next tenant
        self._m_resid[g] = 0
        if self._multi > 1:
            r = self._resid
            self._resid = r._replace(
                mtype=r.mtype.at[g].set(jnp.int32(MSG.NONE))
            )
            self._pending_rep_copies = [
                c
                for c in self._pending_rep_copies
                if c[2] is not lane and c[3] is not lane
            ]
        self._routes_dirty = True
        lane.node._vec_lane = None
        with self._lanes_mu:
            self._lane_by_g[g] = None
            self._free.append(g)

    def _reconcile_membership(self, node) -> None:
        """Recompute the canonical slot mapping from the applied membership
        image and permute the per-peer device state accordingly."""
        lane = self._lane_of(node)
        if lane is None or not lane.active:
            return
        mem = node.sm.get_membership()
        member_ids = set(mem.addresses) | set(mem.observers) | set(mem.witnesses)
        if not member_ids:
            return
        sig = (
            frozenset(member_ids),
            frozenset(mem.observers),
            frozenset(mem.witnesses),
        )
        if sig == lane.mem_sig:
            return  # image unchanged (bootstrap CCs restate membership)
        lane.mem_sig = sig
        P = self.kcfg.peers
        g = lane.g
        perm = lane.set_slots(member_ids)
        s = self._state
        # permute [P]-indexed rows: value at old slot moves to new slot
        def permute_row(row, default):
            vals = np.asarray(row)
            out = np.full_like(vals, default)
            for old, new in perm.items():
                if old < P and new < P:
                    out[new] = vals[old]
            return out

        member = np.zeros((P,), bool)
        voting = np.zeros((P,), bool)
        observer = np.zeros((P,), bool)
        witness = np.zeros((P,), bool)
        for nid, slot in lane.slots.items():
            if slot >= P:
                continue
            member[slot] = True
            if nid in mem.observers:
                observer[slot] = True
            elif nid in mem.witnesses:
                witness[slot] = True
                voting[slot] = True
            else:
                voting[slot] = True
        lane.wit_slots = frozenset(np.nonzero(witness)[0].tolist())
        dev_last = int(np.asarray(s.last_index[g]))
        match = permute_row(s.match[g], 0)
        nxt = permute_row(s.next[g], dev_last + 1)
        nxt = np.maximum(nxt, 1)
        rstate = permute_row(s.rstate[g], RSTATE.RETRY)
        ract = permute_row(s.ract[g], False)
        snap_sent = permute_row(s.snap_sent[g], 0)
        vresp = permute_row(s.vresp[g], False)
        vgrant = permute_row(s.vgrant[g], False)

        def remap_ref(v):
            # slot+1 encoded references (leader/vote/transfer)
            v = int(np.asarray(v))
            if v <= 0:
                return 0
            new = perm.get(v - 1)
            return new + 1 if new is not None else 0

        self_slot = lane.self_slot()
        if self_slot < 0:
            self_slot = lane.slot_of(node.node_id(), provisional=True)
        new_leader = remap_ref(s.leader[g])
        # self-promotion: an observer added as a full member becomes a
        # follower in place, inheriting its replicated log (cf. raft.go
        # addNode / scalar Raft.add_node become_follower path)
        if (
            int(self._m_role[g]) == ROLE.OBSERVER
            and node.node_id() in mem.addresses
        ):
            self._m_role[g] = ROLE.FOLLOWER
            s = s._replace(role=s.role.at[g].set(ROLE.FOLLOWER))
        upd = dict(
            member=s.member.at[g].set(jnp.asarray(member)),
            voting=s.voting.at[g].set(jnp.asarray(voting)),
            observer=s.observer.at[g].set(jnp.asarray(observer)),
            witness=s.witness.at[g].set(jnp.asarray(witness)),
            self_slot=s.self_slot.at[g].set(max(self_slot, 0)),
            leader=s.leader.at[g].set(new_leader),
            vote=s.vote.at[g].set(remap_ref(s.vote[g])),
            transfer_to=s.transfer_to.at[g].set(remap_ref(s.transfer_to[g])),
            match=s.match.at[g].set(jnp.asarray(match)),
            next=s.next.at[g].set(jnp.asarray(nxt)),
            rstate=s.rstate.at[g].set(jnp.asarray(rstate)),
            ract=s.ract.at[g].set(jnp.asarray(ract)),
            snap_sent=s.snap_sent.at[g].set(jnp.asarray(snap_sent)),
            vresp=s.vresp.at[g].set(jnp.asarray(vresp)),
            vgrant=s.vgrant.at[g].set(jnp.asarray(vgrant)),
            # ack bitmasks are slot-indexed: clear and let heartbeats
            # re-confirm (membership changes are rare)
            ri_acks=s.ri_acks.at[g].set(0),
        )
        self._state = s._replace(**upd)
        self._m_leader[g] = new_leader
        # catchup/snapshot-feedback mirrors use slots: remap
        remapped = {}
        for p, v in lane.catchup.items():
            if p in perm:
                remapped[perm[p]] = v
        lane.catchup = remapped
        if not lane.catchup:
            self._catchups.discard(lane)
        lane.snap_inflight = {
            perm[p]: v for p, v in lane.snap_inflight.items() if p in perm
        }
        if not lane.snap_inflight:
            self._snapfb.discard(lane)
        # the slot mapping changed: rebuild the on-device routing rows
        self._routes_dirty = True

    def _reconcile_restore(self, node, ss: Snapshot) -> None:
        """An InstallSnapshot finished recovering: rebuild the lane at the
        snapshot point (cf. raft.go:439-517 restore + restoreRemotes)."""
        lane = self._lane_of(node)
        if lane is None:
            return
        g = lane.g
        P = self.kcfg.peers
        W = self.kcfg.log_window
        mem = ss.membership or node.sm.get_membership()
        member_ids = set(mem.addresses) | set(mem.observers) | set(mem.witnesses)
        lane.set_slots(member_ids)
        lane.mem_sig = (
            frozenset(member_ids),
            frozenset(mem.observers),
            frozenset(mem.witnesses),
        )
        lane.arena = _Arena(self.kcfg.log_window)
        # everything at or below the installed snapshot is applied; seeding
        # the watermark keeps the next phase-4 mark_applied from walking
        # the whole history from zero (same as the activation path)
        lane.arena.applied = max(ss.index, 0)
        lane.catchup = {}
        lane.snap_inflight = {}
        self._catchups.discard(lane)
        self._snapfb.discard(lane)
        member = np.zeros((P,), bool)
        voting = np.zeros((P,), bool)
        observer = np.zeros((P,), bool)
        witness = np.zeros((P,), bool)
        for nid, slot in lane.slots.items():
            if slot >= P:
                continue
            member[slot] = True
            if nid in mem.observers:
                observer[slot] = True
            elif nid in mem.witnesses:
                witness[slot] = True
                voting[slot] = True
            else:
                voting[slot] = True
        lane.wit_slots = frozenset(np.nonzero(witness)[0].tolist())
        self_slot = lane.self_slot()
        if self_slot < 0:
            self_slot = lane.slot_of(node.node_id(), provisional=True)
        s = self._state
        # the lane may carry the snapshot sender's (higher) term, adopted
        # in _handle_install_snapshot; the restore ack must not be
        # droppable as stale by the leader
        term = max(int(np.asarray(s.term[g])), ss.term, lane.adopted_term)
        lane.adopted_term = 0
        upd = dict(
            member=s.member.at[g].set(jnp.asarray(member)),
            voting=s.voting.at[g].set(jnp.asarray(voting)),
            observer=s.observer.at[g].set(jnp.asarray(observer)),
            witness=s.witness.at[g].set(jnp.asarray(witness)),
            self_slot=s.self_slot.at[g].set(max(self_slot, 0)),
            term=s.term.at[g].set(term),
            first_index=s.first_index.at[g].set(1),
            marker_term=s.marker_term.at[g].set(ss.term),
            last_index=s.last_index.at[g].set(0),
            committed=s.committed.at[g].set(0),
            processed=s.processed.at[g].set(0),
            applied=s.applied.at[g].set(0),
            unsaved_from=s.unsaved_from.at[g].set(1),
            log_term=s.log_term.at[g].set(jnp.zeros((W,), jnp.int32)),
            log_is_cc=s.log_is_cc.at[g].set(jnp.zeros((W,), bool)),
            match=s.match.at[g].set(0),
            next=s.next.at[g].set(1),
            rstate=s.rstate.at[g].set(RSTATE.RETRY),
            snap_sent=s.snap_sent.at[g].set(0),
            ri_ctx=s.ri_ctx.at[g].set(0),
            ri_index=s.ri_index.at[g].set(0),
            ri_acks=s.ri_acks.at[g].set(0),
            ri_count=s.ri_count.at[g].set(0),
        )
        self._state = s._replace(**upd)
        # ---- numpy mirrors ------------------------------------------------
        self._m_base[g] = ss.index
        self._m_devfirst[g] = 1
        self._m_term[g] = term
        self._m_commit[g] = 0
        self._m_last[g] = 0
        self._m_quiesced[g] = False
        lane.recovering = False
        # base moved + recovering cleared: recompute routes/base deltas
        self._routes_dirty = True
        # restart/rejoin forensics: a lagging rejoiner whose log was
        # compacted past its index MUST take this path — the longhaul
        # runner and the restart tests assert on this event
        flight_recorder().record(
            "snapshot_installed", cluster=node.cluster_id,
            node=node.node_id(), index=ss.index, term=ss.term,
        )
        # persist the post-restore hard state and ack the leader so its
        # remote leaves the Snapshot state (raft.go handleInstallSnapshot)
        node.logdb.save_raft_state(
            [
                Update(
                    cluster_id=node.cluster_id,
                    node_id=node.node_id(),
                    state=State(term=term, vote=0, commit=ss.index),
                )
            ]
        )
        leader = lane.rev.get(int(self._m_leader[g]) - 1)
        sender = leader if leader and leader != node.node_id() else None
        if sender is None:
            # best effort: ack every voting peer; only the leader cares
            senders = [n for n in lane.slots if n != node.node_id()]
        else:
            senders = [sender]
        for nid in senders:
            node._send_message(
                Message(
                    type=MT.REPLICATE_RESP,
                    cluster_id=node.cluster_id,
                    to=nid,
                    from_=node.node_id(),
                    term=term,
                    log_index=ss.index,
                )
            )

    # --------------------------------------------------------- worker mains
    def _task_worker_main(self, worker: int) -> None:
        batch: list = []
        apply: list = []
        while not self._stopped.is_set():
            cids = self.task_ready.wait_and_take(worker)
            if not cids:
                continue
            for cid in cids:
                node = self.get_node(cid)
                if node is None or node.stopped:
                    continue
                if not node.sm.loaded(OffloadFrom.COMMIT_WORKER):
                    continue  # lost the race with NodeHost close
                try:
                    node.handle_task(batch, apply)
                except Exception:
                    import traceback

                    traceback.print_exc()
                finally:
                    node.sm.offloaded(OffloadFrom.COMMIT_WORKER)
                if node.sm.task_queue.size() > 0:
                    self.set_task_ready(cid)

    def _snapshot_worker_main(self, worker: int) -> None:
        while not self._stopped.is_set():
            cids = self.snapshot_ready.wait_and_take(worker)
            if not cids:
                continue
            for cid in cids:
                node = self.get_node(cid)
                if node is None or node.stopped:
                    continue
                if not node.sm.loaded(OffloadFrom.SNAPSHOT_WORKER):
                    continue  # lost the race with NodeHost close
                try:
                    node.run_snapshot_work()
                except Exception:
                    import traceback

                    traceback.print_exc()
                finally:
                    node.sm.offloaded(OffloadFrom.SNAPSHOT_WORKER)
                lane = self._lane_of(node)
                if lane is not None:
                    self._m_snap_pending[lane.g] = False

    # --------------------------------------------------------------- control
    def profile_summary(self) -> dict:
        return self.profiler.summary()

    def fairness_stats(self) -> dict:
        """Tick-fairness watchdog snapshot: inter-iteration latency vs the
        tick period, the starvation gauge, burst clamps, enforced yields."""
        return self.watchdog.stats()

    def step_stats(self) -> dict:
        """Cumulative per-step columnar counters (kernel steps, outbound
        messages by plane, lanes with commit advance, elections started,
        entries handed to the RSM) — derived host-side from the decoded
        StepOutput, so reading them costs nothing on the device."""
        return dict(self._sstats)

    def lease_stats(self) -> dict:
        """Cumulative lease read counters across all lanes: 'local' =
        linearizable reads served straight off a live leader lease (no
        quorum round), 'fallback' = lease-enabled reads that degraded to
        the ReadIndex quorum path (lease expired / revoked / clock
        suspect). Plain int reads of decode-maintained counters."""
        return {"local": self._lease_local, "fallback": self._lease_fb}

    def counter_stats(self) -> Dict[str, int]:
        """Cumulative protocol-event totals across all lanes, keyed by
        the canonical CTR_NAMES vocabulary (elections started/won,
        heartbeats sent, replicate rejects, commit advances IN INDEX
        UNITS, lease served/fallback, read confirmations). The deltas
        were counted ON DEVICE inside step_batch — including K>1 inner
        steps and device-routed co-hosted traffic — and folded by the
        decode phase; reading them is a plain numpy sum over the
        cumulative mirror, zero device syncs."""
        totals = self._ctr.sum(axis=0)
        return {name: int(totals[i]) for i, name in enumerate(CTR_NAMES)}

    def lane_counters(self) -> Dict[tuple, Dict[str, int]]:
        """Per-lane cumulative event counters (lane key -> CTR_NAMES
        dict), same sourcing as counter_stats. Joined with lane_stats on
        the lane key by tools.top's heat ranking."""
        out: Dict[tuple, Dict[str, int]] = {}
        with self._lanes_mu:
            lanes = list(self._lanes.values())
        ctr = self._ctr
        for lane in lanes:
            if not lane.active:
                continue
            row = ctr[lane.g]
            out[lane.key] = {
                name: int(row[i]) for i, name in enumerate(CTR_NAMES)
            }
        return out

    def device_census(self) -> dict:
        """HBM census snapshot: static plane bytes (reported once at
        allocation) + per-lane logical log fill folded from the decode-
        maintained mirrors — zero device syncs, like lane_stats. The
        ROADMAP paged-arena item's measured baseline."""
        return self.census.snapshot(
            last=self._m_last,
            devfirst=self._m_devfirst,
            active=self._m_active,
        )

    def pressure_stats(self) -> dict:
        """Serving-front backpressure probe (serving.backpressure.
        SaturationMonitor): inbox-row occupancy of the last packed step
        (fraction of the worked lanes' K-row capacity actually filled)
        and the staged-row backlog carried between steps. Plain reads of
        the pack-maintained counters — lock-free, zero device syncs."""
        lanes = self._p_inbox_lanes
        if not lanes:
            return {"inbox_occupancy": 0.0, "staged_backlog": 0}
        return {
            "inbox_occupancy": self._p_inbox_rows
            / (lanes * self.kcfg.inbox_depth),
            "staged_backlog": self._p_staged_backlog,
        }

    def lane_stats(self) -> Dict[tuple, dict]:
        """Per-lane introspection derived ENTIRELY from the numpy mirrors
        the decode phase already maintains — zero device syncs: lane key ->
        {node_id, leader_id, term, commit_gap, ticks_since_leader_change}.
        commit_gap is last_index - commit_index in device units (how far
        the lane's accepted log runs ahead of its quorum commit — a
        persistently large gap flags a lane that cannot reach quorum).
        Exported ~1/s by NodeHost._export_health_gauges as cluster_id-
        labelled engine_lane_* gauges and folded into bench.py's JSON."""
        out: Dict[tuple, dict] = {}
        with self._lanes_mu:
            lanes = list(self._lanes.values())
        leader = self._m_leader
        term = self._m_term
        commit = self._m_commit
        last = self._m_last
        role = self._m_role
        chg = self._m_leader_change_tick
        tick = self.clock.tick
        for lane in lanes:
            if not lane.active:
                continue
            g = lane.g
            out[lane.key] = {
                "node_id": lane.node.node_id(),
                "leader_id": lane.rev.get(int(leader[g]) - 1, 0),
                "term": int(term[g]),
                "commit_gap": max(int(last[g] - commit[g]), 0),
                # monotonic append high-water mark in device units: the
                # placement plane's ingest-rate signal is the DELTA of
                # this between two load folds (serving/placement.py) —
                # still a pure mirror read, zero device syncs
                "last_index": int(last[g]),
                "ticks_since_leader_change": max(int(tick - chg[g]), 0),
                # lane-variant probes: the replica's role (observer/witness
                # lanes included) and resident client-payload bytes — a
                # witness lane must report payload_bytes == 0 (the
                # observer_witness_churn verdict and tests assert on it)
                "role": int(role[g]),
                "payload_bytes": lane.arena.payload_bytes,
            }
        return out

    def hot_lane_stats(
        self, k: int, host: Optional[int] = None
    ) -> Tuple[Dict[tuple, dict], int]:
        """The k hottest active lanes by commit gap (optionally filtered
        to one co-hosted NodeHost), plus the total count the cap hides:
        (lane key -> lane_stats row + heat-relevant counter columns,
        total_active). Selection is one numpy gather + argpartition over
        the decode-maintained mirrors — a 50k-lane host pays the
        per-lane dict cost only for the k lanes somebody will look at
        (the history sampler's slot-bounded lane table, tools.top's
        default ranking input). Counter columns (HOT_LANE_COUNTERS) come
        off the same cumulative host mirror as counter_stats — zero
        device syncs, like everything on this surface."""
        with self._lanes_mu:
            lanes = [
                lane
                for lane in self._lanes.values()
                if lane.active and (host is None or lane.key[0] == host)
            ]
        total = len(lanes)
        out: Dict[tuple, dict] = {}
        if not lanes:
            return out, 0
        k = max(1, int(k))
        gs = np.fromiter((lane.g for lane in lanes), np.int64, total)
        gaps = np.maximum(self._m_last[gs] - self._m_commit[gs], 0)
        if total > k:
            pick = np.argpartition(gaps, total - k)[total - k:]
            # hottest-first order inside the cap (stable for renderers)
            pick = pick[np.argsort(-gaps[pick], kind="stable")]
        else:
            pick = np.argsort(-gaps, kind="stable")
        leader = self._m_leader
        term = self._m_term
        last = self._m_last
        role = self._m_role
        chg = self._m_leader_change_tick
        tick = self.clock.tick
        ctr = self._ctr
        ctr_cols = [
            (name, CTR_NAMES.index(name)) for name in HOT_LANE_COUNTERS
        ]
        for i in pick:
            lane = lanes[int(i)]
            g = lane.g
            row = ctr[g]
            out[lane.key] = {
                "node_id": lane.node.node_id(),
                "leader_id": lane.rev.get(int(leader[g]) - 1, 0),
                "term": int(term[g]),
                "commit_gap": int(gaps[int(i)]),
                "last_index": int(last[g]),
                "ticks_since_leader_change": max(int(tick - chg[g]), 0),
                "role": int(role[g]),
                "payload_bytes": lane.arena.payload_bytes,
                "counters": {n: int(row[ci]) for n, ci in ctr_cols},
            }
        return out, total

    def leader_snapshot(self) -> Dict[tuple, Tuple[int, int]]:
        """One vectorized pass over the numpy mirrors: lane key ->
        (leader_node_id, term) for every active lane. Replaces per-group
        get_leader_id polling at fleet bring-up (50k lanes = one call)."""
        out: Dict[tuple, Tuple[int, int]] = {}
        with self._lanes_mu:
            lanes = list(self._lanes.values())
        leader = self._m_leader
        term = self._m_term
        for lane in lanes:
            if not lane.active:
                continue
            g = lane.g
            out[lane.key] = (
                lane.rev.get(int(leader[g]) - 1, 0), int(term[g])
            )
        return out

    def attach_host(self) -> int:
        with self._hosts_mu:
            host = self._next_host
            self._next_host += 1
            self._host_refs.add(host)
        return host

    def release(self, host: int, flush: bool = True) -> None:
        """Detach one NodeHost handle; the core stops when the last handle
        releases (a shared core outlives any single host). The last-ref
        check and the registry removal happen under _shared_mu so a
        concurrent get_vector_engine() can never attach to a core that is
        about to stop. A non-last release drains the loop once so the
        departing host's lanes are fully deactivated before its NodeHost
        closes the logdb under them.

        flush=False is the CRASH path (NodeHost.crash): a sole-tenant core
        discards its un-decoded in-flight step instead of landing it — a
        SIGKILL'd process would never have decoded or saved that output.
        On a shared core the in-flight step belongs to the surviving
        hosts too, so crash granularity there is the lane teardown and
        the shared step still decodes."""
        with _shared_mu:
            with self._hosts_mu:
                self._host_refs.discard(host)
                self._blocked_hosts.discard(host)
                last = not self._host_refs
            if last:
                _forget_shared_core_locked(self)
        if last:
            self.stop(flush=flush)
        else:
            self.drain()

    def drain(self, timeout: float = 30.0) -> None:
        """Block until the loop has applied every queued reconcile (incl.
        deactivations) and finished its in-flight iteration. The restart
        plane's ordering barrier: after NodeHost.stop_cluster /
        crash_cluster drain, the freed lane is on the free list and a
        restart_cluster can reuse it immediately."""
        if self._stopped.is_set():
            return
        ev = threading.Event()
        self._reconq.append(("barrier", ev))
        self._ready.set()
        ev.wait(timeout)

    def stop(self, flush: bool = True) -> None:
        rep = self.profiler.report()
        if rep:
            _plog.infof("vector engine stage profile:\n%s", rep)
        self.watchdog.close()
        if not flush:
            self._discard_pending = True
        self._stopped.set()
        self._ready.set()
        self.task_ready.wake_all()
        self.snapshot_ready.wake_all()
        # the step thread must fully drain its in-flight iteration before
        # the caller closes the logdb under it; a short join here would let
        # a slow device step race the close (observed as "write to closed
        # file" + a C++ abort at interpreter teardown)
        for t in self._threads:
            t.join(timeout=30 if t.name == "vec-step" else 2)


class VectorEngineHandle:
    """Per-NodeHost facade over a (possibly shared) VectorEngine core.

    Lanes inside the core are keyed (host, cluster_id); the handle carries
    the host id so the Node/NodeHost side keeps addressing the engine by
    bare cluster_id. Attribute access falls through to the core, so the
    VectorNode status mirrors (_m_leader etc.) and the reconcile bridges
    work unchanged."""

    __slots__ = ("core", "host", "kcfg", "clock")

    def __init__(self, core: VectorEngine, host: int) -> None:
        self.core = core
        self.host = host
        self.kcfg = core.kcfg
        self.clock = core.clock

    def add_node(self, node) -> None:
        self.core.add_node(node, self.host)

    def remove_node(self, cluster_id: int) -> None:
        self.core.remove_node((self.host, cluster_id))

    def get_node(self, cluster_id: int):
        return self.core.get_node((self.host, cluster_id))

    def set_node_ready(self, cluster_id: int) -> None:
        self.core.set_node_ready((self.host, cluster_id))

    def set_task_ready(self, cluster_id: int) -> None:
        self.core.set_task_ready((self.host, cluster_id))

    def set_snapshot_ready(self, cluster_id: int) -> None:
        self.core.set_snapshot_ready((self.host, cluster_id))

    def global_tick(self) -> None:
        self.core.global_tick(self.host)

    def try_local_deliver(self, m: Message) -> bool:
        return self.core.try_local_deliver(m)

    def set_host_partitioned(self, partitioned: bool) -> None:
        self.core.set_host_partitioned(self.host, partitioned)

    def set_clock_suspect(self, hold_s: float) -> None:
        """Clock-anomaly report scoped to THIS host's lanes (a shared
        core serves several NodeHosts, each with its own tick worker)."""
        self.core.set_clock_suspect(self.host, hold_s)

    def lease_valid(self, cluster_id: int) -> bool:
        return self.core.lease_valid((self.host, cluster_id))

    def leader_snapshot(self) -> Dict[int, Tuple[int, int]]:
        """cluster_id -> (leader_node_id, term) for this host's lanes."""
        return {
            key[1]: v
            for key, v in self.core.leader_snapshot().items()
            if key[0] == self.host
        }

    def lane_stats(self) -> Dict[int, dict]:
        """cluster_id -> per-lane introspection for this host's lanes."""
        return {
            key[1]: v
            for key, v in self.core.lane_stats().items()
            if key[0] == self.host
        }

    def lane_counters(self) -> Dict[int, Dict[str, int]]:
        """cluster_id -> cumulative event counters for this host's
        lanes (see VectorEngine.lane_counters)."""
        return {
            key[1]: v
            for key, v in self.core.lane_counters().items()
            if key[0] == self.host
        }

    def hot_lane_stats(self, k: int) -> Tuple[Dict[int, dict], int]:
        """This host's k hottest lanes by commit gap + its total active
        lane count (see VectorEngine.hot_lane_stats). Host filtering
        happens BEFORE the cap so a co-hosted fleet's noisy neighbour
        can never crowd this host's lanes out of its own sample."""
        rows, total = self.core.hot_lane_stats(k, host=self.host)
        return {key[1]: v for key, v in rows.items()}, total

    def stop(self) -> None:
        self.core.release(self.host)

    def crash(self) -> None:
        """SIGKILL-equivalent detach (NodeHost.crash): a sole-tenant core
        discards its un-decoded in-flight step; a shared core keeps
        serving its surviving hosts (see VectorEngine.release)."""
        self.core.release(self.host, flush=False)

    def __getattr__(self, name):
        return getattr(self.core, name)


# process-global registry of shared cores (EngineConfig.share_scope)
_shared_mu = threading.Lock()
_shared_cores: Dict[str, VectorEngine] = {}


def get_vector_engine(logdb, nh_config: NodeHostConfig) -> VectorEngineHandle:
    """Engine factory for NodeHost: returns a handle on a fresh core, or on
    the process-shared core named by EngineConfig.share_scope (co-hosted
    replicas then advance in ONE kernel step and exchange messages without
    touching the transport)."""
    scope = getattr(nh_config.engine, "share_scope", None)
    if scope is None:
        core = VectorEngine(logdb, nh_config=nh_config)
        return VectorEngineHandle(core, core.attach_host())
    with _shared_mu:
        core = _shared_cores.get(scope)
        if core is None:
            core = _shared_cores[scope] = VectorEngine(
                logdb, nh_config=nh_config
            )
        else:
            want = nh_config.engine
            mismatches = [
                name
                for name, got, exp in (
                    # requested, not kcfg.groups: the sharded round-up
                    # pads the kernel shape, not the declared capacity
                    ("max_groups", core._groups_requested, want.max_groups),
                    ("max_peers", core.kcfg.peers, want.max_peers),
                    ("log_window", core.kcfg.log_window, want.log_window),
                    ("inbox_depth", core.kcfg.inbox_depth, want.inbox_depth),
                    (
                        "max_entries_per_msg",
                        core.kcfg.max_entries_per_msg,
                        getattr(want, "max_entries_per_msg", 8),
                    ),
                    (
                        "readindex_depth",
                        core.kcfg.readindex_depth,
                        want.readindex_depth,
                    ),
                    # the super-step length is compiled into the shared
                    # core's executable: every co-hosted host runs at
                    # the same K by construction
                    (
                        "steps_per_sync",
                        core._multi,
                        max(1, int(getattr(want, "steps_per_sync", 1) or 1)),
                    ),
                )
                if got != exp
            ]
            if mismatches:
                raise ValueError(
                    f"share_scope {scope!r}: engine shape mismatch on "
                    f"{mismatches} (every co-hosted NodeHost must declare "
                    f"the same EngineConfig shapes)"
                )
        host = core.attach_host()
    return VectorEngineHandle(core, host)


def _forget_shared_core_locked(core: VectorEngine) -> None:
    """Caller holds _shared_mu."""
    for k, v in list(_shared_cores.items()):
        if v is core:
            del _shared_cores[k]


__all__ = [
    "VectorEngine",
    "VectorEngineHandle",
    "VectorNode",
    "get_vector_engine",
]
