"""VectorEngine: the device-kernel-backed execution engine.

The scalar ExecEngine advances each group with a per-group Peer inside
worker threads (cf. reference execengine.go:474-560). This engine is the
TPU-first replacement: ALL groups hosted by a NodeHost live as lanes of one
(G, P) tensor state (ops/state.RaftTensors) and advance together in one
compiled kernel step (ops/kernel.step_batch). The host side of the engine

  1. packs per-group events (ticks, wire messages, proposals, reads,
     config changes, transfers) into the device Inbox,
  2. runs the jitted step,
  3. fans the StepOutput out with the reference's ordering invariants
     (cf. execengine.go:474-560): Replicate messages leave BEFORE the
     fsync; hard state + new entries are persisted in ONE batched
     save_raft_state call for every lane; responses (vote grants,
     ReplicateResp) leave only after persistence; committed entries are
     handed to the RSM task workers after persistence.

Payload bytes never touch the device: the kernel works on (index, term,
is_cc) metadata while the engine keeps an arena of Entry objects keyed by
(lane, real index). The kernel reports where each proposal/replicate landed
(StepOutput.prop_base / rep_base) so the host places payloads at the
device-assigned indexes without guessing.

Node identity on device is the peer slot (0..P-1). The canonical mapping is
rank-in-sorted-order of the member node ids, recomputed whenever membership
changes — a pure function of the (replicated) membership image, so every
replica derives the same mapping at the same applied index. The wire always
carries real node ids and real (un-rebased) indexes.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from ..config import Config, NodeHostConfig
from ..core.peer import PeerAddress, encode_config_change
from ..logger import get_logger
from ..ops.kernel import make_step_fn
from ..ops.state import (
    MSG,
    NEED_SNAPSHOT,
    ROLE,
    RSTATE,
    SEND_HEARTBEAT,
    SEND_REPLICATE,
    SEND_TIMEOUT_NOW,
    SEND_VOTE_REQ,
    Inbox,
    KernelConfig,
    RaftTensors,
    init_state,
    rebase,
)
from ..settings import soft
from ..types import (
    Entry,
    EntryType,
    Message,
    MessageType,
    ReadyToRead,
    Snapshot,
    State,
    SystemCtx,
    Update,
)
from .execengine import WorkReady
from .node import Node

_plog = get_logger("vectorengine")

MT = MessageType

# device index value guard: rebase once any lane's last index crosses this
_REBASE_THRESHOLD = 1 << 30

# ctx encoding: (origin_slot + 1) << 24 | (ctx.low & 0xFFFFFF); the origin
# slot rides inside the 31-bit device hint so a leader can route confirmed
# forwarded reads back to the requesting replica (the reference keeps the
# requester in the message envelope instead, raft.go:1871-1898)
_CTX_LOW_MASK = 0xFFFFFF


def _enc_ctx(origin_slot: int, low: int) -> int:
    return ((origin_slot + 1) << 24) | (low & _CTX_LOW_MASK)


def _ctx_origin(enc: int) -> int:
    return (enc >> 24) - 1


class VectorNode(Node):
    """A Node whose protocol core is a lane of the shared device state.

    The public request surface (propose/read/config-change/snapshot/
    transfer), the RSM manager, the snapshotter drivers and the pending
    notification machinery are all inherited; only the protocol stepping is
    different — there is no Peer, the VectorEngine advances every lane in
    one kernel call.
    """

    def _launch_core(self, cfg, log_reader, peer_addresses, initial, new_node, rng):
        self._vec_initial = initial
        self._vec_new_node = new_node
        self._vec_addresses = list(peer_addresses)
        self._status_mu = threading.Lock()
        self._vstatus = {
            "leader_id": 0,
            "term": 0,
            "state": ROLE.FOLLOWER,
            "commit": 0,
        }
        return None  # no scalar Peer

    # ------------------------------------------------------------ status
    def get_leader_id(self) -> int:
        with self._status_mu:
            return self._vstatus["leader_id"]

    def local_status(self):
        with self._status_mu:
            st = dict(self._vstatus)
        st.update(
            cluster_id=self.cluster_id,
            node_id=self._node_id,
            applied=self.sm.last_applied_index(),
        )
        return st

    def _set_status(self, leader_id: int, term: int, role: int, commit: int) -> None:
        with self._status_mu:
            prev = self._vstatus["leader_id"], self._vstatus["term"]
            self._vstatus.update(
                leader_id=leader_id, term=term, state=role, commit=commit
            )
        if prev != (leader_id, term) and self.events is not None:
            self.events.leader_updated(
                self.cluster_id, self._node_id, leader_id, term
            )

    # ------------------------------------------------- INodeProxy overrides
    def apply_config_change(self, cc) -> None:
        """A config change committed and passed the membership legality
        checks: reconcile the device lane (slot remap) on the engine loop."""
        self.engine.membership_changed(self)

    def config_change_processed(self, key: int, accepted: bool) -> None:
        self.pending_config_change.apply(key, rejected=not accepted)
        # the device's single-pending-config-change latch opens once the
        # change is applied or rejected (cf. raft.go:1242-1295; the scalar
        # core clears it through apply_config_change/reject_config_change)
        self.engine.cc_processed(self)

    # --------------------------------------------------- snapshot overrides
    def _recover_initial_snapshot_locked(self) -> None:
        from ..rsm import Task

        t = Task(
            cluster_id=self.cluster_id,
            node_id=self._node_id,
            snapshot_available=True,
        )
        self.sm.recover_from_snapshot(t)

    def _do_recover_snapshot(self, task) -> None:
        """InstallSnapshot arrived and the SM recovered from it on a
        snapshot worker; reconcile the device lane and ack the leader
        (cf. node.go:950-965 + raft.go handleInstallSnapshotMessage)."""
        idx = self.sm.recover_from_snapshot(task)
        if idx > 0:
            ss = self.snapshotter.get_most_recent_snapshot()
            if ss is not None and not ss.is_empty():
                with self._mu:
                    self.log_reader.apply_snapshot(ss)
                self.engine.snapshot_restored(self, ss)
                return
        self.engine.recover_done(self)


class _Lane:
    """Per-group host bookkeeping owned by the engine loop thread."""

    __slots__ = (
        "g",
        "node",
        "cfg",
        "base",
        "slots",
        "rev",
        "arena",
        "staged_props",
        "staged_reads",
        "staged_ccs",
        "msg_backlog",
        "pack_info",
        "ri_pending",
        "recovering",
        "catchup",
        "leader_slot",
        "term",
        "role",
        "committed",
        "last_index",
        "first_index",
        "applied_since_snapshot",
        "snapshot_pending",
        "active",
        "cc_inflight",
    )

    def __init__(self, g: int, node: VectorNode) -> None:
        self.g = g
        self.node = node
        self.cfg: Config = node.config
        self.base = 0  # real index = device index + base
        self.slots: Dict[int, int] = {}  # node_id -> slot
        self.rev: Dict[int, int] = {}  # slot -> node_id
        self.arena: Dict[int, Entry] = {}  # real index -> Entry
        self.staged_props: deque = deque()  # (Entry, is_local)
        self.staged_reads: deque = deque()  # RequestState
        self.staged_ccs: deque = deque()  # (Entry, key)
        self.msg_backlog: deque = deque()  # wire Messages awaiting a slot
        self.pack_info: Dict[int, tuple] = {}
        self.ri_pending: Dict[int, SystemCtx] = {}  # enc -> real ctx
        self.recovering = False
        self.catchup: Dict[int, Tuple[int, int]] = {}  # slot -> (next, goal)
        self.leader_slot = -1
        self.term = 0
        self.role = ROLE.FOLLOWER
        self.committed = 0
        self.last_index = 0
        self.first_index = 1
        self.applied_since_snapshot = 0
        self.snapshot_pending = False
        self.active = False
        self.cc_inflight = False

    # ------------------------------------------------------- slot mapping
    def set_slots(self, member_ids) -> Dict[int, int]:
        """Canonical mapping: rank in sorted member-id order. Returns the
        old->new slot permutation for device remap."""
        new = {nid: i for i, nid in enumerate(sorted(member_ids))}
        perm = {}
        for nid, old_slot in self.slots.items():
            if nid in new:
                perm[old_slot] = new[nid]
        self.slots = new
        self.rev = {s: nid for nid, s in new.items()}
        return perm

    def slot_of(self, node_id: int, provisional: bool = False) -> int:
        s = self.slots.get(node_id)
        if s is not None:
            return s
        if not provisional:
            return -1
        # a sender we have not learned through membership yet (join path):
        # park it on a free slot; the canonical remap fixes it at apply time
        P = self.node.engine.kcfg.peers
        used = set(self.slots.values())
        for s in range(P):
            if s not in used:
                self.slots[node_id] = s
                self.rev[s] = node_id
                return s
        return -1

    def self_slot(self) -> int:
        return self.slots.get(self.node.node_id(), -1)


class VectorEngine:
    """Engine-compatible facade (add/remove/set_*_ready/stop) around the
    single-stepper loop that advances all lanes per kernel call."""

    def __init__(
        self,
        logdb,
        nh_config: Optional[NodeHostConfig] = None,
        num_task_workers: Optional[int] = None,
        num_snapshot_workers: int = 2,
    ) -> None:
        self._logdb = logdb
        ecfg = nh_config.engine if nh_config is not None else None
        self.kcfg = KernelConfig(
            groups=ecfg.max_groups if ecfg else 64,
            peers=ecfg.max_peers if ecfg else 8,
            log_window=ecfg.log_window if ecfg else 128,
            inbox_depth=ecfg.inbox_depth if ecfg else 8,
            max_entries_per_msg=8,
            readindex_depth=ecfg.readindex_depth if ecfg else 4,
        )
        self._step_fn = make_step_fn(self.kcfg, donate=True)
        self._state: RaftTensors = init_state(self.kcfg)
        self._lanes: Dict[int, _Lane] = {}  # cluster_id -> lane
        self._free = list(range(self.kcfg.groups - 1, -1, -1))
        self._lanes_mu = threading.RLock()
        self._reconq: deque = deque()  # host->device ops, loop-applied
        self._stopped = threading.Event()
        self._ready = threading.Event()
        # numpy staging buffers for the inbox (reused across steps)
        G, K, E = self.kcfg.groups, self.kcfg.inbox_depth, 8
        self._buf = {
            "mtype": np.full((G, K), MSG.NONE, np.int32),
            "from_slot": np.zeros((G, K), np.int32),
            "term": np.zeros((G, K), np.int32),
            "log_index": np.zeros((G, K), np.int32),
            "log_term": np.zeros((G, K), np.int32),
            "commit": np.zeros((G, K), np.int32),
            "reject": np.zeros((G, K), bool),
            "hint": np.zeros((G, K), np.int32),
            "n_entries": np.zeros((G, K), np.int32),
            "entry_terms": np.zeros((G, K, E), np.int32),
            "entry_cc": np.zeros((G, K, E), bool),
        }
        self._ticks = np.zeros((G,), np.int32)
        # worker pools for apply + snapshot work (same split as ExecEngine)
        self._n_task = num_task_workers or min(
            soft.step_engine_task_worker_count, 4
        )
        self._n_snap = num_snapshot_workers
        self.task_ready = WorkReady(self._n_task)
        self.snapshot_ready = WorkReady(self._n_snap)
        self._threads: List[threading.Thread] = []
        t = threading.Thread(target=self._loop, name="vec-step", daemon=True)
        t.start()
        self._threads.append(t)
        for i in range(self._n_task):
            t = threading.Thread(
                target=self._task_worker_main, args=(i,), name=f"vtask-{i}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        for i in range(self._n_snap):
            t = threading.Thread(
                target=self._snapshot_worker_main, args=(i,), name=f"vsnap-{i}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)

    # --------------------------------------------------------- registration
    def add_node(self, node: VectorNode) -> None:
        with self._lanes_mu:
            if not self._free:
                raise RuntimeError(
                    f"vector engine lane capacity ({self.kcfg.groups}) exhausted"
                )
            g = self._free.pop()
            lane = _Lane(g, node)
            self._lanes[node.cluster_id] = lane
        self._reconq.append(("activate", lane))
        self.set_node_ready(node.cluster_id)

    def remove_node(self, cluster_id: int) -> None:
        with self._lanes_mu:
            lane = self._lanes.pop(cluster_id, None)
        if lane is not None:
            self._reconq.append(("deactivate", lane))
            self._ready.set()

    def get_node(self, cluster_id: int):
        with self._lanes_mu:
            lane = self._lanes.get(cluster_id)
        return lane.node if lane is not None else None

    # -------------------------------------------------------------- wakeups
    def set_node_ready(self, cluster_id: int) -> None:
        self._ready.set()

    def set_task_ready(self, cluster_id: int) -> None:
        self.task_ready.notify(cluster_id)

    def set_snapshot_ready(self, cluster_id: int) -> None:
        self.snapshot_ready.notify(cluster_id)

    # ------------------------------------------------- host->device bridges
    def membership_changed(self, node: VectorNode) -> None:
        """Called on a task worker when a config change applies; the loop
        recomputes the canonical slot mapping from the SM membership."""
        self._reconq.append(("membership", node))
        self._ready.set()

    def snapshot_restored(self, node: VectorNode, ss: Snapshot) -> None:
        self._reconq.append(("restore", node, ss))
        self._ready.set()

    def cc_processed(self, node: VectorNode) -> None:
        self._reconq.append(("cc_done", node))
        self._ready.set()

    def recover_done(self, node: VectorNode) -> None:
        self._reconq.append(("recover_done", node))
        self._ready.set()

    # ---------------------------------------------------------------- loop
    def _loop(self) -> None:
        period = 0.002
        while not self._stopped.is_set():
            self._ready.wait(period)
            self._ready.clear()
            if self._stopped.is_set():
                return
            try:
                self._run_once()
            except Exception:
                import traceback

                traceback.print_exc()

    def _run_once(self) -> None:
        self._apply_reconciles()
        with self._lanes_mu:
            lanes = [ln for ln in self._lanes.values() if ln.active]
        if not lanes:
            return
        had_work = self._pack(lanes)
        if not had_work:
            return
        inbox = Inbox(
            mtype=jnp.asarray(self._buf["mtype"]),
            from_slot=jnp.asarray(self._buf["from_slot"]),
            term=jnp.asarray(self._buf["term"]),
            log_index=jnp.asarray(self._buf["log_index"]),
            log_term=jnp.asarray(self._buf["log_term"]),
            commit=jnp.asarray(self._buf["commit"]),
            reject=jnp.asarray(self._buf["reject"]),
            hint=jnp.asarray(self._buf["hint"]),
            n_entries=jnp.asarray(self._buf["n_entries"]),
            entry_terms=jnp.asarray(self._buf["entry_terms"]),
            entry_cc=jnp.asarray(self._buf["entry_cc"]),
        )
        ticks = jnp.asarray(self._ticks)
        self._state, out = self._step_fn(self._state, inbox, ticks)
        self._decode(lanes, out)

    # ---------------------------------------------------------------- pack
    def _pack(self, lanes: List[_Lane]) -> bool:
        K = self.kcfg.inbox_depth
        E = self.kcfg.max_entries_per_msg
        buf = self._buf
        buf["mtype"].fill(MSG.NONE)
        buf["n_entries"].fill(0)
        buf["entry_cc"].fill(False)
        self._ticks.fill(0)
        had = False
        for lane in lanes:
            node = lane.node
            g = lane.g
            lane.pack_info = {}
            msgs, ticks = node.mq.get()
            if ticks:
                capped = min(ticks, lane.cfg.election_rtt)
                self._ticks[g] = capped
                for _ in range(ticks):
                    node.clock.increase_tick()
                    node.pending_proposals.gc()
                    node.pending_read_indexes.gc()
                    node.pending_config_change.gc()
                    node.pending_snapshot.gc()
                had = True
            lane.msg_backlog.extend(msgs)
            if lane.recovering:
                # an InstallSnapshot recover is in flight: hold everything
                # until the device lane is reconciled (cf. node.go:1199)
                continue
            # drain API queues into the staging deques
            for e in node.incoming_proposals.get():
                lane.staged_props.append((e, True))
            for rs in node.incoming_reads.get():
                lane.staged_reads.append(rs)
            with node._mu:
                ccs, node._cc_queue = node._cc_queue, []
            for cc, key in ccs:
                ce = Entry(
                    type=EntryType.CONFIG_CHANGE,
                    cmd=encode_config_change(cc),
                    key=key,
                )
                lane.staged_ccs.append((ce, key))
            k = 0
            # 1. wire/protocol messages first
            while lane.msg_backlog and k < K:
                m = lane.msg_backlog.popleft()
                k_used = self._pack_wire(lane, m, k)
                if k_used:
                    had = True
                    k += 1
            is_leader = lane.role == ROLE.LEADER
            leader_nid = lane.rev.get(lane.leader_slot)
            # 2. one config change per step (lone message; host invariant)
            if k < K and lane.staged_ccs and not lane.cc_inflight:
                if is_leader:
                    ce, key = lane.staged_ccs.popleft()
                    self._pack_row(
                        g, k, MSG.PROPOSE, from_slot=lane.self_slot(),
                        n_entries=1,
                    )
                    buf["entry_cc"][g, k, 0] = True
                    lane.pack_info[k] = ("cc", ce, key)
                    lane.cc_inflight = True
                    had = True
                    k += 1
                elif leader_nid is not None and leader_nid != node.node_id():
                    while lane.staged_ccs:
                        ce, key = lane.staged_ccs.popleft()
                        node._send_message(
                            Message(
                                type=MT.PROPOSE,
                                cluster_id=node.cluster_id,
                                to=leader_nid,
                                from_=node.node_id(),
                                entries=[ce],
                            )
                        )
            # 3. proposals
            if lane.staged_props:
                if is_leader:
                    while lane.staged_props and k < K:
                        ents = []
                        while lane.staged_props and len(ents) < E:
                            ents.append(lane.staged_props.popleft()[0])
                        self._pack_row(
                            g, k, MSG.PROPOSE, from_slot=lane.self_slot(),
                            n_entries=len(ents),
                        )
                        lane.pack_info[k] = ("prop", ents)
                        had = True
                        k += 1
                elif leader_nid is not None and leader_nid != node.node_id():
                    ents = [e for e, _ in lane.staged_props]
                    lane.staged_props.clear()
                    for i in range(0, len(ents), 64):
                        node._send_message(
                            Message(
                                type=MT.PROPOSE,
                                cluster_id=node.cluster_id,
                                to=leader_nid,
                                from_=node.node_id(),
                                entries=ents[i : i + 64],
                            )
                        )
            # 4. reads
            if lane.staged_reads:
                if is_leader and lane.self_slot() >= 0:
                    if k < K:
                        states = list(lane.staged_reads)
                        lane.staged_reads.clear()
                        ctx = node.pending_read_indexes.next_ctx()
                        if node.pending_read_indexes.bind_queued_states(
                            states, ctx
                        ):
                            enc = _enc_ctx(lane.self_slot(), ctx.low)
                            lane.ri_pending[enc] = ctx
                            self._pack_row(
                                g, k, MSG.READ_INDEX,
                                from_slot=lane.self_slot(), hint=enc,
                            )
                            had = True
                            k += 1
                elif leader_nid is not None and leader_nid != node.node_id():
                    states = list(lane.staged_reads)
                    lane.staged_reads.clear()
                    ctx = node.pending_read_indexes.next_ctx()
                    if node.pending_read_indexes.bind_queued_states(states, ctx):
                        enc = _enc_ctx(lane.self_slot(), ctx.low)
                        lane.ri_pending[enc] = ctx
                        node._send_message(
                            Message(
                                type=MT.READ_INDEX,
                                cluster_id=node.cluster_id,
                                to=leader_nid,
                                from_=node.node_id(),
                                hint=enc,
                            )
                        )
            # 5. leadership transfer
            target = node.pending_leader_transfer.get()
            if target is not None and k < K:
                tslot = lane.slots.get(target, -1)
                if tslot >= 0:
                    self._pack_row(
                        g, k, MSG.LEADER_TRANSFER,
                        from_slot=lane.self_slot(), hint=tslot + 1,
                    )
                    had = True
                    k += 1
            if lane.catchup:
                had = True
        return had

    def _pack_row(
        self, g: int, k: int, mtype: int, from_slot: int = 0, term: int = 0,
        log_index: int = 0, log_term: int = 0, commit: int = 0,
        reject: bool = False, hint: int = 0, n_entries: int = 0,
    ) -> None:
        buf = self._buf
        buf["mtype"][g, k] = mtype
        buf["from_slot"][g, k] = max(from_slot, 0)
        buf["term"][g, k] = term
        buf["log_index"][g, k] = log_index
        buf["log_term"][g, k] = log_term
        buf["commit"][g, k] = commit
        buf["reject"][g, k] = reject
        buf["hint"][g, k] = hint
        buf["n_entries"][g, k] = n_entries

    def _pack_wire(self, lane: _Lane, m: Message, k: int) -> bool:
        """Convert one wire message into an inbox row. Returns False when
        the message was consumed host-side (snapshot, propose staging)."""
        g = lane.g
        t = m.type
        if t == MT.INSTALL_SNAPSHOT:
            self._handle_install_snapshot(lane, m)
            return False
        if t == MT.PROPOSE:
            for e in m.entries:
                if e.type == EntryType.CONFIG_CHANGE:
                    lane.staged_ccs.append((e, e.key))
                else:
                    lane.staged_props.append((e, False))
            return False
        if t == MT.QUIESCE:
            return False
        from_slot = lane.slot_of(m.from_, provisional=t == MT.REPLICATE or t == MT.HEARTBEAT or t == MT.REQUEST_VOTE or t == MT.TIMEOUT_NOW or t == MT.READ_INDEX_RESP)
        if from_slot < 0 and m.from_ != 0:
            return False  # unknown sender and no room to learn it
        b = lane.base
        if t == MT.REPLICATE:
            n = len(m.entries)
            E = self.kcfg.max_entries_per_msg
            if n > E:
                # split: re-queue the tail as a chained Replicate
                head, tail = m.entries[:E], m.entries[E:]
                rest = Message(
                    type=MT.REPLICATE, cluster_id=m.cluster_id, to=m.to,
                    from_=m.from_, term=m.term, commit=m.commit,
                    log_index=head[-1].index, log_term=head[-1].term,
                    entries=tail,
                )
                lane.msg_backlog.appendleft(rest)
                m.entries = head
                n = E
            self._pack_row(
                g, k, MSG.REPLICATE, from_slot=from_slot, term=m.term,
                log_index=m.log_index - b, log_term=m.log_term,
                commit=max(m.commit - b, 0), n_entries=n,
            )
            for i, e in enumerate(m.entries):
                self._buf["entry_terms"][g, k, i] = e.term
                self._buf["entry_cc"][g, k, i] = e.is_config_change()
            lane.pack_info[k] = ("rep", list(m.entries))
            return True
        if t == MT.HEARTBEAT:
            self._pack_row(
                g, k, MSG.HEARTBEAT, from_slot=from_slot, term=m.term,
                commit=max(m.commit - b, 0), hint=m.hint,
            )
            return True
        if t == MT.REQUEST_VOTE:
            self._pack_row(
                g, k, MSG.REQUEST_VOTE, from_slot=from_slot, term=m.term,
                log_index=m.log_index - b, log_term=m.log_term, hint=m.hint,
            )
            return True
        if t == MT.REQUEST_VOTE_RESP:
            self._pack_row(
                g, k, MSG.REQUEST_VOTE_RESP, from_slot=from_slot, term=m.term,
                reject=m.reject,
            )
            return True
        if t == MT.REPLICATE_RESP:
            self._pack_row(
                g, k, MSG.REPLICATE_RESP, from_slot=from_slot, term=m.term,
                log_index=m.log_index - b, reject=m.reject,
                hint=max(m.hint - b, 0),
            )
            return True
        if t == MT.HEARTBEAT_RESP:
            self._pack_row(
                g, k, MSG.HEARTBEAT_RESP, from_slot=from_slot, term=m.term,
                hint=m.hint,
            )
            return True
        if t == MT.READ_INDEX:
            self._pack_row(
                g, k, MSG.READ_INDEX, from_slot=from_slot, term=m.term,
                hint=m.hint,
            )
            return True
        if t == MT.READ_INDEX_RESP:
            self._pack_row(
                g, k, MSG.READ_INDEX_RESP, from_slot=from_slot, term=m.term,
                log_index=m.log_index - b, hint=m.hint,
            )
            return True
        if t == MT.TIMEOUT_NOW:
            self._pack_row(
                g, k, MSG.TIMEOUT_NOW, from_slot=from_slot, term=m.term
            )
            return True
        if t == MT.UNREACHABLE:
            self._pack_row(g, k, MSG.UNREACHABLE, from_slot=from_slot)
            return True
        if t == MT.SNAPSHOT_STATUS:
            self._pack_row(
                g, k, MSG.SNAPSHOT_STATUS, from_slot=from_slot, reject=m.reject
            )
            return True
        if t == MT.NOOP:
            self._pack_row(g, k, MSG.NOOP, from_slot=from_slot, term=m.term)
            return True
        return False

    def _handle_install_snapshot(self, lane: _Lane, m: Message) -> None:
        ss = m.snapshot
        if ss is None or ss.is_empty():
            return
        if ss.index <= lane.node.sm.last_applied_index():
            return  # stale snapshot
        lane.recovering = True
        # persist the snapshot record before recovery (restart safety)
        self._logdb.save_raft_state(
            [
                Update(
                    cluster_id=lane.node.cluster_id,
                    node_id=lane.node.node_id(),
                    snapshot=ss,
                )
            ]
        )
        lane.node._push_install_snapshot(ss)

    # --------------------------------------------------------------- decode
    def _decode(self, lanes: List[_Lane], out) -> None:
        o = {k: np.asarray(v) for k, v in out._asdict().items()}
        E = self.kcfg.max_entries_per_msg
        K = self.kcfg.inbox_depth
        updates: List[Update] = []
        lane_saves: List[Tuple[_Lane, List[Entry], State]] = []
        # ---- phase 0: place payloads at device-assigned indexes ----------
        for lane in lanes:
            g = lane.g
            b = lane.base
            node = lane.node
            for k, info in lane.pack_info.items():
                kind = info[0]
                if kind == "prop":
                    ents = info[1]
                    base = int(o["prop_base"][g, k])
                    if base > 0:
                        term = int(o["resp_term"][g, k])
                        for i, e in enumerate(ents):
                            e.index = b + base + i
                            e.term = term
                            lane.arena[e.index] = e
                    else:
                        for e in ents:
                            node.pending_proposals.dropped(e.key)
                elif kind == "cc":
                    ce, key = info[1], info[2]
                    base = int(o["prop_base"][g, k])
                    stripped = bool(o["dropped_cc"][g])
                    if base > 0 and not stripped:
                        ce.index = b + base
                        ce.term = int(o["resp_term"][g, k])
                        lane.arena[ce.index] = ce
                    else:
                        if base > 0:
                            # the kernel appended the entry with its cc bit
                            # stripped (single-pending invariant): it lives
                            # on as an empty noop entry (raft.go:1587-1606)
                            lane.arena[b + base] = Entry(
                                type=EntryType.APPLICATION,
                                index=b + base,
                                term=int(o["resp_term"][g, k]),
                            )
                        lane.cc_inflight = False
                        node.pending_config_change.apply(key, rejected=True)
                elif kind == "rep":
                    base = int(o["rep_base"][g, k])
                    if base > 0:
                        for e in info[1]:
                            lane.arena[e.index] = e
            noop_at = int(o["noop_appended"][g])
            if noop_at > 0:
                lane.arena[b + noop_at] = Entry(
                    type=EntryType.APPLICATION,
                    term=int(o["noop_term"][g]),
                    index=b + noop_at,
                )
            # mirrors
            lane.leader_slot = int(o["leader"][g]) - 1
            lane.term = int(o["term"][g])
            lane.role = int(o["role"][g])
            lane.committed = b + int(o["commit_index"][g])
            lane.last_index = b + int(o["last_index"][g])
            leader_nid = lane.rev.get(lane.leader_slot, 0)
            node._set_status(leader_nid, lane.term, lane.role, lane.committed)
        # ---- phase 1: Replicate messages leave BEFORE the fsync ----------
        send_flags = o["send_flags"]
        rep_gs, rep_ps = np.nonzero(send_flags & SEND_REPLICATE)
        by_g = {lane.g: lane for lane in lanes}
        for g, p in zip(rep_gs.tolist(), rep_ps.tolist()):
            lane = by_g.get(g)
            if lane is None:
                continue
            to_nid = lane.rev.get(p)
            if to_nid is None:
                continue
            b = lane.base
            prev = int(o["send_prev_index"][g, p])
            n = int(o["send_n_entries"][g, p])
            try:
                ents = [lane.arena[b + prev + 1 + i] for i in range(n)]
            except KeyError:
                _plog.errorf(
                    "%s missing arena entries for replicate [%d..%d]",
                    lane.node.describe(), b + prev + 1, b + prev + n,
                )
                continue
            lane.node._send_message(
                Message(
                    type=MT.REPLICATE,
                    cluster_id=lane.node.cluster_id,
                    to=to_nid,
                    from_=lane.node.node_id(),
                    term=int(o["term"][g]),
                    log_index=b + prev,
                    log_term=int(o["send_prev_term"][g, p]),
                    commit=b + int(o["send_commit"][g, p]),
                    entries=ents,
                )
            )
        # ---- phase 2: one batched fsynced write for every lane -----------
        for lane in lanes:
            g = lane.g
            b = lane.base
            sf, st_ = int(o["save_from"][g]), int(o["save_to"][g])
            ents: List[Entry] = []
            if sf > 0:
                for idx in range(b + sf, b + st_ + 1):
                    e = lane.arena.get(idx)
                    if e is None:
                        _plog.errorf(
                            "%s missing arena entry %d for save",
                            lane.node.describe(), idx,
                        )
                        continue
                    ents.append(e)
            vote_slot = int(o["vote"][g])
            state = State(
                term=int(o["term"][g]),
                vote=lane.rev.get(vote_slot - 1, 0) if vote_slot > 0 else 0,
                commit=b + int(o["commit_index"][g]),
            )
            if ents or bool(o["hard_changed"][g]):
                updates.append(
                    Update(
                        cluster_id=lane.node.cluster_id,
                        node_id=lane.node.node_id(),
                        state=state,
                        entries_to_save=ents,
                    )
                )
                lane_saves.append((lane, ents, state))
        if updates:
            self._logdb.save_raft_state(updates)
        for lane, ents, state in lane_saves:
            if ents:
                lane.node.log_reader.append(ents)
            lane.node.log_reader.set_state(state)
        # ---- phase 3: post-fsync sends (votes, responses, heartbeats) ----
        for flag, mk in (
            (SEND_VOTE_REQ, self._mk_vote),
            (SEND_HEARTBEAT, self._mk_heartbeat),
            (SEND_TIMEOUT_NOW, self._mk_timeout_now),
        ):
            gs, ps = np.nonzero(send_flags & flag)
            for g, p in zip(gs.tolist(), ps.tolist()):
                lane = by_g.get(g)
                if lane is None:
                    continue
                to_nid = lane.rev.get(p)
                if to_nid is None:
                    continue
                lane.node._send_message(mk(lane, o, g, p, to_nid))
        resp_gs, resp_ks = np.nonzero(o["resp_type"] != MSG.NONE)
        for g, k in zip(resp_gs.tolist(), resp_ks.tolist()):
            lane = by_g.get(g)
            if lane is None:
                continue
            self._send_resp(lane, o, g, k)
        # snapshot path for peers that fell behind the device window
        snap_gs, snap_ps = np.nonzero(send_flags & NEED_SNAPSHOT)
        for g, p in zip(snap_gs.tolist(), snap_ps.tolist()):
            lane = by_g.get(g)
            if lane is not None:
                self._start_catchup(lane, p, o)
        # ---- phase 4: hand committed entries to the RSM ------------------
        for lane in lanes:
            g = lane.g
            b = lane.base
            af, at = int(o["apply_from"][g]), int(o["apply_to"][g])
            if af <= 0:
                continue
            ents = []
            missing = False
            for idx in range(b + af, b + at + 1):
                e = lane.arena.get(idx)
                if e is None:
                    _plog.errorf(
                        "%s missing arena entry %d for apply",
                        lane.node.describe(), idx,
                    )
                    missing = True
                    break
                ents.append(e)
            if missing or not ents:
                continue
            from ..rsm import Task

            lane.node.sm.task_queue.add(
                Task(
                    cluster_id=lane.node.cluster_id,
                    node_id=lane.node.node_id(),
                    entries=ents,
                )
            )
            lane.applied_since_snapshot += len(ents)
            if any(e.type == EntryType.CONFIG_CHANGE for e in ents):
                lane.cc_inflight = False
            self.set_task_ready(lane.node.cluster_id)
        # ---- phase 5: confirmed reads ------------------------------------
        for lane in lanes:
            g = lane.g
            n = int(o["ready_count"][g])
            if n == 0:
                continue
            node = lane.node
            for i in range(n):
                enc = int(o["ready_ctx"][g, i])
                idx = lane.base + int(o["ready_index"][g, i])
                origin = _ctx_origin(enc)
                if origin == lane.self_slot():
                    ctx = lane.ri_pending.pop(enc, None)
                    if ctx is not None:
                        node.pending_read_indexes.add_ready_to_read(
                            [ReadyToRead(index=idx, system_ctx=ctx)]
                        )
                else:
                    to_nid = lane.rev.get(origin)
                    if to_nid is not None:
                        node._send_message(
                            Message(
                                type=MT.READ_INDEX_RESP,
                                cluster_id=node.cluster_id,
                                to=to_nid,
                                from_=node.node_id(),
                                term=lane.term,
                                log_index=idx,
                                hint=enc,
                            )
                        )
            node.pending_read_indexes.applied(node.sm.last_applied_index())
        # ---- phase 6: maintenance ----------------------------------------
        self._maintain(lanes, o)

    def _mk_vote(self, lane, o, g, p, to_nid) -> Message:
        return Message(
            type=MT.REQUEST_VOTE,
            cluster_id=lane.node.cluster_id,
            to=to_nid,
            from_=lane.node.node_id(),
            term=int(o["term"][g]),
            log_index=lane.base + int(o["vote_last_index"][g]),
            log_term=int(o["vote_last_term"][g]),
            hint=int(o["send_hint"][g, p]),
        )

    def _mk_heartbeat(self, lane, o, g, p, to_nid) -> Message:
        return Message(
            type=MT.HEARTBEAT,
            cluster_id=lane.node.cluster_id,
            to=to_nid,
            from_=lane.node.node_id(),
            term=int(o["term"][g]),
            commit=lane.base + int(o["send_hb_commit"][g, p]),
            hint=int(o["send_hint"][g, p]),
        )

    def _mk_timeout_now(self, lane, o, g, p, to_nid) -> Message:
        return Message(
            type=MT.TIMEOUT_NOW,
            cluster_id=lane.node.cluster_id,
            to=to_nid,
            from_=lane.node.node_id(),
            term=int(o["term"][g]),
        )

    def _send_resp(self, lane: _Lane, o, g: int, k: int) -> None:
        t = int(o["resp_type"][g, k])
        to_slot = int(o["resp_to"][g, k])
        to_nid = lane.rev.get(to_slot)
        if to_nid is None:
            return
        if to_nid == lane.node.node_id():
            return  # self-addressed (e.g. local election artifacts)
        b = lane.base
        wire = {
            MSG.REPLICATE_RESP: MT.REPLICATE_RESP,
            MSG.REQUEST_VOTE_RESP: MT.REQUEST_VOTE_RESP,
            MSG.HEARTBEAT_RESP: MT.HEARTBEAT_RESP,
            MSG.NOOP: MT.NOOP,
        }.get(t)
        if wire is None:
            return
        log_index = int(o["resp_log_index"][g, k])
        hint = int(o["resp_hint"][g, k])
        if wire == MT.REPLICATE_RESP:
            log_index += b
            hint += b
        lane.node._send_message(
            Message(
                type=wire,
                cluster_id=lane.node.cluster_id,
                to=to_nid,
                from_=lane.node.node_id(),
                term=int(o["resp_term"][g, k]),
                log_index=log_index,
                reject=bool(o["resp_reject"][g, k]),
                hint=hint,
                hint_high=int(o["resp_hint2"][g, k]),
            )
        )

    # ------------------------------------------------------ catchup path
    def _start_catchup(self, lane: _Lane, p: int, o) -> None:
        """A peer's next index fell behind the device window. If the host
        log still has the entries, replicate them host-side (the device has
        parked the peer in SNAPSHOT state; ReplicateResps move match and the
        kernel un-parks it once caught). Otherwise stream a real snapshot
        (cf. raft.go:774-785)."""
        if p in lane.catchup:
            return
        g = lane.g
        goal = lane.base + int(o["last_index"][g])
        match = lane.base + int(o["match"][g, p])
        start = match + 1
        first, last = lane.node.log_reader.get_range()
        if start >= first and start <= last + 1:
            # [next_to_send, goal, match_at_last_progress, stall_rounds]
            lane.catchup[p] = [start, goal, match, 0]
        else:
            # the follower needs entries the host log no longer has
            # (compacted behind a snapshot): only a snapshot can help
            self._send_snapshot(lane, p)

    def _send_snapshot(self, lane: _Lane, p: int) -> None:
        to_nid = lane.rev.get(p)
        if to_nid is None:
            return
        ss = lane.node.snapshotter.get_most_recent_snapshot()
        if ss is None or ss.is_empty():
            ss = lane.node.log_reader.snapshot()
        if ss is None or ss.is_empty():
            _plog.warningf(
                "%s peer %d needs a snapshot but none exists",
                lane.node.describe(), to_nid,
            )
            return
        lane.node._send_message(
            Message(
                type=MT.INSTALL_SNAPSHOT,
                cluster_id=lane.node.cluster_id,
                to=to_nid,
                from_=lane.node.node_id(),
                term=lane.term,
                snapshot=ss,
            )
        )

    def _run_catchups(self, lane: _Lane, o) -> None:
        if not lane.catchup:
            return
        g = lane.g
        done = []
        for p, cu in lane.catchup.items():
            nxt, goal, last_match, stall = cu
            match = lane.base + int(o["match"][g, p])
            if match >= goal or lane.role != ROLE.LEADER:
                done.append(p)
                continue
            if match > last_match:
                cu[2], cu[3] = match, 0
            else:
                cu[3] = stall + 1
                if cu[3] > 500:
                    # the follower stopped acking (divergence, loss): give
                    # up on log replay and ship a snapshot instead
                    done.append(p)
                    self._send_snapshot(lane, p)
                    continue
            if match + 1 > nxt:
                nxt = match + 1
            first, last = lane.node.log_reader.get_range()
            if nxt < first:
                done.append(p)
                self._send_snapshot(lane, p)
                continue
            if nxt > last:
                continue  # wait for the follower to ack what's in flight
            hi = min(nxt + self.kcfg.max_entries_per_msg - 1, last, goal)
            try:
                ents = lane.node.log_reader.entries(nxt, hi + 1, 1 << 20)
                prev = nxt - 1
                prev_term = (
                    lane.node.log_reader.term(prev) if prev > 0 else 0
                )
            except Exception:
                done.append(p)
                self._send_snapshot(lane, p)
                continue
            if not ents:
                done.append(p)
                continue
            to_nid = lane.rev.get(p)
            if to_nid is None:
                done.append(p)
                continue
            lane.node._send_message(
                Message(
                    type=MT.REPLICATE,
                    cluster_id=lane.node.cluster_id,
                    to=to_nid,
                    from_=lane.node.node_id(),
                    term=lane.term,
                    log_index=prev,
                    log_term=prev_term,
                    commit=min(lane.committed, ents[-1].index),
                    entries=ents,
                )
            )
            cu[0] = ents[-1].index + 1
        for p in done:
            lane.catchup.pop(p, None)

    # --------------------------------------------------------- maintenance
    def _maintain(self, lanes: List[_Lane], o) -> None:
        W = self.kcfg.log_window
        advance_g: List[int] = []
        advance_first: List[int] = []
        advance_term: List[int] = []
        need_rebase = False
        for lane in lanes:
            g = lane.g
            self._run_catchups(lane, o)
            # periodic snapshot by applied-entry count (node.go:585-601);
            # a wedged window forces one regardless of config
            se = lane.cfg.snapshot_entries
            log_full = bool(o["log_full"][g])
            if (
                (se > 0 and lane.applied_since_snapshot >= se) or log_full
            ) and not lane.snapshot_pending and lane.node.snapshotter is not None:
                applied, _ = lane.node.sm.get_last_applied()
                if applied > 0 and not lane.cfg.is_witness:
                    lane.snapshot_pending = True
                    lane.applied_since_snapshot = 0
                    from ..rsm import SSRequest

                    lane.node.push_take_snapshot_request(SSRequest())
            # device window compaction: advance first_index once the window
            # is half full; applied entries are recoverable from the host
            # log (catchup path) or a snapshot, so the device needs neither
            used = lane.last_index - (lane.base + lane.first_index) + 1
            applied, applied_term = lane.node.sm.get_last_applied()
            target = min(applied, lane.committed)
            if (used > W // 2 or log_full) and target + 1 > lane.base + lane.first_index:
                lane.first_index = target - lane.base + 1
                advance_g.append(g)
                advance_first.append(lane.first_index)
                advance_term.append(applied_term)
                # prune the arena below the window (payloads now live in
                # logdb/log_reader only)
                for idx in [i for i in lane.arena if i < target + 1]:
                    del lane.arena[idx]
            if lane.last_index - lane.base > _REBASE_THRESHOLD:
                need_rebase = True
        if advance_g:
            G = self.kcfg.groups
            mask = np.zeros((G,), bool)
            firsts = np.zeros((G,), np.int32)
            terms = np.zeros((G,), np.int32)
            mask[advance_g] = True
            firsts[advance_g] = advance_first
            terms[advance_g] = advance_term
            s = self._state
            m = jnp.asarray(mask)
            self._state = s._replace(
                first_index=jnp.where(m, jnp.asarray(firsts), s.first_index),
                marker_term=jnp.where(m, jnp.asarray(terms), s.marker_term),
            )
        if need_rebase:
            self._do_rebase(lanes)

    def _do_rebase(self, lanes: List[_Lane]) -> None:
        """Shift device indexes down so they never near 2**31. The delta is
        a multiple of W (ring-slot invariant, cf. ops/state.rebase)."""
        W = self.kcfg.log_window
        G = self.kcfg.groups
        delta = np.zeros((G,), np.int32)
        for lane in lanes:
            d = ((lane.first_index - 1) // W) * W
            if d > 0:
                delta[lane.g] = d
                lane.base += d
                lane.first_index -= d
        if delta.any():
            self._state = rebase(self._state, jnp.asarray(delta))

    # ----------------------------------------------------------- reconciles
    def _apply_reconciles(self) -> None:
        while self._reconq:
            op = self._reconq.popleft()
            try:
                kind = op[0]
                if kind == "activate":
                    self._activate(op[1])
                elif kind == "deactivate":
                    self._deactivate(op[1])
                elif kind == "membership":
                    self._reconcile_membership(op[1])
                elif kind == "restore":
                    self._reconcile_restore(op[1], op[2])
                elif kind == "cc_done":
                    lane = self._lane_of(op[1])
                    if lane is not None and lane.active:
                        s = self._state
                        self._state = s._replace(
                            pending_cc=s.pending_cc.at[lane.g].set(False)
                        )
                        lane.cc_inflight = False
                elif kind == "recover_done":
                    lane = self._lane_of(op[1])
                    if lane is not None:
                        lane.recovering = False
            except Exception:
                import traceback

                traceback.print_exc()

    def _lane_of(self, node) -> Optional[_Lane]:
        with self._lanes_mu:
            return self._lanes.get(node.cluster_id)

    def _activate(self, lane: _Lane) -> None:
        """Bring a lane live: bootstrap (initial start), restart replay, or
        join-as-empty. Mirrors Peer.launch + node.replayLog
        (cf. core/peer.py:75-94, node.go:553-583)."""
        node = lane.node
        node.recover_initial_snapshot()
        cfg = lane.cfg
        g = lane.g
        W = self.kcfg.log_window
        P = self.kcfg.peers
        # membership sources: SM image (restart w/ snapshot) else bootstrap
        mem = node.sm.get_membership()
        member_ids = set(mem.addresses) | set(mem.observers) | set(mem.witnesses)
        if not member_ids:
            member_ids = {a.node_id for a in node._vec_addresses}
        bootstrap = node._vec_initial and node._vec_new_node
        lane.set_slots(member_ids)
        self_slot = lane.self_slot()
        if self_slot < 0 and node.node_id() not in lane.slots:
            # join path: self not yet in membership; park on a free slot
            self_slot = lane.slot_of(node.node_id(), provisional=True)
        obs_ids = set(mem.observers)
        wit_ids = set(mem.witnesses)
        if not mem.addresses and bootstrap:
            obs_ids, wit_ids = set(), set()
        # persisted protocol state
        st = self._logdb_state(node)
        snap = node.snapshotter.get_most_recent_snapshot() if node.snapshotter else None
        snap_index = snap.index if snap is not None and not snap.is_empty() else 0
        first, last = node.log_reader.get_range()
        ents: List[Entry] = []
        if last >= first and last > 0:
            try:
                ents = node.log_reader.entries(first, last + 1, 1 << 30)
            except Exception:
                ents = []
        term = st.term
        vote_nid = st.vote
        committed = st.commit
        if bootstrap and not ents:
            # initial start: membership enters the log as config-change
            # entries at term 1, committed immediately (core/peer.py:273-294)
            addrs = sorted(node._vec_addresses, key=lambda a: a.node_id)
            from ..types import ConfigChange, ConfigChangeType

            for i, pa in enumerate(addrs):
                cc = ConfigChange(
                    type=ConfigChangeType.ADD_NODE,
                    node_id=pa.node_id,
                    initialize=True,
                    address=pa.address,
                )
                ents.append(
                    Entry(
                        type=EntryType.CONFIG_CHANGE,
                        term=1,
                        index=i + 1,
                        cmd=encode_config_change(cc),
                    )
                )
            committed = len(ents)
            term = max(term, 1)
        elif node._vec_new_node and not cfg.is_observer and not cfg.is_witness:
            term = max(term, 1)
        base = snap_index
        lane.base = base
        last_real = ents[-1].index if ents else max(snap_index, last if last else 0)
        dev_last = max(last_real - base, 0)
        dev_first = max(dev_last - W + 1, 1)
        lane.first_index = dev_first
        lane.committed = max(committed, snap_index)
        lane.last_index = last_real
        # ring metadata from the replayed entries
        ring_terms = np.zeros((W,), np.int32)
        ring_cc = np.zeros((W,), bool)
        for e in ents:
            lane.arena[e.index] = e
            di = e.index - base
            if dev_first <= di <= dev_last:
                ring_terms[di % W] = e.term
                ring_cc[di % W] = e.type == EntryType.CONFIG_CHANGE
        marker = dev_first - 1
        if marker == 0:
            marker_term = snap.term if snap_index and base == snap_index else 0
        else:
            try:
                marker_term = node.log_reader.term(base + marker)
            except Exception:
                marker_term = 0
        member = np.zeros((P,), bool)
        voting = np.zeros((P,), bool)
        observer = np.zeros((P,), bool)
        witness = np.zeros((P,), bool)
        for nid, slot in lane.slots.items():
            if slot >= P:
                continue
            member[slot] = True
            if nid in obs_ids:
                observer[slot] = True
            elif nid in wit_ids:
                witness[slot] = True
                voting[slot] = True
            else:
                voting[slot] = True
        role = (
            ROLE.OBSERVER if cfg.is_observer
            else ROLE.WITNESS if cfg.is_witness
            else ROLE.FOLLOWER
        )
        vote_slot = lane.slots.get(vote_nid, -1)
        s = self._state
        seed = int(np.asarray(s.seed[g]))
        from ..ops.state import _mix

        et = max(cfg.election_rtt, 3)
        hb = max(cfg.heartbeat_rtt, 1)
        upd = dict(
            active=s.active.at[g].set(True),
            self_slot=s.self_slot.at[g].set(max(self_slot, 0)),
            member=s.member.at[g].set(jnp.asarray(member)),
            voting=s.voting.at[g].set(jnp.asarray(voting)),
            observer=s.observer.at[g].set(jnp.asarray(observer)),
            witness=s.witness.at[g].set(jnp.asarray(witness)),
            term=s.term.at[g].set(term),
            vote=s.vote.at[g].set(vote_slot + 1 if vote_slot >= 0 else 0),
            role=s.role.at[g].set(role),
            leader=s.leader.at[g].set(0),
            tick_count=s.tick_count.at[g].set(0),
            election_tick=s.election_tick.at[g].set(0),
            heartbeat_tick=s.heartbeat_tick.at[g].set(0),
            election_timeout=s.election_timeout.at[g].set(et),
            heartbeat_timeout=s.heartbeat_timeout.at[g].set(hb),
            rand_timeout=s.rand_timeout.at[g].set(
                et + _mix(seed, term, max(self_slot, 0)) % et
            ),
            check_quorum=s.check_quorum.at[g].set(cfg.check_quorum),
            first_index=s.first_index.at[g].set(dev_first),
            marker_term=s.marker_term.at[g].set(marker_term),
            last_index=s.last_index.at[g].set(dev_last),
            committed=s.committed.at[g].set(lane.committed - base),
            processed=s.processed.at[g].set(max(snap_index - base, 0)),
            applied=s.applied.at[g].set(max(snap_index - base, 0)),
            unsaved_from=s.unsaved_from.at[g].set(
                1 if bootstrap else dev_last + 1
            ),
            log_term=s.log_term.at[g].set(jnp.asarray(ring_terms)),
            log_is_cc=s.log_is_cc.at[g].set(jnp.asarray(ring_cc)),
            match=s.match.at[g].set(0),
            next=s.next.at[g].set(dev_last + 1),
            rstate=s.rstate.at[g].set(RSTATE.RETRY),
            ract=s.ract.at[g].set(False),
            snap_sent=s.snap_sent.at[g].set(0),
            vresp=s.vresp.at[g].set(False),
            vgrant=s.vgrant.at[g].set(False),
            transfer_to=s.transfer_to.at[g].set(0),
            transfer_flag=s.transfer_flag.at[g].set(False),
            pending_cc=s.pending_cc.at[g].set(False),
            ri_ctx=s.ri_ctx.at[g].set(0),
            ri_index=s.ri_index.at[g].set(0),
            ri_acks=s.ri_acks.at[g].set(0),
            ri_count=s.ri_count.at[g].set(0),
        )
        self._state = s._replace(**upd)
        lane.active = True
        self._ready.set()

    def _logdb_state(self, node) -> State:
        st, _ = node.log_reader.node_state()
        return st if st is not None else State()

    def _deactivate(self, lane: _Lane) -> None:
        s = self._state
        self._state = s._replace(active=s.active.at[lane.g].set(False))
        lane.active = False
        with self._lanes_mu:
            self._free.append(lane.g)

    def _reconcile_membership(self, node) -> None:
        """Recompute the canonical slot mapping from the applied membership
        image and permute the per-peer device state accordingly."""
        lane = self._lane_of(node)
        if lane is None or not lane.active:
            return
        mem = node.sm.get_membership()
        member_ids = set(mem.addresses) | set(mem.observers) | set(mem.witnesses)
        if not member_ids:
            return
        P = self.kcfg.peers
        g = lane.g
        old_rev = dict(lane.rev)
        perm = lane.set_slots(member_ids)
        s = self._state
        # permute [P]-indexed rows: value at old slot moves to new slot
        def permute_row(row, default):
            vals = np.asarray(row)
            out = np.full_like(vals, default)
            for old, new in perm.items():
                if old < P and new < P:
                    out[new] = vals[old]
            return out

        member = np.zeros((P,), bool)
        voting = np.zeros((P,), bool)
        observer = np.zeros((P,), bool)
        witness = np.zeros((P,), bool)
        for nid, slot in lane.slots.items():
            if slot >= P:
                continue
            member[slot] = True
            if nid in mem.observers:
                observer[slot] = True
            elif nid in mem.witnesses:
                witness[slot] = True
                voting[slot] = True
            else:
                voting[slot] = True
        dev_last = int(np.asarray(s.last_index[g]))
        match = permute_row(s.match[g], 0)
        nxt = permute_row(s.next[g], dev_last + 1)
        nxt = np.maximum(nxt, 1)
        rstate = permute_row(s.rstate[g], RSTATE.RETRY)
        ract = permute_row(s.ract[g], False)
        snap_sent = permute_row(s.snap_sent[g], 0)
        vresp = permute_row(s.vresp[g], False)
        vgrant = permute_row(s.vgrant[g], False)

        def remap_ref(v):
            # slot+1 encoded references (leader/vote/transfer)
            v = int(np.asarray(v))
            if v <= 0:
                return 0
            new = perm.get(v - 1)
            return new + 1 if new is not None else 0

        self_slot = lane.self_slot()
        if self_slot < 0:
            self_slot = lane.slot_of(node.node_id(), provisional=True)
        upd = dict(
            member=s.member.at[g].set(jnp.asarray(member)),
            voting=s.voting.at[g].set(jnp.asarray(voting)),
            observer=s.observer.at[g].set(jnp.asarray(observer)),
            witness=s.witness.at[g].set(jnp.asarray(witness)),
            self_slot=s.self_slot.at[g].set(max(self_slot, 0)),
            leader=s.leader.at[g].set(remap_ref(s.leader[g])),
            vote=s.vote.at[g].set(remap_ref(s.vote[g])),
            transfer_to=s.transfer_to.at[g].set(remap_ref(s.transfer_to[g])),
            match=s.match.at[g].set(jnp.asarray(match)),
            next=s.next.at[g].set(jnp.asarray(nxt)),
            rstate=s.rstate.at[g].set(jnp.asarray(rstate)),
            ract=s.ract.at[g].set(jnp.asarray(ract)),
            snap_sent=s.snap_sent.at[g].set(jnp.asarray(snap_sent)),
            vresp=s.vresp.at[g].set(jnp.asarray(vresp)),
            vgrant=s.vgrant.at[g].set(jnp.asarray(vgrant)),
            # ack bitmasks are slot-indexed: clear and let heartbeats
            # re-confirm (membership changes are rare)
            ri_acks=s.ri_acks.at[g].set(0),
        )
        self._state = s._replace(**upd)
        # catchup/leader mirrors use slots: remap
        lane.catchup = {
            perm[p]: v for p, v in lane.catchup.items() if p in perm
        }
        if lane.leader_slot >= 0:
            lane.leader_slot = perm.get(lane.leader_slot, -1)

    def _reconcile_restore(self, node, ss: Snapshot) -> None:
        """An InstallSnapshot finished recovering: rebuild the lane at the
        snapshot point (cf. raft.go:439-517 restore + restoreRemotes)."""
        lane = self._lane_of(node)
        if lane is None:
            return
        g = lane.g
        P = self.kcfg.peers
        W = self.kcfg.log_window
        mem = ss.membership or node.sm.get_membership()
        member_ids = set(mem.addresses) | set(mem.observers) | set(mem.witnesses)
        lane.set_slots(member_ids)
        lane.base = ss.index
        lane.first_index = 1
        lane.committed = ss.index
        lane.last_index = ss.index
        lane.arena = {}
        lane.catchup = {}
        member = np.zeros((P,), bool)
        voting = np.zeros((P,), bool)
        observer = np.zeros((P,), bool)
        witness = np.zeros((P,), bool)
        for nid, slot in lane.slots.items():
            if slot >= P:
                continue
            member[slot] = True
            if nid in mem.observers:
                observer[slot] = True
            elif nid in mem.witnesses:
                witness[slot] = True
                voting[slot] = True
            else:
                voting[slot] = True
        self_slot = lane.self_slot()
        if self_slot < 0:
            self_slot = lane.slot_of(node.node_id(), provisional=True)
        s = self._state
        term = max(int(np.asarray(s.term[g])), ss.term)
        upd = dict(
            member=s.member.at[g].set(jnp.asarray(member)),
            voting=s.voting.at[g].set(jnp.asarray(voting)),
            observer=s.observer.at[g].set(jnp.asarray(observer)),
            witness=s.witness.at[g].set(jnp.asarray(witness)),
            self_slot=s.self_slot.at[g].set(max(self_slot, 0)),
            term=s.term.at[g].set(term),
            first_index=s.first_index.at[g].set(1),
            marker_term=s.marker_term.at[g].set(ss.term),
            last_index=s.last_index.at[g].set(0),
            committed=s.committed.at[g].set(0),
            processed=s.processed.at[g].set(0),
            applied=s.applied.at[g].set(0),
            unsaved_from=s.unsaved_from.at[g].set(1),
            log_term=s.log_term.at[g].set(jnp.zeros((W,), jnp.int32)),
            log_is_cc=s.log_is_cc.at[g].set(jnp.zeros((W,), bool)),
            match=s.match.at[g].set(0),
            next=s.next.at[g].set(1),
            rstate=s.rstate.at[g].set(RSTATE.RETRY),
            snap_sent=s.snap_sent.at[g].set(0),
            ri_ctx=s.ri_ctx.at[g].set(0),
            ri_index=s.ri_index.at[g].set(0),
            ri_acks=s.ri_acks.at[g].set(0),
            ri_count=s.ri_count.at[g].set(0),
        )
        self._state = s._replace(**upd)
        lane.recovering = False
        # persist the post-restore hard state and ack the leader so its
        # remote leaves the Snapshot state (raft.go handleInstallSnapshot)
        self._logdb.save_raft_state(
            [
                Update(
                    cluster_id=node.cluster_id,
                    node_id=node.node_id(),
                    state=State(term=term, vote=0, commit=ss.index),
                )
            ]
        )
        leader = lane.rev.get(lane.leader_slot)
        sender = leader if leader and leader != node.node_id() else None
        if sender is None:
            # best effort: ack every voting peer; only the leader cares
            senders = [n for n in lane.slots if n != node.node_id()]
        else:
            senders = [sender]
        for nid in senders:
            node._send_message(
                Message(
                    type=MT.REPLICATE_RESP,
                    cluster_id=node.cluster_id,
                    to=nid,
                    from_=node.node_id(),
                    term=term,
                    log_index=ss.index,
                )
            )

    # --------------------------------------------------------- worker mains
    def _task_worker_main(self, worker: int) -> None:
        batch: list = []
        apply: list = []
        while not self._stopped.is_set():
            cids = self.task_ready.wait_and_take(worker)
            if not cids:
                continue
            for cid in cids:
                node = self.get_node(cid)
                if node is None or node.stopped:
                    continue
                try:
                    node.handle_task(batch, apply)
                except Exception:
                    import traceback

                    traceback.print_exc()
                if node.sm.task_queue.size() > 0:
                    self.set_task_ready(cid)

    def _snapshot_worker_main(self, worker: int) -> None:
        while not self._stopped.is_set():
            cids = self.snapshot_ready.wait_and_take(worker)
            if not cids:
                continue
            for cid in cids:
                node = self.get_node(cid)
                if node is None or node.stopped:
                    continue
                try:
                    node.run_snapshot_work()
                except Exception:
                    import traceback

                    traceback.print_exc()
                lane = self._lane_of(node)
                if lane is not None:
                    lane.snapshot_pending = False

    # --------------------------------------------------------------- control
    def stop(self) -> None:
        self._stopped.set()
        self._ready.set()
        self.task_ready.wake_all()
        self.snapshot_ready.wake_all()
        # the step thread must fully drain its in-flight iteration before
        # the caller closes the logdb under it; a short join here would let
        # a slow device step race the close (observed as "write to closed
        # file" + a C++ abort at interpreter teardown)
        for t in self._threads:
            t.join(timeout=30 if t.name == "vec-step" else 2)


__all__ = ["VectorEngine", "VectorNode"]
