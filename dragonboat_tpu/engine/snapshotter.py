"""Host-side snapshot file lifecycle.

cf. snapshotter.go:34-338 + internal/server/snapshotenv.go:117-280 — a
snapshot is written into a temp directory, finalized with an atomic rename,
and recorded in the LogDB; orphaned temp dirs from crashes are swept at
startup. Keeps the 3 most recent snapshots (snapshotter.go:34-36).
"""
from __future__ import annotations

import os
import shutil
import threading
from typing import List, Optional, Tuple

from ..rsm.manager import SSMeta, SSRequest
from ..rsm.snapshotio import (
    SnapshotHeader,
    SnapshotReader,
    SnapshotWriter,
    validate_snapshot_file,
)
from ..statemachine import ISnapshotFileCollection, SnapshotFile
from ..types import Membership, Snapshot, Update

SNAPSHOTS_TO_KEEP = 3
GENERATING_SUFFIX = ".generating"
# metadata record written into exported snapshot dirs (cf. the reference's
# server.SnapshotMetadataFilename "snapshot.metadata")
SNAPSHOT_METADATA_FILENAME = "snapshot.metadata"
RECEIVING_SUFFIX = ".receiving"


class FileCollection(ISnapshotFileCollection):
    """Collects external files the SM adds during save
    (cf. internal/rsm/files.go:26-89)."""

    def __init__(self, dirname: str) -> None:
        self._dir = dirname
        self.files: List[SnapshotFile] = []

    def add_file(self, file_id: int, path: str, metadata: bytes) -> None:
        self.files.append(
            SnapshotFile(file_id=file_id, filepath=path, metadata=metadata)
        )

    def finalize(self, record_dir: Optional[str] = None) -> List:
        """Hard-link/copy external files into the snapshot dir. The files
        land in self._dir (the crash-safe .generating temp dir), but the
        RECORDED paths must point at record_dir — the final directory the
        temp dir is renamed to on commit — or every later load would chase
        a path that no longer exists."""
        out = []
        from ..types import SnapshotFile as WireFile

        for i, f in enumerate(self.files):
            name = f"external-file-{f.file_id}"
            dst = os.path.join(self._dir, name)
            try:
                os.link(f.filepath, dst)
            except OSError:
                shutil.copy2(f.filepath, dst)
            out.append(
                WireFile(
                    filepath=os.path.join(record_dir or self._dir, name),
                    file_size=os.path.getsize(dst),
                    file_id=f.file_id,
                    metadata=f.metadata,
                )
            )
        return out


class Snapshotter:
    """Per-node snapshot manager (cf. snapshotter.go:55-78)."""

    def __init__(self, root_dir: str, cluster_id: int, node_id: int, logdb) -> None:
        self.cluster_id = cluster_id
        self.node_id = node_id
        self._logdb = logdb
        self._dir = os.path.join(
            root_dir, f"snapshot-part-{cluster_id:020d}-{node_id:020d}"
        )
        self._mu = threading.Lock()
        self._sm = None
        # lazy dir: a node that never snapshots never touches the fs — at
        # 50k groups the per-cluster mkdir+orphan scan was a measured third
        # of fleet bring-up. Orphan processing only matters if the dir
        # already exists (a previous incarnation wrote into it).
        if os.path.isdir(self._dir):
            self.process_orphans()

    def bind_sm(self, sm) -> None:
        self._sm = sm

    # ------------------------------------------------------------- locations
    def _final_dir(self, index: int) -> str:
        return os.path.join(self._dir, f"snapshot-{index:016X}")

    def _tmp_dir(self, index: int, suffix: str = GENERATING_SUFFIX) -> str:
        return self._final_dir(index) + suffix

    def _file_path(self, index: int) -> str:
        return os.path.join(self._final_dir(index), f"snapshot-{index:016X}.gbsnap")

    # ----------------------------------------------------------- save / load
    def save(self, save_fn, meta: SSMeta) -> Tuple[Snapshot, object]:
        """Write the snapshot image (cf. snapshotter.go:95-142 Save). The
        rsm manager supplies save_fn(writer, files)."""
        index = meta.index
        tmp = self._tmp_dir(index)
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        fname = f"snapshot-{index:016X}.gbsnap"
        fpath = os.path.join(tmp, fname)
        header = SnapshotHeader(
            index=meta.index,
            term=meta.term,
            on_disk_index=meta.on_disk_index,
            smtype=self._sm.sm_type() if self._sm is not None else 0,
            membership=meta.membership,
            compression=meta.compression,
        )
        files = FileCollection(tmp)
        with open(fpath, "wb") as f:
            w = SnapshotWriter(f, header, meta.session)
            save_fn(w, files)
            w.close()
            f.flush()
            os.fsync(f.fileno())
        wire_files = files.finalize(record_dir=self._final_dir(index))
        ss = Snapshot(
            filepath=os.path.join(self._final_dir(index), fname),
            file_size=os.path.getsize(fpath),
            index=meta.index,
            term=meta.term,
            membership=meta.membership,
            files=wire_files,
            cluster_id=self.cluster_id,
            type=header.smtype,
            on_disk_index=meta.on_disk_index,
        )
        return ss, tmp

    def commit(self, ss: Snapshot, req: Optional[SSRequest] = None) -> None:
        """Finalize: atomic rename + logdb record + retention
        (cf. snapshotter.go:173-194 Commit)."""
        tmp = self._tmp_dir(ss.index)
        final = self._final_dir(ss.index)
        if req is not None and req.is_exported():
            # exported snapshots move to the user path instead, with a
            # metadata record so tools.import_snapshot can rebuild the
            # Snapshot record (cf. server.SnapshotMetadataFilename). The
            # metadata is written INSIDE the temp dir so the rename below is
            # the single crash-atomic commit point; all recorded paths are
            # rebased onto the post-rename destination.
            import dataclasses

            from .. import codec

            dst = os.path.join(req.path, os.path.basename(final))
            meta_ss = dataclasses.replace(
                ss,
                filepath=os.path.join(dst, os.path.basename(ss.filepath)),
                files=[
                    dataclasses.replace(
                        f,
                        filepath=os.path.join(
                            dst, os.path.basename(f.filepath)
                        ),
                    )
                    for f in ss.files
                ],
            )
            mpath = os.path.join(tmp, SNAPSHOT_METADATA_FILENAME)
            with open(mpath, "wb") as f:
                f.write(codec.encode_snapshot(meta_ss))
                f.flush()
                os.fsync(f.fileno())
            os.rename(tmp, dst)
            return
        with self._mu:
            if os.path.exists(final):
                shutil.rmtree(tmp, ignore_errors=True)
            else:
                os.replace(tmp, final)
            self._logdb.save_snapshots(
                [
                    Update(
                        cluster_id=self.cluster_id,
                        node_id=self.node_id,
                        snapshot=ss,
                    )
                ]
            )
        self.compact(ss.index)

    def get_most_recent_snapshot(self) -> Optional[Snapshot]:
        snaps = self._logdb.list_snapshots(self.cluster_id, self.node_id, 2**62)
        while snaps:
            ss = snaps[-1]
            if ss.dummy or ss.witness or os.path.exists(ss.filepath):
                return ss
            snaps.pop()
        return None

    def load(self, ss: Snapshot, load_fn) -> None:
        """Open + validate + hand payload stream to the rsm layer
        (cf. snapshotter.go:144-171 Load)."""
        with open(ss.filepath, "rb") as f:
            r = SnapshotReader(f)
            files = [
                SnapshotFile(
                    file_id=sf.file_id, filepath=sf.filepath, metadata=sf.metadata
                )
                for sf in ss.files
            ]
            load_fn(r, r.session, files)

    def stream(self, save_fn, meta: SSMeta, sink) -> None:
        """Stream a snapshot through a chunk sink (on-disk SM live stream,
        cf. statemachine.go:680-695); sink implements write/close."""
        header = SnapshotHeader(
            index=meta.index,
            term=meta.term,
            on_disk_index=meta.on_disk_index,
            smtype=self._sm.sm_type() if self._sm is not None else 0,
            membership=meta.membership,
        )
        w = SnapshotWriter(sink, header, meta.session)
        try:
            save_fn(w, None)
            w.close()
            sink.finalize()
        except Exception:
            sink.abort()
            raise

    # ------------------------------------------------------------- retention
    def compact(self, latest_index: int) -> None:
        """Keep SNAPSHOTS_TO_KEEP records, remove older files + records
        (cf. snapshotter.go:255-277)."""
        snaps = self._logdb.list_snapshots(self.cluster_id, self.node_id, 2**62)
        if len(snaps) <= SNAPSHOTS_TO_KEEP:
            return
        for ss in snaps[:-SNAPSHOTS_TO_KEEP]:
            self._logdb.delete_snapshot(self.cluster_id, self.node_id, ss.index)
            shutil.rmtree(self._final_dir(ss.index), ignore_errors=True)

    def shrink(self, to_index: int) -> None:
        """Replace applied full snapshots of an on-disk SM with dummy
        metadata-only images (cf. snapshotter.go:229-253). The dummy keeps
        index/term/membership for restart replay but drops the payload."""
        snaps = self._logdb.list_snapshots(self.cluster_id, self.node_id, to_index)
        for ss in snaps:
            if ss.dummy or ss.witness:
                continue
            dummy = Snapshot(
                filepath=ss.filepath,
                index=ss.index,
                term=ss.term,
                membership=ss.membership,
                cluster_id=ss.cluster_id,
                on_disk_index=ss.on_disk_index,
                dummy=True,
            )
            self._logdb.save_snapshots(
                [
                    Update(
                        cluster_id=self.cluster_id,
                        node_id=self.node_id,
                        snapshot=dummy,
                    )
                ]
            )
            shutil.rmtree(self._final_dir(ss.index), ignore_errors=True)

    # --------------------------------------------------------------- recovery
    def process_orphans(self) -> None:
        """Sweep crashed temp dirs (cf. snapshotter.go:279-338).

        `.receiving` dirs carrying a stream-progress record are NOT
        orphans anymore: they are the resume state of an interrupted
        inbound snapshot stream (transport/chunks.py) — the restarted
        host's re-streamed install fast-forwards through the chunks they
        already hold instead of re-transferring them. Progress-less
        `.receiving` dirs (pre-resume-protocol leftovers, torn creates)
        still sweep; the chunk tracker reclaims stale resumable partials
        itself when a newer stream begins."""
        if not os.path.isdir(self._dir):
            return
        for name in os.listdir(self._dir):
            path = os.path.join(self._dir, name)
            if name.endswith(GENERATING_SUFFIX):
                shutil.rmtree(path, ignore_errors=True)
            elif name.endswith(RECEIVING_SUFFIX) and not os.path.exists(
                os.path.join(path, "stream-progress.json")
            ):
                shutil.rmtree(path, ignore_errors=True)

    def dir_path(self) -> str:
        return self._dir


__all__ = ["Snapshotter", "FileCollection", "SNAPSHOTS_TO_KEEP"]
