"""Execution engine layer (cf. execengine.go, node.go)."""

from .execengine import ExecEngine, WorkReady
from .node import Node
from .quiesce import QuiesceManager
from .snapshotter import Snapshotter

__all__ = ["ExecEngine", "WorkReady", "Node", "QuiesceManager", "Snapshotter"]
