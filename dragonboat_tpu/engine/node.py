"""Per-group node runtime: binds one Raft group's Peer + state machine +
request queues and pumps events between them.

cf. node.go:53-1399 — the node is the unit the execution engine schedules.
All protocol work happens inside step_node() on a step worker; all apply
work inside handle_task() on a task worker; the public request methods only
enqueue and wake the engine.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Callable, List, Optional

from ..client import Session
from ..config import Config
from ..core.peer import Peer, PeerAddress, encode_config_change
from ..core.remote import RemoteState
from ..core.logentry import ErrCompacted
from ..requests import (
    BATCH_KEY_BIT,
    BatchRequestState,
    ErrClusterClosed,
    ErrInvalidSession,
    ErrPayloadTooBig,
    ErrSnapshotStreamAborted,
    ErrSystemBusy,
    ErrTimeoutTooSmall,
    LogicalClock,
    PendingConfigChange,
    PendingLeaderTransfer,
    PendingProposal,
    PendingReadIndex,
    PendingSnapshot,
    RequestState,
    batch_id_of,
    make_batch_id,
    make_batch_key,
)
from ..rsm.encoded import maybe_encode_entry
from ..rsm import (
    SSRequest,
    SS_REQ_EXPORTED,
    SS_REQ_USER,
    StateMachineManager,
    Task,
    wrap_state_machine,
)
from ..settings import soft
from ..statemachine import Result
from ..types import (
    ConfigChange,
    Entry,
    EntryType,
    Membership,
    Message,
    MessageType,
    Snapshot,
    Update,
)
from ..trace import LatencyTrace, flight_recorder, mint_trace_id
from .quiesce import QuiesceManager
from .queue import EntryQueue, MessageQueue, ReadIndexQueue
from .snapshotstate import SnapshotState


class Node:
    def __init__(
        self,
        cfg: Config,
        peer_addresses: List[PeerAddress],
        initial: bool,
        new_node: bool,
        sm_factory: Callable,
        log_reader,
        logdb,
        snapshotter,
        send_message: Callable[[Message], None],
        engine,
        event_listener=None,
        rng: Optional[random.Random] = None,
        send_messages: Optional[Callable[[List[Message]], None]] = None,
        register_peer: Optional[Callable[[int, int, str], None]] = None,
    ) -> None:
        self.config = cfg
        self.cluster_id = cfg.cluster_id
        self._node_id = cfg.node_id
        self.log_reader = log_reader
        self.logdb = logdb
        self.snapshotter = snapshotter
        self._send_message = send_message
        # optional bulk path (one co-hosted delivery pass + one grouped
        # wire send per batch); None falls back to per-message sends
        self._send_messages = send_messages
        # host transport registrar: a committed ADD_* config change (and
        # a snapshot-restored membership) carries the member's address in
        # REPLICATED state, so every applying replica can register it —
        # without this, only the host that REQUESTED the change can route
        # to the new member, and a migrated-in replica strands the moment
        # leadership leaves that host (cf. nodes.go: the reference gets
        # the same cluster-wide knowledge from its nodehost registry)
        self._register_peer = register_peer
        self.engine = engine
        self.events = event_listener
        self.clock = self._make_clock(engine)
        self.pending_proposals = PendingProposal(self.clock)
        self.pending_read_indexes = PendingReadIndex(self.clock)
        self.pending_config_change = PendingConfigChange(self.clock)
        self.pending_snapshot = PendingSnapshot(self.clock)
        self.pending_leader_transfer = PendingLeaderTransfer()
        self.incoming_proposals = EntryQueue(soft.incoming_proposal_queue_length)
        self.incoming_reads = ReadIndexQueue(soft.incoming_read_index_queue_length)
        # batch-tracked proposals (propose_batch_async): ONE handle per
        # submission, completion routed by the key's (batch_id, seq)
        self._batch_mu = threading.Lock()
        self._batches: dict = {}  # batch_id -> BatchRequestState
        self._batch_seq = 0
        self.mq = MessageQueue(soft.received_message_queue_length)
        # sampled request-latency seam (see trace.LatencySampler): the
        # engine owns the sampler so every group on it shares one ratio
        # (EngineConfig.profile_sample_ratio); unsampled requests pay one
        # increment and allocate nothing
        self._req_sampler = getattr(engine, "request_sampler", None)
        self.quiesce_mgr = QuiesceManager(
            enabled=cfg.quiesce, election_tick=cfg.election_rtt
        )
        self.stopped = False
        self._mu = threading.Lock()
        self._init_mu = threading.Lock()
        # config-change requests handed from API to step worker
        self._cc_queue: List = []
        self._leader_id = 0
        self._current_term = 0
        # logical-clock stamp of the last observed leader transition:
        # ExecEngine.lane_stats() derives ticks_since_leader_change from it
        # (parity with the vector engine's _m_leader_change_tick mirror)
        self._leader_change_tick = 0
        self._rate_limited = False  # refreshed each step (cf. node.go:1095)
        # ticks each peer has spent parked in RemoteState.SNAPSHOT, for
        # the delayed snapshot-status retry (_snapshot_feedback)
        self._snap_parked: dict = {}
        # aborted inbound snapshot-install stream window: while fresh, ops
        # that gate on the install fail FAST with the typed
        # ErrSnapshotStreamAborted instead of a generic timeout. Plain
        # GIL-atomic stamps — written from the chunk sink's notify, read
        # on the API paths; cleared when a restore completes.
        self._install_abort_deadline = 0.0
        self._install_abort_hint = 0.0
        self._confirmed_applied = 0  # applied index confirmed into an Update
        self.initialized = threading.Event()
        # rsm manager
        managed = wrap_state_machine(
            sm_factory(cfg.cluster_id, cfg.node_id), cfg.cluster_id, cfg.node_id
        )
        self.sm = StateMachineManager(snapshotter, managed, self, cfg)
        if snapshotter is not None:
            snapshotter.bind_sm(self.sm)
        if self.sm.on_disk_state_machine():
            # open the user's on-disk state BEFORE the protocol core (and
            # any snapshot recovery / log replay) runs: the returned index
            # seeds the manager's skip-until cursor so already-persisted
            # entries are not re-applied, and step_node's applied-cursor
            # notifications start from it (cf. statemachine.go:374-389
            # OpenOnDiskStateMachine; node.go:553-583)
            self.sm.open()
        # snapshot FSM: flags + one req/completed slot per kind
        # (cf. snapshotstate.go:64-214)
        self.ss = SnapshotState()
        self._applied_since_snapshot = 0
        # launch the protocol core (VectorNode overrides: its protocol state
        # lives in the shared device tensors, not a per-group Peer)
        self.peer = self._launch_core(
            cfg, log_reader, peer_addresses, initial, new_node, rng
        )
        if not self._has_snapshot_to_recover():
            self.initialized.set()

    def _make_clock(self, engine):
        """Per-node logical clock; the VectorEngine overrides this with one
        clock shared by every lane so deadlines stay comparable."""
        return LogicalClock()

    def _launch_core(self, cfg, log_reader, peer_addresses, initial, new_node, rng):
        return Peer.launch(
            cfg,
            log_reader,
            events=self._make_raft_event_adapter(),
            addresses=peer_addresses,
            initial=initial,
            new_node=new_node,
            rng=rng,
        )

    # ----------------------------------------------------------------- naming
    def node_id(self) -> int:
        return self._node_id

    def describe(self) -> str:
        return f"[{self.cluster_id:05d}:{self._node_id:05d}]"

    # ----------------------------------------------------- INodeProxy methods
    def node_ready(self) -> None:
        self.engine.set_node_ready(self.cluster_id)

    # -------------------------------------------------- latency observation
    def _metrics_registry(self):
        ev = self.events
        return getattr(ev, "metrics", None) if ev is not None else None

    def _observe_entry_latency(self, lt: LatencyTrace) -> None:
        """A sampled proposal finished its apply: fold the lifecycle into
        the proposing node's latency histograms. Owner-pinned (co-hosted
        replicas apply the identical Entry objects) and once-only."""
        if lt.owner is not self or lt.done:
            return
        lt.done = True
        if lt.trace_id:
            # final causal stage: the sampled proposal applied + notified
            # on its proposing node
            flight_recorder().record(
                "proposal_applied", cluster=self.cluster_id,
                node=self._node_id, trace=lt.trace_id,
            )
        m = self._metrics_registry()
        if m is None:
            return
        now = time.monotonic()
        key = (self.cluster_id, self._node_id)
        # a missing commit stamp (engine variant without one) degrades to
        # commit==apply rather than dropping the sample
        commit_t = lt.t_commit or now
        m.observe(
            "proposal_commit_latency_seconds", key, max(commit_t - lt.t0, 0.0)
        )
        m.observe(
            "proposal_apply_latency_seconds", key, max(now - lt.t0, 0.0)
        )

    def _read_latency_done(self, rs: RequestState) -> None:
        t0 = rs.lat
        r = rs.result
        if t0 is None or r is None or not r.completed:
            return  # timed-out/dropped reads are not read latencies
        m = self._metrics_registry()
        if m is not None:
            m.observe(
                "readindex_latency_seconds",
                (self.cluster_id, self._node_id),
                max(time.monotonic() - t0, 0.0),
            )

    def apply_update(self, entry, result, rejected, ignored, notify_read) -> None:
        if entry.lat is not None:
            self._observe_entry_latency(entry.lat)
        if entry.key & BATCH_KEY_BIT:
            self._batch_applied(batch_id_of(entry.key), 1)
        else:
            self.pending_proposals.applied(
                entry.key, entry.client_id, entry.series_id, result, rejected
            )
        if notify_read:
            self.pending_read_indexes.applied(entry.index)

    def apply_update_run(self, entries, results=None) -> None:
        """Run-level completion for a contiguous batch of plain applied
        entries (the RSM manager's fast path): batch-tracked proposals
        complete per (batch_id, count) instead of per entry. `results`
        aligns with `entries`; None means no per-request keys exist in the
        run (the manager skips result realignment for pure batch runs)."""
        counts: dict = {}
        if results is None and not self._batches:
            return  # replica apply with no locally-tracked batches
        if results is None:
            for e in entries:
                if e.lat is not None:
                    self._observe_entry_latency(e.lat)
                if e.key & BATCH_KEY_BIT:
                    bid = batch_id_of(e.key)
                    counts[bid] = counts.get(bid, 0) + 1
        else:
            for e, r in zip(entries, results):
                if e.lat is not None:
                    self._observe_entry_latency(e.lat)
                if e.key & BATCH_KEY_BIT:
                    bid = batch_id_of(e.key)
                    counts[bid] = counts.get(bid, 0) + 1
                elif e.key:
                    self.pending_proposals.applied(
                        e.key, e.client_id, e.series_id, r, False
                    )
        for bid, n in counts.items():
            self._batch_applied(bid, n)

    def _batch_applied(self, batch_id: int, n: int) -> None:
        with self._batch_mu:
            h = self._batches.get(batch_id)
        if h is None:
            return  # submitted elsewhere (replica apply) or already expired
        h.add_done(completed=n)
        if h.finished:
            with self._batch_mu:
                self._batches.pop(batch_id, None)

    def proposal_dropped(self, entry) -> None:
        """Drop notification that understands batch-tracked keys (the
        engine calls this instead of pending_proposals.dropped directly)."""
        if entry.key & BATCH_KEY_BIT:
            with self._batch_mu:
                h = self._batches.get(batch_id_of(entry.key))
            if h is not None:
                h.add_done(dropped=1)
        else:
            self.pending_proposals.dropped(entry.key)

    def apply_config_change(self, cc: ConfigChange) -> None:
        """Called by the RSM when a config change commits; updates the
        protocol-core membership (cf. node.go applyConfigChange)."""
        self._register_cc_address(cc)
        with self._mu:
            self.peer.apply_config_change(cc)
        if cc.node_id == self._node_id and cc.type.name == "REMOVE_NODE":
            pass  # node removal handled by nodehost monitor

    def _register_cc_address(self, cc: ConfigChange) -> None:
        """Every replica applying an ADD_* change registers the new
        member's address with its host transport: the address rides the
        replicated entry, so routing knowledge is cluster-wide, not
        request-host-local (a live migration's swapped-in member must
        stay reachable after leadership leaves the host that added it)."""
        if self._register_peer is not None and cc.address:
            self._register_peer(self.cluster_id, cc.node_id, cc.address)

    def membership_loaded(self, membership) -> None:
        """A snapshot restore installed a full membership image: register
        every member's address (the joiner's ONLY source of its peers'
        addresses — its bootstrap is empty by definition of join)."""
        if self._register_peer is None:
            return
        for table in (
            membership.addresses,
            getattr(membership, "observers", None) or {},
            getattr(membership, "witnesses", None) or {},
        ):
            for nid, addr in table.items():
                if addr:
                    self._register_peer(self.cluster_id, nid, addr)

    def config_change_processed(self, key: int, accepted: bool) -> None:
        if accepted:
            self.pending_config_change.apply(key, rejected=False)
        else:
            self.peer.reject_config_change()
            self.pending_config_change.apply(key, rejected=True)

    def should_stop(self) -> bool:
        return self.stopped

    # ------------------------------------------------------------ public API
    def propose(
        self, session: Session, cmd: bytes, timeout_ticks: int
    ) -> RequestState:
        if len(cmd) > soft.max_proposal_payload_size:
            raise ErrPayloadTooBig()
        if self._rate_limited:
            # some replica's in-mem log is over Config.max_in_mem_log_size;
            # refuse new work until the fleet drains (cf. node.go:1094-1105
            # handleProposals + requests.go ErrSystemBusy)
            raise ErrSystemBusy()
        rs, entry = self.pending_proposals.propose(session, cmd, timeout_ticks)
        s = self._req_sampler
        if s is not None and s.sample():
            # propose-enqueue timestamp; the trace rides the Entry through
            # arena -> commit -> apply and back to the histograms. The
            # trace id additionally rides the wire (Entry/Message codec)
            # so remote hops stamp the same causal key.
            entry.lat = LatencyTrace(
                self, time.monotonic(), trace_id=mint_trace_id()
            )
            entry.trace_id = entry.lat.trace_id
            flight_recorder().record(
                "propose_enqueue", cluster=self.cluster_id,
                node=self._node_id, trace=entry.trace_id,
            )
        # optional payload compression at the propose boundary: the wire,
        # logdb and apply queue all carry the compressed form; replicas
        # decompress once at apply time (cf. rsm/encoded.go:47-176)
        maybe_encode_entry(self.config.entry_compression_type, entry)
        if not self.incoming_proposals.add(entry):
            self.pending_proposals.dropped(rs.key)
            raise ErrSystemBusy()
        self.engine.set_node_ready(self.cluster_id)
        return rs

    def propose_batch(
        self, session: Session, cmds, timeout_ticks: int
    ) -> List[RequestState]:
        """Submit many proposals with one registry lock, one queue lock
        and one engine wake-up. The per-proposal Python round-trip is the
        submission ceiling on a pipelined client; batching amortizes it
        (the engines already ingest and persist in batches). Only no-op
        sessions may batch: a registered session's at-most-once bookkeeping
        is strictly sequential (cf. client session semantics,
        requests.go:141-166). Overflow past the queue capacity completes
        those requests as DROPPED rather than failing the whole batch."""
        cmds = list(cmds)  # one-shot iterables must survive the pre-checks
        if not session.is_noop_session() and len(cmds) > 1:
            raise ErrInvalidSession()
        for cmd in cmds:
            if len(cmd) > soft.max_proposal_payload_size:
                raise ErrPayloadTooBig()
        if self._rate_limited:
            raise ErrSystemBusy()
        rss, entries = self.pending_proposals.propose_batch(
            session, cmds, timeout_ticks
        )
        s = self._req_sampler
        if entries and s is not None and s.sample():
            # one sampled entry per batch keeps the sampler's 1-in-N
            # meaning "1-in-N submissions", not "N samples per wave"
            e = entries[-1]
            e.lat = LatencyTrace(
                self, time.monotonic(), trace_id=mint_trace_id()
            )
            e.trace_id = e.lat.trace_id
            flight_recorder().record(
                "propose_enqueue", cluster=self.cluster_id,
                node=self._node_id, trace=e.trace_id, batch=len(entries),
            )
        for entry in entries:
            maybe_encode_entry(self.config.entry_compression_type, entry)
        accepted = self.incoming_proposals.add_many(entries)
        for entry in entries[accepted:]:
            self.pending_proposals.dropped(entry.key)
        if accepted:
            self.engine.set_node_ready(self.cluster_id)
        return rss

    def propose_batch_async(
        self, session: Session, cmds, timeout_ticks: int
    ) -> BatchRequestState:
        """Fire-and-collect batch submission: ONE handle, ONE completion
        event for the whole batch; per-proposal results are not retained
        (use propose/propose_batch when they matter). No-op sessions only.
        The entries carry (batch_id, seq) in their key, so completion
        survives host-side forwarding and leader changes."""
        cmds = list(cmds)
        if not session.is_noop_session():
            raise ErrInvalidSession()
        if timeout_ticks < 1:
            raise ErrTimeoutTooSmall()
        for cmd in cmds:
            if len(cmd) > soft.max_proposal_payload_size:
                raise ErrPayloadTooBig()
        if self._rate_limited:
            raise ErrSystemBusy()
        with self._batch_mu:
            if self.stopped:
                raise ErrClusterClosed()
            self._batch_seq += 1
            bid = make_batch_id(self._node_id, self._batch_seq)
            h = BatchRequestState(
                bid, len(cmds), self.clock.tick + timeout_ticks
            )
            self._batches[bid] = h
        if not cmds:
            h.expire()
            return h
        key0 = make_batch_key(bid, 0)
        entries = [
            Entry(
                key=key0 + i,
                client_id=session.client_id,
                series_id=session.series_id,
                responded_to=session.responded_to,
                cmd=cmd,
            )
            for i, cmd in enumerate(cmds)
        ]
        s = self._req_sampler
        if entries and s is not None and s.sample():
            e = entries[-1]
            e.lat = LatencyTrace(
                self, time.monotonic(), trace_id=mint_trace_id()
            )
            e.trace_id = e.lat.trace_id
            flight_recorder().record(
                "propose_enqueue", cluster=self.cluster_id,
                node=self._node_id, trace=e.trace_id, batch=len(entries),
            )
        if self.config.entry_compression_type:
            for entry in entries:
                maybe_encode_entry(self.config.entry_compression_type, entry)
        accepted = self.incoming_proposals.add_many(entries)
        if accepted < len(entries):
            h.add_done(dropped=len(entries) - accepted)
        if accepted:
            self.engine.set_node_ready(self.cluster_id)
        return h

    def gc_batches(self) -> None:
        """Expire timed-out batch handles (called from the tick/gc pass)."""
        if not self._batches:
            return
        now = self.clock.tick
        with self._batch_mu:
            dead = [
                bid for bid, h in self._batches.items() if h.deadline < now
            ]
            handles = [self._batches.pop(bid) for bid in dead]
        for h in handles:
            h.expire()

    # -------------------------------------------- snapshot-stream aborts
    def notify_install_aborted(self, retry_after_s: float) -> None:
        """An inbound snapshot-install stream for this replica aborted
        (receiver crash / sender failure / chunk gap): open the fail-fast
        window. `retry_after_s` is both the window length and the hint
        clients receive — sized by the caller to the raft snapshot-status
        retry cadence (when a re-streamed install should have landed)."""
        self._install_abort_hint = retry_after_s
        self._install_abort_deadline = time.monotonic() + retry_after_s

    def clear_install_aborted(self) -> None:
        """A snapshot restore completed: the lag the aborted stream left
        behind is gone, stop failing fast."""
        self._install_abort_deadline = 0.0

    def _check_install_aborted(self) -> None:
        # the window opened because a stream this replica NEEDED died
        # (retry restarts are filtered out at the chunk tracker); until a
        # restore completes (clear_install_aborted) or the re-stream
        # window passes, ops gated on the install fail fast with the
        # typed, retry-hinted error — a retried op lands after the hint
        # and succeeds whether the node recovered via the re-streamed
        # install or via leader log replay
        dl = self._install_abort_deadline
        if dl and time.monotonic() < dl:
            raise ErrSnapshotStreamAborted(self._install_abort_hint)

    def notify_admission(self) -> bool:
        """Serving-front first-admit wake (engine/quiesce.py contract):
        an idle quiesced group resumes ticking immediately instead of
        waiting for the admitted op to reach the step loop. Returns True
        when the group was actually quiesced. Called from API threads;
        the quiesce fields are GIL-atomic scalars and a racing step-side
        tick at worst re-enters quiesce one threshold later — the same
        tolerance record_activity already has."""
        woke = self.quiesce_mgr.wake_on_admit()
        if woke:
            self.engine.set_node_ready(self.cluster_id)
        return woke

    def read(self, timeout_ticks: int) -> RequestState:
        # a linearizable read on a lagging replica gates on the applied
        # index catching up to the read index — exactly what a snapshot
        # install advances. With the install stream freshly aborted the
        # read would burn its whole budget into ErrTimeout; fail fast
        # with the typed, retry-hinted error instead.
        self._check_install_aborted()
        rs = self.pending_read_indexes.read(timeout_ticks)
        s = self._req_sampler
        if s is not None and s.sample():
            rs.lat = time.monotonic()
            rs.on_complete(self._read_latency_done)
        if not self.incoming_reads.add(rs):
            raise ErrSystemBusy()
        self.engine.set_node_ready(self.cluster_id)
        return rs

    def request_config_change(
        self, cc: ConfigChange, timeout_ticks: int
    ) -> RequestState:
        rs, cc, key = self.pending_config_change.request(cc, timeout_ticks)
        with self._mu:
            self._cc_queue.append((cc, key))
        self.engine.set_node_ready(self.cluster_id)
        return rs

    def request_snapshot(self, req: SSRequest, timeout_ticks: int) -> RequestState:
        rs, req = self.pending_snapshot.request(req, timeout_ticks)
        if self.ss.taking_snapshot():
            # a save is already in flight (possibly an automatic one that
            # registered no pending request): ignore rather than stack a
            # second save behind it (cf. node.go reportIgnored path)
            self.pending_snapshot.apply(0, ignored=True)
            return rs
        last_applied = self.sm.last_applied_index()
        if not req.is_exported() and (
            last_applied == self.ss.get_req_snapshot_index()
        ):
            # nothing applied since the last requested snapshot: ignore
            # instead of writing an identical image (cf. node.go:1085-1091)
            self.pending_snapshot.apply(0, ignored=True)
            return rs
        self.ss.set_req_snapshot_index(last_applied)
        self.push_take_snapshot_request(req)
        return rs

    def request_leader_transfer(self, target_id: int) -> None:
        self.pending_leader_transfer.request(target_id)
        self.engine.set_node_ready(self.cluster_id)

    # -------------------------------------------------------- engine: stepping
    def step_node(self) -> Optional[Update]:
        """One protocol step (cf. node.go:1016-1067 stepNode/handleEvents).
        Runs on a step worker; returns an Update to process or None."""
        if self.stopped:
            return None
        with self._mu:
            # finalize any completed snapshot save first: it may install a
            # snapshot record / compact the log the step below reads
            self._process_snapshot_status()
            last_applied = self.sm.last_applied_index()
            # applied cursor feeds campaign eligibility + entry pagination
            # (cf. node.go stepNode -> p.NotifyRaftLastApplied)
            self.peer.notify_raft_last_applied(last_applied)
            self._rate_limited = self.peer.rate_limited()
            # an applied-cursor advance not yet confirmed into an Update is
            # itself an event: without it, the LAST applies of a burst never
            # produce the update whose commit trims them out of the in-mem
            # log (cf. node.go:908-921 getUpdate confirmedIndex,
            # node.go:1030-1034 handleEvents)
            applied_advanced = last_applied != self._confirmed_applied
            has_event = self._handle_events() or applied_advanced
            if not has_event:
                return None
            if not (self.peer.has_update(True) or applied_advanced):
                # still commit the logical clock work
                return None
            ud = self.peer.get_update(True, last_applied)
            self._confirmed_applied = last_applied
            return ud

    def _handle_events(self) -> bool:
        had = False
        had |= self._handle_read_index_requests()
        had |= self._handle_received_messages()
        had |= self._handle_config_change_requests()
        had |= self._handle_proposals()
        had |= self._handle_leader_transfer()
        # always step if the peer accumulated output (e.g. from ticks)
        return had or self.peer.has_update(True) or self.peer.has_entry_to_apply()

    def _handle_proposals(self) -> bool:
        entries = self.incoming_proposals.get()
        if not entries:
            return False
        self.quiesce_mgr.record_activity()
        self.peer.propose_entries(entries)
        return True

    def _handle_read_index_requests(self) -> bool:
        reqs = self.incoming_reads.get()
        if not reqs:
            return False
        self.quiesce_mgr.record_activity()
        ctx = self.pending_read_indexes.next_ctx()
        if self.pending_read_indexes.bind_queued_states(reqs, ctx):
            self.peer.read_index(ctx)
        return True

    def _handle_config_change_requests(self) -> bool:
        if not self._cc_queue:
            return False
        ccs, self._cc_queue = self._cc_queue, []
        for cc, key in ccs:
            self.quiesce_mgr.record_activity()
            self.peer.propose_config_change(cc, key)
        return True

    def _handle_leader_transfer(self) -> bool:
        target = self.pending_leader_transfer.get()
        if target is None:
            return False
        self.peer.request_leader_transfer(target)
        return True

    def _handle_received_messages(self) -> bool:
        msgs, ticks = self.mq.get()
        if ticks > 0:
            # coalesced ticks capped at election timeout (node.go:1152-1159)
            for _ in range(min(ticks, self.config.election_rtt)):
                self._tick()
        had = ticks > 0
        for m in msgs:
            had = True
            if m.type == MessageType.INSTALL_SNAPSHOT:
                self._handle_install_snapshot(m)
            elif m.type == MessageType.REPLICATE and self._snapshot_busy():
                continue  # drop Replicate while snapshotting (node.go:1199)
            elif m.type == MessageType.QUIESCE:
                self.quiesce_mgr.try_enter_quiesce()
            else:
                if not m.type == MessageType.LOCAL_TICK:
                    self.quiesce_mgr.record_activity()
                self.peer.handle(m)
        return had

    def _handle_install_snapshot(self, m: Message) -> None:
        self.quiesce_mgr.record_activity()
        self.peer.handle(m)

    def _tick(self) -> None:
        self.clock.increase_tick()
        # one gate for ALL pendings sharing this clock: should_gc consumes
        # the window, so gating inside each gc() would let the first
        # starve the rest (reads/cc/snapshots would never time out)
        if self.clock.should_gc():
            self.pending_proposals.gc()
            self.pending_read_indexes.gc()
            self.pending_config_change.gc()
            self.pending_snapshot.gc()
            self.gc_batches()
        if self.quiesce_mgr.tick():
            self.peer.quiesced_tick()
        else:
            self.peer.tick()
        self._snapshot_feedback()

    def _snapshot_feedback(self) -> None:
        """Scalar twin of the vector engine's _run_snapshot_feedback (and
        dragonboat's snapshotstatus push delay): a streamed install whose
        receiver dies after the chunks leave the sender produces neither a
        transport failure nor a SNAPSHOT_RECEIVED ack, so the leader's
        remote would sit in RemoteState.SNAPSHOT forever — is_paused()
        blocks replication and no heartbeat response can move it. Count
        how long each remote has been parked in SNAPSHOT; past the retry
        window, feed the core a synthetic rejected SNAPSHOT_STATUS so the
        remote un-parks (-> WAIT) and normal probing resumes."""
        r = getattr(self.peer, "raft", None)
        if r is None or not r.is_leader():
            if self._snap_parked:
                self._snap_parked.clear()
            return
        retry_ticks = max(4 * self.config.election_rtt, 16)
        parked = self._snap_parked
        seen = []
        for group in (r.remotes, r.observers, r.witnesses):
            for nid, rm in group.items():
                if rm.state != RemoteState.SNAPSHOT:
                    continue
                held = parked.get(nid, 0) + 1
                if held > retry_ticks:
                    parked.pop(nid, None)
                    self.mq.add(
                        Message(
                            type=MessageType.SNAPSHOT_STATUS,
                            cluster_id=self.cluster_id,
                            from_=nid,
                            reject=True,
                        )
                    )
                    self.engine.set_node_ready(self.cluster_id)
                else:
                    parked[nid] = held
                    seen.append(nid)
        for nid in list(parked):
            if nid not in seen:
                del parked[nid]

    # ----------------------------------------------- engine: update processing
    def process_dropped(self, ud: Update) -> None:
        for e in ud.dropped_entries:
            self.proposal_dropped(e)
        for ctx in ud.dropped_read_indexes:
            self.pending_read_indexes.dropped(ctx)

    def send_replicate_messages(self, ud: Update) -> None:
        """Replicate messages leave before the local fsync — Raft thesis
        §10.2.1 pipelining (cf. execengine.go:508-516)."""
        for m in ud.messages:
            if m.type == MessageType.REPLICATE:
                m.cluster_id = self.cluster_id
                self._send_message(m)

    def process_raft_update(self, ud: Update) -> None:
        """Post-fsync processing (cf. node.go:975-1000)."""
        if ud.snapshot is not None and not ud.snapshot.is_empty():
            self.log_reader.apply_snapshot(ud.snapshot)
        self.log_reader.append(ud.entries_to_save)
        for m in ud.messages:
            if m.type == MessageType.REPLICATE:
                continue
            m.cluster_id = self.cluster_id
            self._send_message(m)
        if ud.state is not None and not ud.state.is_empty():
            self.log_reader.set_state(ud.state)
        if ud.ready_to_reads:
            # confirmed read contexts release once the SM catches up
            # (cf. node.go:943-948 processReadyToRead)
            self.pending_read_indexes.add_ready_to_read(ud.ready_to_reads)
        self.pending_read_indexes.applied(self.sm.last_applied_index())
        self._save_snapshot_required(ud)

    def apply_raft_update(self, ud: Update) -> None:
        """Queue committed entries for the task workers
        (cf. node.go:967-973 + pushEntries node.go:505-515)."""
        if ud.snapshot is not None and not ud.snapshot.is_empty():
            self._push_install_snapshot(ud.snapshot)
        if not ud.committed_entries:
            return
        now = 0.0
        for e in ud.committed_entries:
            lt = e.lat
            if lt is not None and lt.t_commit == 0.0:
                if not now:
                    now = time.monotonic()
                lt.t_commit = now  # quorum commit observed (sampled entry)
                if lt.trace_id:
                    flight_recorder().record(
                        "quorum_commit", cluster=self.cluster_id,
                        node=self._node_id, trace=lt.trace_id,
                        index=e.index,
                    )
        self.sm.task_queue.add(
            Task(
                cluster_id=self.cluster_id,
                node_id=self._node_id,
                entries=ud.committed_entries,
            )
        )
        self._applied_since_snapshot += len(ud.committed_entries)
        self.engine.set_task_ready(self.cluster_id)

    def commit_raft_update(self, ud: Update) -> None:
        with self._mu:
            self.peer.commit(ud)

    # ------------------------------------------------------- engine: applying
    def handle_task(self, batch, apply) -> bool:
        """Drain apply work on a task worker; returns True if a snapshot
        task needs a snapshot worker (cf. node.go:795). Snapshot tasks land
        in the FSM's per-kind request slots (snapshotstate.go:143-161); a
        task racing an occupied slot goes back to the task queue and
        retries once the worker drains the slot."""
        st = self.sm.handle(batch, apply)
        if st is not None:
            if st.snapshot_requested:
                deposited = self.ss.save_req.set(st)
            else:
                deposited = self.ss.recover_req.set(st)
                if deposited:
                    # Replicate traffic is dropped while the SM rebuilds
                    # (node.go:1199); flag from deposit, not worker pickup
                    self.ss.set_recovering_from_snapshot()
            if not deposited:
                # requeue WITHOUT signalling: run_snapshot_work re-signals
                # task_ready after draining the slot — self-signalling here
                # would hot-spin the task worker for the whole in-flight
                # snapshot
                self.sm.task_queue.add(st)
                return False
            self.engine.set_snapshot_ready(self.cluster_id)
            return True
        return False

    # ------------------------------------------------------- snapshot drivers
    def _has_snapshot_to_recover(self) -> bool:
        if self.snapshotter is None:
            return False
        ss = self.snapshotter.get_most_recent_snapshot()
        return ss is not None and not ss.is_empty()

    def recover_initial_snapshot(self) -> None:
        """Engine init path: install the newest snapshot before stepping
        (cf. getUninitializedNodeTask node.go:1318-1328). Idempotent under
        racing callers (start_cluster thread + step worker)."""
        with self._init_mu:
            if self.initialized.is_set():
                return
            self._recover_initial_snapshot_locked()
            self.initialized.set()

    def _recover_initial_snapshot_locked(self) -> None:
        t = Task(
            cluster_id=self.cluster_id,
            node_id=self._node_id,
            snapshot_available=True,
        )
        idx = self.sm.recover_from_snapshot(t)
        if idx > 0:
            self.peer.notify_raft_last_applied(self.sm.last_applied_index())

    def _push_install_snapshot(self, ss: Snapshot) -> None:
        """A snapshot arrived through the protocol (InstallSnapshot path):
        recover the SM from it (cf. node.go:950-965 processSnapshot)."""
        t = Task(
            cluster_id=self.cluster_id,
            node_id=self._node_id,
            index=ss.index,
            snapshot_available=True,
            init_done=True,
        )
        self.sm.task_queue.add(t)
        self.engine.set_task_ready(self.cluster_id)

    def push_take_snapshot_request(self, req: SSRequest) -> None:
        t = Task(
            cluster_id=self.cluster_id,
            node_id=self._node_id,
            snapshot_requested=True,
            ss_request=req,
        )
        self.sm.task_queue.add(t)
        self.engine.set_task_ready(self.cluster_id)

    def _snapshot_busy(self) -> bool:
        # taking OR recovering: both make concurrent Replicate application
        # unsafe/worthless (cf. node.go:1199)
        return self.ss.busy()

    def _save_snapshot_required(self, ud: Update) -> None:
        """Periodic snapshot trigger by applied-entry count
        (cf. node.go:585-601 saveSnapshotRequired)."""
        se = self.config.snapshot_entries
        if se == 0 or self.snapshotter is None:
            return
        if self._applied_since_snapshot < se:
            return
        if self.ss.taking_snapshot():
            return
        self.ss.set_taking_snapshot()
        self._applied_since_snapshot = 0
        self.push_take_snapshot_request(SSRequest())

    def run_snapshot_work(self) -> None:
        """Executed on a snapshot worker: drain the FSM's request slots and
        any deferred log compaction (cf. execengine.go:227-335 snapshot
        worker mains + snapshotstate.go req slots)."""
        did = False
        task, had = self.ss.save_req.take()
        if had:
            did = True
            self._do_save_snapshot(task.ss_request or SSRequest())
        task, had = self.ss.recover_req.take()
        if had:
            did = True
            self._do_recover_snapshot(task)
        if did:
            # a snapshot task that raced the occupied slot sits requeued in
            # the task queue; wake the task worker now that the slot drained
            self.engine.set_task_ready(self.cluster_id)
        compact_to = self.ss.get_compact_log_to()
        if compact_to > 0:
            # persistent-log compaction is disk IO: it runs HERE, not under
            # the protocol lock where finalization queued it
            # (cf. snapshotstate.go compactLogTo + node.go:849-867)
            self.logdb.remove_entries_to(
                self.cluster_id, self._node_id, compact_to
            )

    def _do_save_snapshot(self, req: SSRequest) -> None:
        """IO half of a save, on the snapshot worker; the result lands in
        the save_completed slot and the step loop finalizes it under the
        protocol lock (_process_snapshot_status) — log-reader mutations
        from this thread would race concurrent steps."""
        self.ss.set_taking_snapshot()
        ss = None
        failed = ignored = False
        try:
            if self.snapshotter is None:
                ignored = True
            else:
                ss, env = self.sm.save_snapshot(req)
                self.snapshotter.commit(ss, req)
        except Exception:
            failed = True
        self.ss.save_completed.put((ss, req, failed, ignored))
        self._notify_snapshot_status()

    def _notify_snapshot_status(self) -> None:
        """Route completed snapshot work back to whichever loop owns this
        node's protocol state (scalar: the step worker; vector override:
        the engine loop)."""
        self.engine.set_node_ready(self.cluster_id)

    def _process_snapshot_status(self) -> None:
        """Finalize completed snapshot work; caller holds the protocol
        lock (cf. node.go processSaveStatus)."""
        for t in self.ss.save_completed.take_all():
            ss, req, failed, ignored = t
            try:
                if ignored or failed:
                    self.pending_snapshot.apply(
                        0, ignored=ignored, failed=failed
                    )
                    continue
                if not req.is_exported():
                    # exported snapshots leave the node's own history
                    # alone: no logdb record was written, so advancing the
                    # log reader / compacting here would delete entries
                    # the node still needs to replay (cf. nodehost.go
                    # exported path)
                    self.log_reader.create_snapshot(ss)
                    self._compact_log(ss, req)
                self.ss.set_snapshot_index(ss.index)
                self.pending_snapshot.apply(ss.index, ignored=False)
            except Exception:
                # a finalization fault (logdb/log-reader IO) must surface
                # as a failed request, not a silent timeout
                self.pending_snapshot.apply(0, ignored=False, failed=True)
            finally:
                self.ss.clear_taking_snapshot()

    def _do_recover_snapshot(self, task: Task) -> None:
        try:
            idx = self.sm.recover_from_snapshot(task)
            if idx > 0:
                ss = self.snapshotter.get_most_recent_snapshot()
                if ss is not None and not ss.is_empty():
                    with self._mu:
                        self.log_reader.apply_snapshot(ss)
                        self.peer.restore_remotes(ss)
                        self.peer.notify_raft_last_applied(
                            self.sm.last_applied_index()
                        )
                self.clear_install_aborted()
        finally:
            self.ss.clear_recovering_from_snapshot()

    def _compact_log(self, ss: Snapshot, req: SSRequest) -> None:
        """Keep compaction_overhead entries behind the snapshot
        (cf. node.go:680-693 + 849-867). Caller holds _mu — the in-memory
        log-reader mutation must be exclusive with protocol steps; the
        persistent-log removal is disk IO and is deferred to a snapshot
        worker through compact_log_to."""
        overhead = (
            req.compaction_overhead
            if req is not None and req.override_compaction
            else self.config.compaction_overhead
        )
        if overhead == 0:
            return
        if ss.index <= overhead:
            return
        compact_to = ss.index - overhead
        try:
            self.log_reader.compact(compact_to)
        except ErrCompacted:
            return  # already compacted past this point: benign
        self.ss.set_compact_log_to(compact_to)
        self.engine.set_snapshot_ready(self.cluster_id)

    # ---------------------------------------------------------------- events
    def _make_raft_event_adapter(self):
        node = self

        class _Adapter:
            def leader_updated(self, cluster_id, node_id, leader_id, term):
                if leader_id != node._leader_id:
                    node._leader_change_tick = node.clock.tick
                node._leader_id = leader_id
                node._current_term = term
                if node.events is not None:
                    node.events.leader_updated(cluster_id, node_id, leader_id, term)

            def __getattr__(self, name):
                # forward the full event vocabulary (campaign_launched,
                # proposal_dropped, ... cf. internal/server/event.go:75-83)
                if node.events is not None:
                    return getattr(node.events, name)

                def noop(*a, **k):
                    return None

                return noop

        return _Adapter()

    def get_leader_id(self):
        with self._mu:
            st = self.peer.local_status()
        return st["leader_id"]

    def local_status(self):
        with self._mu:
            return self.peer.local_status()

    # -------------------------------------------------------------- shutdown
    def close(self) -> None:
        self.stopped = True
        self.incoming_proposals.close()
        self.incoming_reads.close()
        self.mq.close()
        self.pending_proposals.close()
        self.pending_read_indexes.close()
        self.pending_config_change.close()
        self.pending_snapshot.close()
        with self._batch_mu:
            handles = list(self._batches.values())
            self._batches.clear()
        for h in handles:
            h.expire()
        self.sm.offloaded()


__all__ = ["Node"]
