"""Per-node snapshot coordination FSM.

Counterpart of the reference's snapshotState (snapshotstate.go:64-214):
activity flags (taking / recovering / streaming), one REQUEST slot per
kind, a completion queue, and the snapshot/compaction indexes. The
engine's snapshot workers perform the IO-heavy half and post completions;
the step loop consumes them under the node's protocol lock
(cf. node.go processSnapshotStatus), which is what makes log-reader
mutations race-free against concurrent steps. Compaction of the
persistent log is deferred back to a snapshot worker through
compact_log_to (snapshotstate.go:131-141) so disk IO never runs under
the protocol lock.

Slot discipline (cf. snapshotTask snapshotstate.go:28-62): a REQUEST slot
holds at most one task; set() reports a collision and the caller requeues
(the reference panics because its gating guarantees single-occupancy).
Completions ride a small FIFO instead of the reference's one slot: a
second save can finish before the step loop finalizes the first, and a
single slot would silently drop one.

Divergences from snapshotstate.go: stream request/completed slots do not
exist here — snapshot streaming rides the transport's SnapshotLane
(nodehost._async_send_snapshot), which reports through the streaming
counter below; recovery completes inline on the snapshot worker (it
already takes the protocol lock), so no recover-completed slot either.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import List, Optional, Tuple


class TaskSlot:
    """One-slot task mailbox."""

    __slots__ = ("_mu", "_task", "_has")

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._task = None
        self._has = False

    def set(self, task) -> bool:
        """Deposit a task; False when the slot is already occupied."""
        with self._mu:
            if self._has:
                return False
            self._task = task
            self._has = True
            return True

    def take(self) -> Tuple[object, bool]:
        """Remove and return (task, had_task)."""
        with self._mu:
            task, had = self._task, self._has
            self._task = None
            self._has = False
            return task, had

    def occupied(self) -> bool:
        with self._mu:
            return self._has


class TaskQueue:
    """Small FIFO for completion records."""

    __slots__ = ("_mu", "_q")

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._q: deque = deque()

    def put(self, task) -> None:
        with self._mu:
            self._q.append(task)

    def take_all(self) -> List:
        with self._mu:
            out = list(self._q)
            self._q.clear()
            return out

    def occupied(self) -> bool:
        with self._mu:
            return bool(self._q)


class SnapshotState:
    """cf. snapshotstate.go:64-214."""

    __slots__ = (
        "_mu",
        "_taking",
        "_recovering",
        "_streams",
        "_snapshot_index",
        "_req_snapshot_index",
        "_compact_log_to",
        "save_req",
        "recover_req",
        "save_completed",
    )

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._taking = False
        self._recovering = False
        self._streams = 0
        self._snapshot_index = 0
        self._req_snapshot_index = 0
        self._compact_log_to = 0
        self.save_req = TaskSlot()
        self.recover_req = TaskSlot()
        self.save_completed = TaskQueue()

    # ------------------------------------------------------------- flags
    def taking_snapshot(self) -> bool:
        with self._mu:
            return self._taking

    def set_taking_snapshot(self) -> None:
        with self._mu:
            self._taking = True

    def clear_taking_snapshot(self) -> None:
        with self._mu:
            self._taking = False

    def recovering_from_snapshot(self) -> bool:
        with self._mu:
            return self._recovering

    def set_recovering_from_snapshot(self) -> None:
        with self._mu:
            self._recovering = True

    def clear_recovering_from_snapshot(self) -> None:
        with self._mu:
            self._recovering = False

    # streaming is a counter, not a boolean: several transport lanes can
    # stream this node's snapshots to different peers at once
    def streaming_snapshot(self) -> bool:
        with self._mu:
            return self._streams > 0

    def begin_stream(self) -> None:
        with self._mu:
            self._streams += 1

    def end_stream(self) -> None:
        with self._mu:
            self._streams = max(0, self._streams - 1)

    def busy(self) -> bool:
        with self._mu:
            return self._taking or self._recovering

    # ----------------------------------------------------------- indexes
    def set_snapshot_index(self, index: int) -> None:
        with self._mu:
            self._snapshot_index = index

    def get_snapshot_index(self) -> int:
        with self._mu:
            return self._snapshot_index

    def set_req_snapshot_index(self, index: int) -> None:
        with self._mu:
            self._req_snapshot_index = index

    def get_req_snapshot_index(self) -> int:
        with self._mu:
            return self._req_snapshot_index

    def set_compact_log_to(self, index: int) -> None:
        with self._mu:
            self._compact_log_to = max(self._compact_log_to, index)

    def get_compact_log_to(self) -> int:
        """Swap-read: returns the pending compaction point and clears it
        (cf. snapshotstate.go:135-137 atomic.SwapUint64)."""
        with self._mu:
            v = self._compact_log_to
            self._compact_log_to = 0
            return v

    def has_compact_log_to(self) -> bool:
        with self._mu:
            return self._compact_log_to > 0


__all__ = ["SnapshotState", "TaskSlot", "TaskQueue"]
