"""Async request plumbing: pending proposals, reads, config changes,
snapshots, leader transfers.

cf. requests.go:48-1133 — every user request becomes a RequestState with a
completion event; timeouts are enforced by a logical clock advanced on the
NodeHost tick so no per-request timers exist. Proposals are keyed (the key
rides in the entry and comes back from the apply path), ReadIndex requests
batch many user reads under one 128-bit system context.
"""
from __future__ import annotations

import itertools
import os
import struct
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from .client import Session
from .statemachine import Result
from .types import (
    Entry,
    EntryType,
    ConfigChange,
    Membership,
    Snapshot,
    SystemCtx,
)


class RequestError(Exception):
    code = "request error"


class ErrClusterNotFound(RequestError):
    code = "cluster not found"


class ErrClusterNotReady(RequestError):
    code = "cluster not ready"


class ErrClusterClosed(RequestError):
    code = "raft cluster already closed"


class ErrTimeout(RequestError):
    code = "timeout"


class ErrCanceled(RequestError):
    code = "request canceled"


class ErrRejected(RequestError):
    code = "request rejected"


class ErrSystemBusy(RequestError):
    """Overload shed: fail fast, safe to retry. `retry_after_s` is the
    machine-readable backoff hint (0.0 = none); the serving plane's
    typed subclasses (serving.admission.ErrOverloaded family) populate
    it, and serving.retry.call_with_retries honors it as a backoff
    floor — so every ErrSystemBusy anywhere in the stack reads uniformly
    at the client."""

    code = "system is too busy, try again later"
    retry_after_s = 0.0


class ErrSnapshotStreamAborted(ErrSystemBusy):
    """An inbound snapshot-install stream feeding this replica's catch-up
    aborted mid-transfer (receiver crash, sender failure, chunk gap).
    Client ops that gate on the install — linearizable reads waiting for
    the applied index, any op while the group has no reachable leader —
    fail FAST with this instead of burning their whole budget into a
    generic ErrTimeout. Subclasses ErrSystemBusy so
    serving.retry.call_with_retries retries it automatically, honoring
    `retry_after_s` (sized to the raft snapshot-status retry window: when
    the re-streamed install should have landed) as the backoff floor."""

    code = "snapshot install stream aborted, retry later"

    def __init__(self, retry_after_s: float = 0.0):
        super().__init__()
        self.retry_after_s = float(retry_after_s)


class ErrMigrationAborted(ErrSystemBusy):
    """A live group migration (serving/placement.py: leadership transfer
    + streamed-snapshot member swap) was aborted mid-flight — operator
    abort, catch-up timeout, or an admission shed of the migration's own
    bulk-class traffic. The group stays where it was and keeps serving;
    the move itself is what failed, and it is safe to retry once the
    pressure that killed it clears. Subclasses ErrSystemBusy so
    serving.retry.call_with_retries retries it automatically, honoring
    `retry_after_s` (sized by the aborting step: an admission shed
    forwards the shed's own hint, a catch-up timeout suggests one
    snapshot-status window) as the backoff floor."""

    code = "group migration aborted, retry later"

    def __init__(self, retry_after_s: float = 0.0, reason: str = ""):
        super().__init__(reason or self.code)
        self.retry_after_s = float(retry_after_s)
        self.reason = reason


class ErrLeaseExpired(ErrSystemBusy):
    """The lease-only read probe (NodeHost.lease_read) found no live
    leader lease on this replica — expired, revoked by step-down or
    leadership transfer, or suspended by a clock-anomaly report from the
    tick plane. This error is raised ONLY by the explicit lease-only
    probe API; the normal linearizable read path never surfaces it — an
    invalid lease there silently degrades to the ReadIndex quorum round
    (degradation, not danger). Subclasses ErrSystemBusy so
    serving.retry.call_with_retries retries it automatically, honoring
    `retry_after_s` (sized to roughly one heartbeat interval: the next
    quorum heartbeat round is what re-arms the lease) as the backoff
    floor."""

    code = "no live leader lease, read via ReadIndex instead"

    def __init__(self, retry_after_s: float = 0.0, reason: str = ""):
        super().__init__(reason or self.code)
        self.retry_after_s = float(retry_after_s)
        self.reason = reason


class ErrInvalidSession(RequestError):
    code = "invalid session"


class ErrTimeoutTooSmall(RequestError):
    code = "timeout is too small"


class ErrPayloadTooBig(RequestError):
    code = "payload is too big"


class ErrSystemStopped(RequestError):
    code = "system stopped"


# request completion codes (cf. requests.go RequestResultCode)
REQUEST_TIMEOUT = 0
REQUEST_COMPLETED = 1
REQUEST_TERMINATED = 2
REQUEST_REJECTED = 3
REQUEST_DROPPED = 4


@dataclass
class RequestResult:
    code: int = REQUEST_TIMEOUT
    result: Result = field(default_factory=Result)
    snapshot_index: int = 0

    @property
    def completed(self) -> bool:
        return self.code == REQUEST_COMPLETED

    @property
    def timeout(self) -> bool:
        return self.code == REQUEST_TIMEOUT

    @property
    def terminated(self) -> bool:
        return self.code == REQUEST_TERMINATED

    @property
    def rejected(self) -> bool:
        return self.code == REQUEST_REJECTED

    @property
    def dropped(self) -> bool:
        return self.code == REQUEST_DROPPED


# ---------------------------------------------------------------------------
# batch proposals: one completion record per submission
# ---------------------------------------------------------------------------

# Entry.key namespace bit marking batch-tracked proposals: the key encodes
# (batch_id, seq) instead of naming a per-request registry slot, so a
# thousand-proposal batch costs ONE registration and ONE completion event
# instead of a thousand (no referent in the reference — its clients are
# strictly one RequestState per proposal, requests.go:267-329).
BATCH_KEY_BIT = 1 << 62
_BATCH_SEQ_BITS = 24


def make_batch_id(node_id: int, counter: int) -> int:
    """Batch ids are registry keys AND travel in replicated entry keys, so
    they embed the submitting node's identity: a replica applying another
    node's batch entries must not credit a same-numbered batch of its own
    (the per-request path gets this protection from client_id/series_id
    checks; the batch path gets it from the id itself)."""
    return ((node_id & 0xFFFF) << 22) | (counter & 0x3FFFFF)


def make_batch_key(batch_id: int, seq: int) -> int:
    return BATCH_KEY_BIT | (batch_id << _BATCH_SEQ_BITS) | seq


def batch_id_of(key: int) -> int:
    return (key & ~BATCH_KEY_BIT) >> _BATCH_SEQ_BITS


class BatchRequestState:
    """Completion record for one propose_batch_async submission: counts
    applied/dropped proposals and fires a single event when the whole
    batch is accounted for. Thread-safe (engine loop + apply workers +
    the waiting client)."""

    __slots__ = ("batch_id", "n", "completed", "dropped", "deadline",
                 "_event", "_mu")

    def __init__(self, batch_id: int, n: int, deadline: int) -> None:
        self.batch_id = batch_id
        self.n = n
        self.completed = 0
        self.dropped = 0
        self.deadline = deadline
        self._event = threading.Event()
        self._mu = threading.Lock()

    def add_done(self, completed: int = 0, dropped: int = 0) -> None:
        with self._mu:
            self.completed += completed
            self.dropped += dropped
            if self.completed + self.dropped >= self.n:
                self._event.set()

    def expire(self) -> None:
        """Timeout: account every outstanding proposal as dropped."""
        with self._mu:
            rest = self.n - self.completed - self.dropped
            if rest > 0:
                self.dropped += rest
            self._event.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    @property
    def finished(self) -> bool:
        return self._event.is_set()


# guards the callback handoff in RequestState._fire_cb; module-level so
# the per-request fast path (no callback registered) stays lock-free
_cb_fire_mu = threading.Lock()

# sticky proposal-shard assignment per client thread (see
# PendingProposal.propose); module-level so every node's registry spreads
# the same way
_shard_tls = threading.local()
_shard_rr = itertools.count()


class RequestState:
    """One in-flight request (cf. requests.go:267-329). wait() blocks the
    calling thread; the engine thread completes it via notify()."""

    __slots__ = ("key", "client_id", "series_id", "deadline", "_event",
                 "_result", "_cb", "lat")

    def __init__(self) -> None:
        self.key = 0
        self.client_id = 0
        self.series_id = 0
        self.deadline = 0
        self._event = threading.Event()
        self._result: Optional[RequestResult] = None
        self._cb = None
        # sampled-latency timestamp (see trace.LatencySampler): None on
        # the unsampled hot path; Node.read stamps a monotonic float on
        # 1-in-N reads so completion can observe readindex latency
        # (proposals carry their trace on the Entry instead — the same
        # object travels propose -> arena -> commit -> apply)
        self.lat = None

    def notify(self, result: RequestResult) -> None:
        self._result = result
        self._event.set()
        if self._cb is not None:
            self._fire_cb()

    def on_complete(self, cb) -> None:
        """Invoke cb(self) exactly once when the request completes — from
        the completing engine thread, so cb must be brief and non-blocking
        (used by the embedding ABI's event delivery; cf. the reference's
        Event.Set discipline, binding dragonboat.h:377-394). Fires
        immediately if already complete. Callbacks COMPOSE: a second
        registration chains after the first instead of replacing it (the
        latency sampler registers on 1-in-N reads before the caller gets
        the RequestState — a replacing slot would silently drop whichever
        callback came first)."""
        prev = self._cb
        if prev is not None:
            nxt = cb

            def cb(rs, _prev=prev, _nxt=nxt):
                _prev(rs)
                _nxt(rs)

        self._cb = cb
        if self._event.is_set():
            self._fire_cb()

    def _fire_cb(self) -> None:
        with _cb_fire_mu:  # exactly-once between notify and on_complete
            cb, self._cb = self._cb, None
        if cb is not None:
            cb(self)

    def wait(self, timeout: Optional[float] = None) -> RequestResult:
        if not self._event.wait(timeout):
            return RequestResult(code=REQUEST_TIMEOUT)
        return self._result

    def done(self) -> bool:
        return self._event.is_set()

    @property
    def result(self) -> Optional[RequestResult]:
        return self._result


class LogicalClock:
    """Tick-driven clock for request GC (cf. requests.go:223-241)."""

    __slots__ = ("tick", "last_gc_time", "gc_tick")

    GC_TICK = 2

    def __init__(self) -> None:
        self.tick = 0
        self.last_gc_time = 0

    def increase_tick(self) -> None:
        self.tick += 1

    def should_gc(self) -> bool:
        if self.tick - self.last_gc_time >= self.GC_TICK:
            self.last_gc_time = self.tick
            return True
        return False


class _ProposalShard:
    """Keyed in-flight proposals, one lock's worth
    (cf. proposalShard requests.go:983-1133)."""

    def __init__(self, clock: LogicalClock, offset: int = 0,
                 stride: int = 1) -> None:
        self._mu = threading.Lock()
        self._pending: Dict[int, RequestState] = {}
        self._clock = clock
        # keys from this shard are ≡ offset (mod stride), so completions
        # route back by key alone; the random base has its low 16 bits
        # clear, keeping the congruence intact. Bits 61+ stay clear so a
        # per-request key can never collide with the BATCH_KEY_BIT
        # namespace (batch-tracked proposals route by batch id instead).
        self._key_seq = itertools.count(
            ((int.from_bytes(os.urandom(6), "big") << 16)
             & ((1 << 61) - 1)) + offset,
            stride,
        )
        self.stopped = False

    def _make_request(
        self, session: Session, cmd: bytes, deadline: int
    ) -> Tuple[RequestState, Entry]:
        """One registration record; single and batch submission MUST build
        identical requests (shared so they cannot drift)."""
        rs = RequestState()
        rs.key = next(self._key_seq)
        rs.client_id = session.client_id
        rs.series_id = session.series_id
        rs.deadline = deadline
        entry = Entry(
            key=rs.key,
            client_id=session.client_id,
            series_id=session.series_id,
            responded_to=session.responded_to,
            cmd=cmd,
        )
        return rs, entry

    def propose(
        self, session: Session, cmd: bytes, timeout_ticks: int
    ) -> Tuple[RequestState, Entry]:
        if timeout_ticks < 1:
            raise ErrTimeoutTooSmall()
        rs, entry = self._make_request(
            session, cmd, self._clock.tick + timeout_ticks
        )
        with self._mu:
            if self.stopped:
                raise ErrClusterClosed()
            self._pending[rs.key] = rs
        return rs, entry

    def propose_batch(
        self, session: Session, cmds, timeout_ticks: int
    ) -> Tuple[List[RequestState], List[Entry]]:
        """Register a whole batch under ONE lock acquisition — the
        per-proposal lock round-trip is the submission-path hot spot."""
        if timeout_ticks < 1:
            raise ErrTimeoutTooSmall()
        deadline = self._clock.tick + timeout_ticks
        pairs = [self._make_request(session, cmd, deadline) for cmd in cmds]
        with self._mu:
            if self.stopped:
                raise ErrClusterClosed()
            for rs, _ in pairs:
                self._pending[rs.key] = rs
        return [rs for rs, _ in pairs], [e for _, e in pairs]

    def applied(
        self, key: int, client_id: int, series_id: int, result: Result,
        rejected: bool,
    ) -> None:
        """Apply-path notification (cf. requests.go:1086-1103)."""
        with self._mu:
            rs = self._pending.get(key)
            if rs is None:
                return
            if rs.client_id != client_id or rs.series_id != series_id:
                return
            del self._pending[key]
        code = REQUEST_REJECTED if rejected else REQUEST_COMPLETED
        rs.notify(RequestResult(code=code, result=result))

    def dropped(self, key: int) -> None:
        with self._mu:
            rs = self._pending.pop(key, None)
        if rs is not None:
            rs.notify(RequestResult(code=REQUEST_DROPPED))

    def close(self) -> None:
        with self._mu:
            self.stopped = True
            pending = list(self._pending.values())
            self._pending.clear()
        for rs in pending:
            rs.notify(RequestResult(code=REQUEST_TERMINATED))

    def gc(self) -> None:
        """Sweep expired requests. Unconditional: the caller owns the
        cadence (one should_gc() check per clock window covers every
        Pending* sharing that clock — gating here let the first callee
        consume the window and starve the rest)."""
        now = self._clock.tick
        with self._mu:
            expired = [k for k, rs in self._pending.items() if rs.deadline < now]
            states = [self._pending.pop(k) for k in expired]
        for rs in states:
            rs.notify(RequestResult(code=REQUEST_TIMEOUT))

    def has_pending(self) -> bool:
        return bool(self._pending)

    def pending_count(self) -> int:
        """Lock-free in-flight count (backpressure probe; a torn read
        costs one stale sample, never a wrong decision stream)."""
        return len(self._pending)


class PendingProposal:
    """Sharded in-flight proposal registry (cf. pendingProposal
    requests.go:903-981: 16 shards keyed by random key to cut mutex
    contention). Even under the GIL the single proposal lock is contended
    — every client thread and the engine's apply path serialize on it —
    so proposals shard by submitting thread and completions route back by
    key congruence (shard i issues keys ≡ i mod SHARDS)."""

    SHARDS = 8

    def __init__(self, clock: LogicalClock) -> None:
        self._shards = [
            _ProposalShard(clock, offset=i, stride=self.SHARDS)
            for i in range(self.SHARDS)
        ]

    def _thread_shard(self) -> "_ProposalShard":
        # thread affinity: each client thread gets a sticky shard index
        # (round-robin at first use — thread idents are pointer-aligned,
        # so ident % SHARDS would collide), keeping concurrent submitters
        # on different locks with no per-propose shared routing state
        idx = getattr(_shard_tls, "idx", None)
        if idx is None:
            idx = _shard_tls.idx = next(_shard_rr)
        return self._shards[idx % self.SHARDS]

    def propose(
        self, session: Session, cmd: bytes, timeout_ticks: int
    ) -> Tuple[RequestState, Entry]:
        return self._thread_shard().propose(session, cmd, timeout_ticks)

    def propose_batch(
        self, session: Session, cmds, timeout_ticks: int
    ) -> Tuple[List[RequestState], List[Entry]]:
        return self._thread_shard().propose_batch(
            session, cmds, timeout_ticks
        )

    def applied(
        self, key: int, client_id: int, series_id: int, result: Result,
        rejected: bool,
    ) -> None:
        self._shards[key % self.SHARDS].applied(
            key, client_id, series_id, result, rejected
        )

    def dropped(self, key: int) -> None:
        self._shards[key % self.SHARDS].dropped(key)

    def close(self) -> None:
        for s in self._shards:
            s.close()

    def gc(self) -> None:
        for s in self._shards:
            s.gc()

    def has_pending(self) -> bool:
        return any(s.has_pending() for s in self._shards)

    def pending_count(self) -> int:
        """Total in-flight proposals across shards (backpressure probe)."""
        return sum(s.pending_count() for s in self._shards)


class PendingReadIndex:
    """ReadIndex batching: many user reads share one system context
    (cf. requests.go:654-886)."""

    def __init__(self, clock: LogicalClock) -> None:
        self._mu = threading.Lock()
        self._clock = clock
        # reads queued but not yet bound to a ctx
        self._queued: List[RequestState] = []
        # ctx -> (bound reads, ready index or None)
        self._batches: Dict[SystemCtx, List[RequestState]] = {}
        self._ready: List[Tuple[SystemCtx, int]] = []  # confirmed, awaiting apply
        self._ctx_seq = itertools.count(1)
        self.stopped = False

    def read(self, timeout_ticks: int) -> RequestState:
        if timeout_ticks < 1:
            raise ErrTimeoutTooSmall()
        rs = RequestState()
        rs.deadline = self._clock.tick + timeout_ticks
        with self._mu:
            if self.stopped:
                raise ErrClusterClosed()
            self._queued.append(rs)
        return rs

    def has_queued(self) -> bool:
        return bool(self._queued)

    def has_pending(self) -> bool:
        return bool(self._queued or self._batches)

    def pending_count(self) -> int:
        """Queued + bound-but-unreleased reads (backpressure probe;
        lock-free, torn reads cost one stale sample)."""
        return len(self._queued) + sum(
            len(b) for b in self._batches.values()
        )

    def has_ctx(self, ctx: SystemCtx) -> bool:
        """Whether a bound batch is still alive for ctx (engine-side
        routing entries are GC'd once their batch times out or completes)."""
        return ctx in self._batches

    def next_ctx(self) -> SystemCtx:
        return SystemCtx(
            low=next(self._ctx_seq),
            high=int.from_bytes(os.urandom(8), "big") | 1,
        )

    def bind_queued(self, ctx: SystemCtx) -> bool:
        """Engine: bind all queued reads to ctx before Peer.read_index(ctx)
        (cf. nextReadIndexCtx/peepNextCtx requests.go:732-778)."""
        with self._mu:
            if not self._queued:
                return False
            self._batches[ctx] = self._queued
            self._queued = []
        return True

    def bind_queued_states(self, states: List[RequestState], ctx: SystemCtx) -> bool:
        """Bind an explicit batch popped from the node's read queue; the
        states were registered in _queued by read() and move to the ctx."""
        if not states:
            return False
        with self._mu:
            qs = set(map(id, states))
            self._queued = [rs for rs in self._queued if id(rs) not in qs]
            live = [rs for rs in states if not rs.done()]
            if not live:
                return False
            self._batches[ctx] = live
        return True

    def add_ready_to_read(self, ready: List) -> None:
        """Update.ready_to_reads arrived (cf. addReadyToRead)."""
        if not ready:
            return
        with self._mu:
            for r in ready:
                if r.system_ctx in self._batches:
                    self._ready.append((r.system_ctx, r.index))

    def applied(self, applied_index: int) -> None:
        """SM applied up to applied_index: release confirmed reads whose
        read index is covered (cf. requests.go:798-858)."""
        done: List[Tuple[List[RequestState], int]] = []
        with self._mu:
            if not self._ready:
                return
            remaining = []
            for ctx, idx in self._ready:
                if idx <= applied_index:
                    states = self._batches.pop(ctx, [])
                    done.append((states, idx))
                else:
                    remaining.append((ctx, idx))
            self._ready = remaining
        for states, _ in done:
            for rs in states:
                rs.notify(RequestResult(code=REQUEST_COMPLETED))

    def dropped(self, ctx: SystemCtx) -> None:
        with self._mu:
            states = self._batches.pop(ctx, [])
        for rs in states:
            rs.notify(RequestResult(code=REQUEST_DROPPED))

    def close(self) -> None:
        with self._mu:
            self.stopped = True
            states = list(self._queued)
            self._queued = []
            for batch in self._batches.values():
                states.extend(batch)
            self._batches.clear()
            self._ready = []
        for rs in states:
            rs.notify(RequestResult(code=REQUEST_TERMINATED))

    def gc(self) -> None:
        """Sweep expired requests. Unconditional: the caller owns the
        cadence (one should_gc() check per clock window covers every
        Pending* sharing that clock — gating here let the first callee
        consume the window and starve the rest)."""
        now = self._clock.tick
        expired: List[RequestState] = []
        with self._mu:
            keep = []
            for rs in self._queued:
                (expired if rs.deadline < now else keep).append(rs)
            self._queued = keep
            for ctx in list(self._batches):
                batch = self._batches[ctx]
                live = [rs for rs in batch if rs.deadline >= now]
                expired.extend(rs for rs in batch if rs.deadline < now)
                if live:
                    self._batches[ctx] = live
                else:
                    del self._batches[ctx]
                    self._ready = [(c, i) for c, i in self._ready if c != ctx]
        for rs in expired:
            rs.notify(RequestResult(code=REQUEST_TIMEOUT))


class _SingleSlotPending:
    """Base for config-change / snapshot / transfer requests: at most one
    outstanding request per node (cf. pendingConfigChange requests.go:388-393)."""

    def __init__(self, clock: LogicalClock) -> None:
        self._mu = threading.Lock()
        self._clock = clock
        self._pending: Optional[RequestState] = None
        self._key_seq = itertools.count(1)
        self.stopped = False

    def _request(self, timeout_ticks: int) -> RequestState:
        if timeout_ticks < 1:
            raise ErrTimeoutTooSmall()
        rs = RequestState()
        rs.key = next(self._key_seq)
        rs.deadline = self._clock.tick + timeout_ticks
        with self._mu:
            if self.stopped:
                raise ErrClusterClosed()
            if self._pending is not None:
                raise ErrSystemBusy()
            self._pending = rs
        return rs

    def _take(self, key: Optional[int] = None) -> Optional[RequestState]:
        with self._mu:
            rs = self._pending
            if rs is None:
                return None
            if key is not None and rs.key != key:
                return None
            self._pending = None
        return rs

    def close(self) -> None:
        with self._mu:
            self.stopped = True
            rs = self._pending
            self._pending = None
        if rs is not None:
            rs.notify(RequestResult(code=REQUEST_TERMINATED))

    def gc(self) -> None:
        """Sweep expired requests. Unconditional: the caller owns the
        cadence (one should_gc() check per clock window covers every
        Pending* sharing that clock — gating here let the first callee
        consume the window and starve the rest)."""
        now = self._clock.tick
        with self._mu:
            rs = self._pending
            if rs is None or rs.deadline >= now:
                return
            self._pending = None
        rs.notify(RequestResult(code=REQUEST_TIMEOUT))

    def has_pending(self) -> bool:
        return self._pending is not None


class PendingConfigChange(_SingleSlotPending):
    def request(
        self, cc: ConfigChange, timeout_ticks: int
    ) -> Tuple[RequestState, ConfigChange, int]:
        rs = self._request(timeout_ticks)
        return rs, cc, rs.key

    def apply(self, key: int, rejected: bool) -> None:
        rs = self._take(key)
        if rs is not None:
            code = REQUEST_REJECTED if rejected else REQUEST_COMPLETED
            rs.notify(RequestResult(code=code))

    def dropped(self, key: int) -> None:
        rs = self._take(key)
        if rs is not None:
            rs.notify(RequestResult(code=REQUEST_DROPPED))


class PendingSnapshot(_SingleSlotPending):
    def request(self, req, timeout_ticks: int) -> Tuple[RequestState, object]:
        rs = self._request(timeout_ticks)
        return rs, req

    def apply(self, index: int, ignored: bool, failed: bool = False) -> None:
        rs = self._take()
        if rs is None:
            return
        if ignored or failed:
            rs.notify(RequestResult(code=REQUEST_REJECTED))
        else:
            rs.notify(
                RequestResult(code=REQUEST_COMPLETED, snapshot_index=index)
            )


class PendingLeaderTransfer:
    """cf. requests.go:402-431; completion is observed via leadership
    change events rather than an apply callback."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._target: Optional[int] = None

    def request(self, target: int) -> None:
        with self._mu:
            if self._target is not None:
                raise ErrSystemBusy()
            self._target = target

    def get(self) -> Optional[int]:
        with self._mu:
            t = self._target
            self._target = None
            return t

    def peek(self) -> bool:
        return self._target is not None


__all__ = [
    "RequestError",
    "ErrClusterNotFound",
    "ErrClusterNotReady",
    "ErrClusterClosed",
    "ErrTimeout",
    "ErrCanceled",
    "ErrRejected",
    "ErrSystemBusy",
    "ErrMigrationAborted",
    "ErrLeaseExpired",
    "ErrInvalidSession",
    "ErrTimeoutTooSmall",
    "ErrPayloadTooBig",
    "ErrSystemStopped",
    "REQUEST_TIMEOUT",
    "REQUEST_COMPLETED",
    "REQUEST_TERMINATED",
    "REQUEST_REJECTED",
    "REQUEST_DROPPED",
    "RequestResult",
    "RequestState",
    "BatchRequestState",
    "BATCH_KEY_BIT",
    "make_batch_key",
    "batch_id_of",
    "LogicalClock",
    "PendingProposal",
    "PendingReadIndex",
    "PendingConfigChange",
    "PendingSnapshot",
    "PendingLeaderTransfer",
]
