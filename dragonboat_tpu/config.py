"""Configuration for Raft groups and NodeHost instances.

Mirrors the three-tier config system of the reference (cf. config/config.go:60-169
for the per-group Config, config/config.go:211-307 for NodeHostConfig) with the
same validation rules, plus TPU-engine specific knobs (EngineConfig) that have
no referent in the Go implementation.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from .types import CompressionType


class ConfigError(ValueError):
    pass


@dataclass
class Config:
    """Per-Raft-group configuration (cf. config/config.go:60-169)."""

    node_id: int = 0
    cluster_id: int = 0
    check_quorum: bool = False
    election_rtt: int = 0
    heartbeat_rtt: int = 0
    snapshot_entries: int = 0
    compaction_overhead: int = 0
    ordered_config_change: bool = False
    max_in_mem_log_size: int = 0
    snapshot_compression_type: CompressionType = CompressionType.NO_COMPRESSION
    entry_compression_type: CompressionType = CompressionType.NO_COMPRESSION
    is_observer: bool = False
    is_witness: bool = False
    quiesce: bool = False
    # Pre-vote (Raft thesis 9.6): before a real campaign the replica runs
    # a non-disruptive poll at term+1 — the prospective candidate's term
    # and the voters' terms/votes stay untouched until a quorum confirms
    # the election could be won. Stops a rejoining/partition-healed
    # replica from bumping a stable quorum's term. Off by default: the
    # False path is bit-identical to the pre-knob protocol.
    pre_vote: bool = False
    # Leader leases: a leader that heard heartbeat acks from a quorum
    # within one heartbeat round serves linearizable reads LOCALLY (no
    # ReadIndex quorum round-trip) until the lease expires. The lease is
    # bounded strictly below the minimum randomized election timeout
    # minus the skew margin (lease_margin_rtt), so no rival can win an
    # election while a live lease could still serve reads — provided
    # host clocks drift less than the margin per election window; the
    # ClockPlane chaos apparatus (faults.py) attacks exactly that
    # assumption and the watchdog-detected clock-anomaly path revokes
    # the lease rather than trusting it. Off by default: the False path
    # is bit-identical to the pre-knob protocol, and an expired/revoked
    # lease always falls back to the ReadIndex path (degradation, not
    # danger).
    lease_read: bool = False
    # Skew margin in RTT ticks subtracted from the lease lifetime:
    # lease duration = election_rtt - lease_margin_rtt, granted from
    # the quorum round's START tick. 0 = auto (one heartbeat_rtt).
    # Must leave a positive lease: lease_margin_rtt < election_rtt -
    # heartbeat_rtt (the grant lags the round start by up to one
    # heartbeat round-trip).
    lease_margin_rtt: int = 0

    def validate(self) -> None:
        # cf. config/config.go:176-208 Validate
        if self.node_id == 0:
            raise ConfigError("invalid NodeID, it must be >= 1")
        if self.heartbeat_rtt == 0:
            raise ConfigError("HeartbeatRTT must be > 0")
        if self.election_rtt == 0:
            raise ConfigError("ElectionRTT must be > 0")
        if self.election_rtt <= 2 * self.heartbeat_rtt:
            raise ConfigError(
                "invalid election rtt, ElectionRTT must be > 2 * HeartbeatRTT"
            )
        if self.max_in_mem_log_size > 0 and self.max_in_mem_log_size < 64:
            raise ConfigError("MaxInMemLogSize is too small")
        if self.is_witness and self.snapshot_entries > 0:
            raise ConfigError("witness node can not take snapshot")
        if self.is_witness and self.is_observer:
            raise ConfigError("witness node can not be an observer")
        if self.lease_margin_rtt < 0:
            raise ConfigError("LeaseMarginRTT must be >= 0")
        if self.lease_read:
            if self.is_witness or self.is_observer:
                raise ConfigError(
                    "witness/observer node can not serve lease reads"
                )
            margin = self.lease_margin_rtt or self.heartbeat_rtt
            if margin >= self.election_rtt - self.heartbeat_rtt:
                raise ConfigError(
                    "invalid lease margin, LeaseMarginRTT must be < "
                    "ElectionRTT - HeartbeatRTT or the lease never opens"
                )

    def lease_margin_ticks(self) -> int:
        """The effective skew margin (ticks) a lease grant subtracts:
        the configured LeaseMarginRTT, or one heartbeat RTT when auto."""
        return self.lease_margin_rtt or self.heartbeat_rtt

    def get_max_in_mem_log_size(self) -> int:
        if self.max_in_mem_log_size == 0:
            return 2**63 - 1
        return self.max_in_mem_log_size


@dataclass
class EngineConfig:
    """TPU batched-engine knobs; no referent in the reference implementation.

    The vectorized engine advances all groups in a fixed-capacity tensor
    program; these values bound the static shapes of that program. Larger
    values raise per-step HBM footprint but amortize kernel-launch overhead
    over more protocol work.
    """

    # "vector" = the device-kernel engine (engine/vector.py) advancing all
    # groups in one compiled step — the TPU-native flagship and the
    # default; "scalar" = per-group Python Peer stepping
    # (engine/execengine.py), kept as the portable fallback/oracle.
    kind: str = "vector"
    # Shard the engine's (G, ...) state over every visible jax device
    # (jax.sharding.Mesh along the group axis). Groups are independent
    # Raft instances, so at steps_per_sync=1 the kernel partitions with
    # no cross-device collectives on the hot path. Composed with
    # steps_per_sync>1 the inter-step router exchanges candidate
    # messages across shards inside the launch (Pallas async remote DMA
    # ring on TPU, XLA all-gather elsewhere; DBTPU_PALLAS_ROUTE=0 forces
    # the collective) so co-hosted replicas on different chips still
    # talk without the host. max_groups is rounded up to a device
    # multiple; the round-up is stamped in step_stats
    # (padded_groups/mesh_devices) and ghost lanes are never allocated.
    shard_over_mesh: bool = False
    # Max Raft groups per NodeHost; the G dimension of the kernel tensors.
    # (Default sized for fast bring-up; large fleets raise it explicitly.)
    max_groups: int = 128
    # Max peers per group (incl. self); the P dimension.
    max_peers: int = 8
    # Device-resident log window per group (entries of (term) metadata).
    log_window: int = 256
    # Max inbound protocol messages consumed per group per kernel step.
    inbox_depth: int = 8
    # Max outstanding ReadIndex system contexts per group on device.
    readindex_depth: int = 4
    # Max proposal batches appended per group per step.
    proposal_lanes: int = 1
    # How many protocol micro-steps (inbox drain rounds) per kernel launch.
    micro_steps: int = 1
    # Max entries carried by one inbox row / Replicate message. The kernel's
    # ring-slot scatter is O(G*W) regardless of this value, so raising it
    # widens per-step ingestion at the cost of inbox transfer size only.
    max_entries_per_msg: int = 8
    # Device-resident multi-step: K protocol steps per kernel launch.
    # At K=1 (default) the engine runs the classic one-step loop,
    # bit-identical to every release before the knob existed. At K>1 the
    # step body runs under a lax.scan and co-hosted replica traffic
    # (Replicate/acks/heartbeats/votes between lanes of one shared core)
    # is routed ON DEVICE between inner steps — zero host Message objects
    # for shared-core traffic — while host-only work (WAL save, SM apply,
    # client notify, cross-host sends) accumulates in per-step output
    # slots and drains once per super-step: one kernel dispatch + ONE
    # _fetch_output device sync per K protocol steps, and one merged
    # fsync barrier per window. Trade-off: host events (proposals,
    # reads, ticks) enter only at super-step boundaries, so client
    # completion latency grows with K while dispatch/fetch host wall
    # shrinks by ~K. K must be a static int (it is compiled into the
    # scan length). Composes with shard_over_mesh: the sharded K-step
    # kernel routes cross-shard lane traffic device-to-device between
    # inner steps and stays bit-identical to the unsharded reference.
    steps_per_sync: int = 1
    # Pipeline the engine loop: dispatch kernel step t, then decode step
    # t-1's output while the device computes. Removes the device wait from
    # the loop's critical path (a ~2x step rate on accelerators, where the
    # wait is real idle time; on the cpu backend the "wait" is the host
    # computing the kernel, so there is nothing to reclaim and the extra
    # step of latency only hurts). None = auto: on for accelerators, off
    # for cpu. Costs one extra step of pack staleness, which the window
    # throttle accounts for.
    overlap_decode: "Optional[bool]" = None
    # Stage-profiler sampling for the vector engine hot loop: 0 = sparse
    # default (1 in 32 iterations — steady-state cost is two clock reads
    # per stage only on sampled iterations), 1 = record every step (full
    # stage timings; benches and debugging), N>1 = sample 1/N.
    profile_sample_ratio: int = 0
    # Per-step cap on coalesced tick backlogs after an engine loop stall
    # (cold compile, CPU contention between co-scheduled loops). Backlog
    # beyond the cap is SHED, not deferred: a stall compresses into at
    # most this many logical ticks per step and the rest of the wall-
    # clock time is simply not charged to timers — which is what keeps
    # the randomized election-timer spread intact (the old election-RTT
    # cap charged a whole election timeout in one step, synchronizing
    # every follower's timeout into split-vote storms). Tick-denominated
    # timeouts therefore stretch across stalls by design. 0 = auto: each
    # lane's heartbeat RTT; never exceeds a lane's election RTT.
    max_catchup_ticks: int = 0
    # Tick-fairness watchdog yield threshold in milliseconds: an engine
    # loop iteration longer than this yields the CPU to co-scheduled peer
    # loops it starved (see engine/fairness.py). None = auto
    # (max(4 tick periods, 20ms)); 0 disables enforcement (the starvation
    # gauge keeps measuring either way).
    fairness_yield_ms: "Optional[float]" = None
    # Co-hosted engine sharing: NodeHosts in one process constructed with
    # the same non-None scope string share ONE VectorEngine device state, so
    # all their replicas advance in a single kernel step and messages
    # between them short-circuit the transport (the TPU-native deployment
    # shape: one engine per accelerator host, many NodeHost replicas on it).
    share_scope: "Optional[str]" = None


@dataclass
class NodeHostConfig:
    """Per-process configuration (cf. config/config.go:211-307)."""

    deployment_id: int = 0
    wal_dir: str = ""
    nodehost_dir: str = ""
    rtt_millisecond: int = 0
    raft_address: str = ""
    listen_address: str = ""
    mutual_tls: bool = False
    ca_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    max_send_queue_size: int = 0
    max_receive_queue_size: int = 0
    logdb_factory: Optional[Callable] = None
    raft_rpc_factory: Optional[Callable] = None
    enable_metrics: bool = False
    raft_event_listener: Optional[object] = None
    system_event_listener: Optional[object] = None
    max_snapshot_send_bytes_per_second: int = 0
    max_snapshot_recv_bytes_per_second: int = 0
    # outbound snapshot stream caps (cf. lane.go:40-237 + StreamConnections
    # config.go:299-306): total concurrent lanes and per-target lanes; a
    # request over either cap fails fast through the snapshot-status
    # feedback path instead of queuing an unbounded thread
    max_snapshot_connections: int = 8
    max_snapshot_lanes_per_target: int = 2
    engine: EngineConfig = field(default_factory=EngineConfig)

    def validate(self) -> None:
        # cf. config/config.go:309-345 Validate
        if self.rtt_millisecond == 0:
            raise ConfigError("invalid RTTMillisecond")
        if not _is_valid_address(self.raft_address):
            raise ConfigError("invalid NodeHost address")
        if self.listen_address and not _is_valid_address(self.listen_address):
            raise ConfigError("invalid ListenAddress")
        if self.mutual_tls:
            if not self.ca_file:
                raise ConfigError("CA file not specified")
            if not self.cert_file:
                raise ConfigError("cert file not specified")
            if not self.key_file:
                raise ConfigError("key file not specified")
        if 0 < self.max_send_queue_size < 64:
            raise ConfigError("MaxSendQueueSize value is too small")
        if 0 < self.max_receive_queue_size < 64:
            raise ConfigError("MaxReceiveQueueSize value is too small")

    def get_listen_address(self) -> str:
        return self.listen_address or self.raft_address


def _is_valid_address(addr: str) -> bool:
    if not addr or ":" not in addr:
        return False
    host, _, port = addr.rpartition(":")
    if not host:
        return False
    try:
        p = int(port)
    except ValueError:
        return False
    return 0 < p < 65536
