"""JAX backend environment guards shared by tests, bench, and driver hooks.

The environment auto-imports jax via a sitecustomize hook and registers an
'axon' TPU-tunnel backend whose client creation can hang when the tunnel is
busy. The plugin monkeypatches xla_bridge._get_backend_uncached, so setting
JAX_PLATFORMS=cpu alone does NOT prevent the tunnel client from being
initialized — the factory must be dropped before any backend init.
"""
from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def pin_cpu(n_devices: int | None = None) -> None:
    """Pin jax to the cpu platform and drop the axon backend factory.

    Must run before any jax backend is initialized. When ``n_devices`` is
    given, (re)sets the host-platform virtual device count so a stale value
    from the environment cannot undersize the mesh.
    """
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if _COUNT_FLAG in flags:
            flags = re.sub(rf"{_COUNT_FLAG}=\d+", f"{_COUNT_FLAG}={n_devices}", flags)
        else:
            flags = f"{flags} {_COUNT_FLAG}={n_devices}".strip()
        os.environ["XLA_FLAGS"] = flags

    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb

        initialized = _xb.backends_are_initialized()
    except Exception:  # pragma: no cover - internal layout changed
        return
    if initialized:
        raise RuntimeError(
            "pin_cpu() called after a JAX backend was initialized; the cpu "
            "pin and device-count flags cannot take effect. Call it before "
            "any jax.devices()/jit dispatch in the process."
        )
    try:
        _xb._backend_factories.pop("axon", None)
    except Exception:  # pragma: no cover - internal layout changed
        pass


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Enable JAX's persistent compilation cache for this process.

    The engine's step kernel costs seconds of XLA compile per distinct
    KernelConfig; every fresh process (each pytest run, each bench config,
    the driver's verify loop) pays it again from scratch. The on-disk
    cache makes the second process start warm (measured ~6.4s -> ~1.9s
    for the default shape on a 2-core cpu box). Entry points opt in —
    library code never mutates global jax config. Safe to call more than
    once; failures (read-only FS, old jax) degrade to uncached compiles.
    """
    path = cache_dir or os.environ.get(
        "DBTPU_COMPILE_CACHE_DIR",
        os.path.join(
            os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
            "dragonboat-tpu-xla",
        ),
    )
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # pragma: no cover - cache is best-effort
        pass


def maybe_pin_cpu() -> None:
    """pin_cpu() iff the process was asked for the cpu platform via
    JAX_PLATFORMS=cpu — the one-line guard every cpu-capable entry point
    (bench, examples, the embedding glue) must run before anything can
    initialize jax. Raises pin_cpu's RuntimeError if a backend already
    initialized: silently proceeding would leave the axon tunnel factory
    registered, which is exactly the hang this guard exists to prevent."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        pin_cpu()
