"""Pluggable logging indirection (cf. reference logger/logger.go:25-147).

The reference routes every package's logging through an ILogger factory so
embedding applications can redirect it. Here the same seam wraps stdlib
logging: `set_logger_factory` swaps the backend for every named package
logger already handed out (the reference's SetLoggerFactory has the same
retroactive behavior via its wrapper indirection).
"""
from __future__ import annotations

import logging
import threading
from typing import Callable, Dict

CRITICAL = logging.CRITICAL
ERROR = logging.ERROR
WARNING = logging.WARNING
INFO = logging.INFO
DEBUG = logging.DEBUG


class ILogger:
    """cf. logger/logger.go:47 ILogger."""

    def set_level(self, level: int) -> None:
        raise NotImplementedError

    def debugf(self, fmt: str, *args) -> None:
        raise NotImplementedError

    def infof(self, fmt: str, *args) -> None:
        raise NotImplementedError

    def warningf(self, fmt: str, *args) -> None:
        raise NotImplementedError

    def errorf(self, fmt: str, *args) -> None:
        raise NotImplementedError

    def panicf(self, fmt: str, *args) -> None:
        raise NotImplementedError


class StdLogger(ILogger):
    """Default backend over the stdlib logging module
    (the capnslog equivalent, cf. logger/capnslogger.go)."""

    def __init__(self, pkg: str) -> None:
        self._log = logging.getLogger(f"dragonboat_tpu.{pkg}")

    def set_level(self, level: int) -> None:
        self._log.setLevel(level)

    def debugf(self, fmt: str, *args) -> None:
        self._log.debug(fmt, *args)

    def infof(self, fmt: str, *args) -> None:
        self._log.info(fmt, *args)

    def warningf(self, fmt: str, *args) -> None:
        self._log.warning(fmt, *args)

    def errorf(self, fmt: str, *args) -> None:
        self._log.error(fmt, *args)

    def panicf(self, fmt: str, *args) -> None:
        msg = fmt % args if args else fmt
        self._log.critical(msg)
        raise RuntimeError(msg)


class _Wrapped(ILogger):
    """Stable handle whose backend can be swapped after the fact."""

    def __init__(self, pkg: str, backend: ILogger) -> None:
        self._pkg = pkg
        self._backend = backend

    def _swap(self, backend: ILogger) -> None:
        self._backend = backend

    def set_level(self, level: int) -> None:
        self._backend.set_level(level)

    def debugf(self, fmt: str, *args) -> None:
        self._backend.debugf(fmt, *args)

    def infof(self, fmt: str, *args) -> None:
        self._backend.infof(fmt, *args)

    def warningf(self, fmt: str, *args) -> None:
        self._backend.warningf(fmt, *args)

    def errorf(self, fmt: str, *args) -> None:
        self._backend.errorf(fmt, *args)

    def panicf(self, fmt: str, *args) -> None:
        self._backend.panicf(fmt, *args)


_mu = threading.Lock()
_factory: Callable[[str], ILogger] = StdLogger
_loggers: Dict[str, _Wrapped] = {}


def get_logger(pkg: str) -> ILogger:
    """Package-level logger; survives later set_logger_factory calls."""
    with _mu:
        w = _loggers.get(pkg)
        if w is None:
            w = _Wrapped(pkg, _factory(pkg))
            _loggers[pkg] = w
        return w


def set_logger_factory(factory: Callable[[str], ILogger]) -> None:
    """cf. logger.SetLoggerFactory — swaps the backend of every logger,
    including ones already handed out."""
    global _factory
    with _mu:
        _factory = factory
        for pkg, w in _loggers.items():
            w._swap(factory(pkg))


__all__ = [
    "ILogger", "StdLogger", "get_logger", "set_logger_factory",
    "CRITICAL", "ERROR", "WARNING", "INFO", "DEBUG",
]
