"""Compact binary codec for wire/state types.

The reference uses protobuf with a hand-written marshal fast path
(raftpb/raft_optimized.go). Here the codec is a little-endian
length-prefixed format built on struct packing — no varint dance, fixed
headers, memoryview slicing — fast enough in CPython and trivially portable
to the C++ transport/logdb runtime (the layout is the ABI).

All encode_* return bytes; all decode_* take (buf, offset) and return
(value, new_offset).
"""
from __future__ import annotations

import struct
from typing import List, Tuple

from .types import (
    Bootstrap,
    ConfigChange,
    ConfigChangeType,
    Entry,
    EntryType,
    Membership,
    Message,
    MessageBatch,
    MessageType,
    Snapshot,
    SnapshotChunk,
    SnapshotFile,
    State,
)

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
# type, term, index, key, client_id, series_id, responded_to, trace_id,
# cmd_len
_ENTRY = struct.Struct("<BQQQQQQQI")
# type, to, from, cluster_id, term, log_term, log_index, commit, reject,
# hint, hint_high, trace_id, n_entries, has_snapshot
_MSG = struct.Struct("<BQQQQQQQBQQQIB")
_STATE = struct.Struct("<QQQ")


class CodecError(ValueError):
    """The single controlled failure mode of every decode_* function:
    corrupt or truncated input raises this (found by dragonboat_tpu.fuzz;
    the reference gets the same guarantee from protobuf unmarshal errors,
    raftpb/fuzz.go:15-49)."""


def _need(buf, off: int, n: int) -> None:
    if n < 0 or off + n > len(buf):
        raise CodecError(f"truncated: need {n} bytes at {off}, have {len(buf)}")


def _checked(fn):
    """Public decoders convert every low-level unpack failure (truncated
    struct, bad enum value, invalid utf-8) into CodecError."""
    import functools

    @functools.wraps(fn)
    def wrap(buf, off: int = 0):
        try:
            return fn(buf, off)
        except CodecError:
            raise
        except (struct.error, ValueError, UnicodeDecodeError, IndexError,
                OverflowError) as e:
            raise CodecError(f"{fn.__name__}: {e}") from e

    return wrap


def _pack_bytes(b: bytes) -> bytes:
    return _U32.pack(len(b)) + b


def _unpack_bytes(buf, off: int) -> Tuple[bytes, int]:
    (n,) = _U32.unpack_from(buf, off)
    off += 4
    _need(buf, off, n)
    return bytes(buf[off : off + n]), off + n


def _pack_str(s: str) -> bytes:
    return _pack_bytes(s.encode())


def _unpack_str(buf, off: int) -> Tuple[str, int]:
    b, off = _unpack_bytes(buf, off)
    return b.decode(), off


def _unpack_count(buf, off: int, min_item_size: int) -> Tuple[int, int]:
    """Length-prefixed collection count, bounded by the bytes that could
    possibly remain — a corrupt count must not drive a multi-billion
    iteration loop."""
    (n,) = _U32.unpack_from(buf, off)
    off += 4
    if min_item_size > 0 and n > (len(buf) - off) // min_item_size:
        raise CodecError(f"corrupt collection count {n} at {off}")
    return n, off


# ---------------------------------------------------------------- Entry

def encode_entry(e: Entry) -> bytes:
    return (
        _ENTRY.pack(
            int(e.type),
            e.term,
            e.index,
            e.key,
            e.client_id,
            e.series_id,
            e.responded_to,
            e.trace_id,
            len(e.cmd),
        )
        + e.cmd
    )


@_checked
def decode_entry(buf, off: int = 0) -> Tuple[Entry, int]:
    t, term, index, key, cid, sid, resp, tid, clen = _ENTRY.unpack_from(
        buf, off
    )
    off += _ENTRY.size
    _need(buf, off, clen)
    cmd = bytes(buf[off : off + clen])
    return (
        Entry(
            type=EntryType(t),
            term=term,
            index=index,
            key=key,
            client_id=cid,
            series_id=sid,
            responded_to=resp,
            trace_id=tid,
            cmd=cmd,
        ),
        off + clen,
    )


def encode_entries(entries: List[Entry]) -> bytes:
    parts = [_U32.pack(len(entries))]
    parts.extend(encode_entry(e) for e in entries)
    return b"".join(parts)


def join_encoded_entries(parts: List[bytes]) -> bytes:
    """Assemble an entry-list record from per-entry encode_entry() outputs
    (the logdb batch cache keeps those parts to avoid re-encoding)."""
    return _U32.pack(len(parts)) + b"".join(parts)


@_checked
def decode_entries(buf, off: int = 0) -> Tuple[List[Entry], int]:
    n, off = _unpack_count(buf, off, _ENTRY.size)
    out = []
    for _ in range(n):
        e, off = decode_entry(buf, off)
        out.append(e)
    return out, off


# ---------------------------------------------------------------- State

def encode_state(st: State) -> bytes:
    return _STATE.pack(st.term, st.vote, st.commit)


@_checked
def decode_state(buf, off: int = 0) -> Tuple[State, int]:
    term, vote, commit = _STATE.unpack_from(buf, off)
    return State(term=term, vote=vote, commit=commit), off + _STATE.size


# ------------------------------------------------------------ Membership

def _pack_addr_map(m: dict) -> bytes:
    parts = [_U32.pack(len(m))]
    for nid in sorted(m):
        parts.append(_U64.pack(nid))
        parts.append(_pack_str(m[nid]))
    return b"".join(parts)


def _unpack_addr_map(buf, off: int) -> Tuple[dict, int]:
    n, off = _unpack_count(buf, off, 12)  # u64 nid + u32 len prefix
    out = {}
    for _ in range(n):
        (nid,) = _U64.unpack_from(buf, off)
        off += 8
        addr, off = _unpack_str(buf, off)
        out[nid] = addr
    return out, off


def encode_membership(m: Membership) -> bytes:
    parts = [_U64.pack(m.config_change_id)]
    parts.append(_pack_addr_map(m.addresses))
    parts.append(_pack_addr_map(m.observers))
    parts.append(_pack_addr_map(m.witnesses))
    removed = sorted(m.removed)
    parts.append(_U32.pack(len(removed)))
    for nid in removed:
        parts.append(_U64.pack(nid))
    return b"".join(parts)


@_checked
def decode_membership(buf, off: int = 0) -> Tuple[Membership, int]:
    (ccid,) = _U64.unpack_from(buf, off)
    off += 8
    addresses, off = _unpack_addr_map(buf, off)
    observers, off = _unpack_addr_map(buf, off)
    witnesses, off = _unpack_addr_map(buf, off)
    n, off = _unpack_count(buf, off, 8)
    removed = {}
    for _ in range(n):
        (nid,) = _U64.unpack_from(buf, off)
        off += 8
        removed[nid] = True
    return (
        Membership(
            config_change_id=ccid,
            addresses=addresses,
            observers=observers,
            witnesses=witnesses,
            removed=removed,
        ),
        off,
    )


# -------------------------------------------------------------- Snapshot

_SS = struct.Struct("<QQQQBBBBQ")  # filesize,index,term,cluster,dummy,type,imported,witness,on_disk_index


def encode_snapshot(ss: Snapshot) -> bytes:
    parts = [
        _SS.pack(
            ss.file_size,
            ss.index,
            ss.term,
            ss.cluster_id,
            1 if ss.dummy else 0,
            ss.type,
            1 if ss.imported else 0,
            1 if ss.witness else 0,
            ss.on_disk_index,
        )
    ]
    parts.append(_pack_str(ss.filepath))
    parts.append(_pack_bytes(ss.checksum))
    if ss.membership is not None:
        parts.append(b"\x01")
        parts.append(encode_membership(ss.membership))
    else:
        parts.append(b"\x00")
    parts.append(_U32.pack(len(ss.files)))
    for f in ss.files:
        parts.append(_U64.pack(f.file_id))
        parts.append(_U64.pack(f.file_size))
        parts.append(_pack_str(f.filepath))
        parts.append(_pack_bytes(f.metadata))
    return b"".join(parts)


@_checked
def decode_snapshot(buf, off: int = 0) -> Tuple[Snapshot, int]:
    fs, idx, term, cid, dummy, typ, imported, witness, odi = _SS.unpack_from(buf, off)
    off += _SS.size
    filepath, off = _unpack_str(buf, off)
    checksum, off = _unpack_bytes(buf, off)
    has_m = buf[off]
    off += 1
    membership = None
    if has_m:
        membership, off = decode_membership(buf, off)
    nf, off = _unpack_count(buf, off, 24)  # 2x u64 + 2x u32 prefixes
    files = []
    for _ in range(nf):
        (fid,) = _U64.unpack_from(buf, off)
        off += 8
        (fsize,) = _U64.unpack_from(buf, off)
        off += 8
        fp, off = _unpack_str(buf, off)
        meta, off = _unpack_bytes(buf, off)
        files.append(
            SnapshotFile(filepath=fp, file_size=fsize, file_id=fid, metadata=meta)
        )
    return (
        Snapshot(
            filepath=filepath,
            file_size=fs,
            index=idx,
            term=term,
            membership=membership,
            files=files,
            checksum=checksum,
            dummy=bool(dummy),
            cluster_id=cid,
            type=typ,
            imported=bool(imported),
            on_disk_index=odi,
            witness=bool(witness),
        ),
        off,
    )


# --------------------------------------------------------------- Message

def encode_message(m: Message) -> bytes:
    parts = [
        _MSG.pack(
            int(m.type),
            m.to,
            m.from_,
            m.cluster_id,
            m.term,
            m.log_term,
            m.log_index,
            m.commit,
            1 if m.reject else 0,
            m.hint,
            m.hint_high,
            m.trace_id,
            len(m.entries),
            1 if m.snapshot is not None else 0,
        )
    ]
    parts.extend(encode_entry(e) for e in m.entries)
    if m.snapshot is not None:
        parts.append(encode_snapshot(m.snapshot))
    return b"".join(parts)


@_checked
def decode_message(buf, off: int = 0) -> Tuple[Message, int]:
    (
        t,
        to,
        frm,
        cid,
        term,
        lterm,
        lidx,
        commit,
        reject,
        hint,
        hint_high,
        tid,
        n_ent,
        has_ss,
    ) = _MSG.unpack_from(buf, off)
    off += _MSG.size
    entries = []
    for _ in range(n_ent):
        e, off = decode_entry(buf, off)
        entries.append(e)
    ss = None
    if has_ss:
        ss, off = decode_snapshot(buf, off)
    return (
        Message(
            type=MessageType(t),
            to=to,
            from_=frm,
            cluster_id=cid,
            term=term,
            log_term=lterm,
            log_index=lidx,
            commit=commit,
            reject=bool(reject),
            hint=hint,
            hint_high=hint_high,
            trace_id=tid,
            entries=entries,
            snapshot=ss,
        ),
        off,
    )


# ----------------------------------------------------------- MessageBatch

def encode_message_batch(b: MessageBatch) -> bytes:
    parts = [
        _U64.pack(b.deployment_id),
        _U32.pack(b.bin_ver),
        _pack_str(b.source_address),
        _U32.pack(len(b.requests)),
    ]
    parts.extend(encode_message(m) for m in b.requests)
    return b"".join(parts)


@_checked
def decode_message_batch(buf, off: int = 0) -> Tuple[MessageBatch, int]:
    (did,) = _U64.unpack_from(buf, off)
    off += 8
    (bv,) = _U32.unpack_from(buf, off)
    off += 4
    src, off = _unpack_str(buf, off)
    (n,) = _U32.unpack_from(buf, off)
    off += 4
    msgs = []
    for _ in range(n):
        m, off = decode_message(buf, off)
        msgs.append(m)
    return (
        MessageBatch(
            requests=msgs, deployment_id=did, source_address=src, bin_ver=bv
        ),
        off,
    )


# ---------------------------------------------------------- SnapshotChunk

_CHUNK = struct.Struct("<QQQQQQQQQQQQBBQB")


def encode_chunk(c: SnapshotChunk) -> bytes:
    parts = [
        _CHUNK.pack(
            c.cluster_id,
            c.node_id,
            c.from_,
            c.chunk_id,
            c.chunk_size,
            c.chunk_count,
            c.index,
            c.term,
            c.file_size,
            c.deployment_id,
            c.file_chunk_id,
            c.file_chunk_count,
            1 if c.has_file_info else 0,
            1 if c.witness else 0,
            c.on_disk_index,
            1 if c.membership is not None else 0,
        )
    ]
    parts.append(_pack_str(c.filepath))
    parts.append(_pack_bytes(c.data))
    if c.has_file_info and c.file_info is not None:
        parts.append(_U64.pack(c.file_info.file_id))
        parts.append(_U64.pack(c.file_info.file_size))
        parts.append(_pack_str(c.file_info.filepath))
        parts.append(_pack_bytes(c.file_info.metadata))
    if c.membership is not None:
        parts.append(encode_membership(c.membership))
    return b"".join(parts)


@_checked
def decode_chunk(buf, off: int = 0) -> Tuple[SnapshotChunk, int]:
    (
        cid,
        nid,
        frm,
        chunk_id,
        chunk_size,
        chunk_count,
        index,
        term,
        file_size,
        did,
        fcid,
        fcc,
        has_fi,
        witness,
        odi,
        has_m,
    ) = _CHUNK.unpack_from(buf, off)
    off += _CHUNK.size
    filepath, off = _unpack_str(buf, off)
    data, off = _unpack_bytes(buf, off)
    fi = None
    if has_fi:
        (fid,) = _U64.unpack_from(buf, off)
        off += 8
        (fsize,) = _U64.unpack_from(buf, off)
        off += 8
        fp, off = _unpack_str(buf, off)
        meta, off = _unpack_bytes(buf, off)
        fi = SnapshotFile(filepath=fp, file_size=fsize, file_id=fid, metadata=meta)
    membership = None
    if has_m:
        membership, off = decode_membership(buf, off)
    return (
        SnapshotChunk(
            cluster_id=cid,
            node_id=nid,
            from_=frm,
            chunk_id=chunk_id,
            chunk_size=chunk_size,
            chunk_count=chunk_count,
            data=data,
            index=index,
            term=term,
            filepath=filepath,
            file_size=file_size,
            deployment_id=did,
            file_chunk_id=fcid,
            file_chunk_count=fcc,
            has_file_info=bool(has_fi),
            file_info=fi,
            membership=membership,
            on_disk_index=odi,
            witness=bool(witness),
        ),
        off,
    )


# -------------------------------------------------------------- Bootstrap

def encode_bootstrap(b: Bootstrap) -> bytes:
    return (
        _pack_addr_map(b.addresses) + (b"\x01" if b.join else b"\x00") + _U32.pack(b.type)
    )


@_checked
def decode_bootstrap(buf, off: int = 0) -> Tuple[Bootstrap, int]:
    addresses, off = _unpack_addr_map(buf, off)
    join = buf[off] == 1
    off += 1
    (t,) = _U32.unpack_from(buf, off)
    off += 4
    return Bootstrap(addresses=addresses, join=join, type=t), off


__all__ = [
    "encode_entry",
    "decode_entry",
    "encode_entries",
    "decode_entries",
    "encode_state",
    "decode_state",
    "encode_membership",
    "decode_membership",
    "encode_snapshot",
    "decode_snapshot",
    "encode_message",
    "decode_message",
    "encode_message_batch",
    "decode_message_batch",
    "encode_chunk",
    "decode_chunk",
    "encode_bootstrap",
    "decode_bootstrap",
]
