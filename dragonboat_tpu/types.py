"""Wire and protocol state types for dragonboat-tpu.

This is the raftpb-equivalent layer (cf. reference raftpb/raft.pb.go:26-51 for
message types, raftpb/raft.go:44-110 for the non-pb runtime types). Unlike the
reference there is no protobuf dependency: these are plain Python dataclasses
with a compact binary codec (see codec.py) used by the transport and logdb.

Protocol-state integers (term, index, node ids) are uint64 in the reference;
the scalar oracle keeps them as Python ints, while the vectorized kernel keeps
them as int32 device tensors (indices/terms stay well below 2**31 in any
realistic deployment window; the kernel rebases indices against the compaction
watermark to keep them small).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field, replace
from typing import List, Optional, Sequence, Tuple

NO_LEADER = 0
NO_NODE = 0
NO_LIMIT = 2**63 - 1


class MessageType(enum.IntEnum):
    """Message types; numbering matches reference raftpb/raft.pb.go:26-51 so
    that traces are comparable against the reference."""

    LOCAL_TICK = 0
    ELECTION = 1
    LEADER_HEARTBEAT = 2
    CONFIG_CHANGE_EVENT = 3
    NOOP = 4
    PING = 5
    PONG = 6
    PROPOSE = 7
    SNAPSHOT_STATUS = 8
    UNREACHABLE = 9
    CHECK_QUORUM = 10
    BATCHED_READ_INDEX = 11
    REPLICATE = 12
    REPLICATE_RESP = 13
    REQUEST_VOTE = 14
    REQUEST_VOTE_RESP = 15
    INSTALL_SNAPSHOT = 16
    HEARTBEAT = 17
    HEARTBEAT_RESP = 18
    READ_INDEX = 19
    READ_INDEX_RESP = 20
    QUIESCE = 21
    SNAPSHOT_RECEIVED = 22
    LEADER_TRANSFER = 23
    TIMEOUT_NOW = 24
    RATE_LIMIT = 25
    # Pre-vote phase (Raft thesis 9.6 / the Paxos-Raft-parallels catalog's
    # standard fix for rejoin-induced leader disturbance; no referent in
    # the reference dragonboat, numbering continues past its table). A
    # REQUEST_PREVOTE carries the PROSPECTIVE term (current+1) and never
    # changes the receiver's term or vote; a granted REQUEST_PREVOTE_RESP
    # echoes that prospective term back.
    REQUEST_PREVOTE = 26
    REQUEST_PREVOTE_RESP = 27


NUM_MESSAGE_TYPES = 28

# Message types generated locally and never put on the wire
# (cf. raftpb/raft.go IsLocalMessageType).
_LOCAL_TYPES = frozenset(
    {
        MessageType.LOCAL_TICK,
        MessageType.ELECTION,
        MessageType.LEADER_HEARTBEAT,
        MessageType.CONFIG_CHANGE_EVENT,
        MessageType.CHECK_QUORUM,
        MessageType.BATCHED_READ_INDEX,
        MessageType.SNAPSHOT_RECEIVED,
        MessageType.RATE_LIMIT,
    }
)

_RESPONSE_TYPES = frozenset(
    {
        MessageType.REPLICATE_RESP,
        MessageType.REQUEST_VOTE_RESP,
        MessageType.REQUEST_PREVOTE_RESP,
        MessageType.HEARTBEAT_RESP,
        MessageType.READ_INDEX_RESP,
        MessageType.UNREACHABLE,
        MessageType.SNAPSHOT_STATUS,
        MessageType.LEADER_TRANSFER,
    }
)

_REQUEST_TYPES = frozenset({MessageType.PROPOSE, MessageType.READ_INDEX})

# Messages only a leader sends (cf. internal/raft/raft.go:1382-1385).
_LEADER_TYPES = frozenset(
    {
        MessageType.REPLICATE,
        MessageType.INSTALL_SNAPSHOT,
        MessageType.HEARTBEAT,
        MessageType.TIMEOUT_NOW,
        MessageType.READ_INDEX_RESP,
    }
)


def is_local_message(t: MessageType) -> bool:
    return t in _LOCAL_TYPES


def is_response_message(t: MessageType) -> bool:
    return t in _RESPONSE_TYPES


def is_request_message(t: MessageType) -> bool:
    return t in _REQUEST_TYPES


def is_leader_message(t: MessageType) -> bool:
    return t in _LEADER_TYPES


class EntryType(enum.IntEnum):
    APPLICATION = 0
    CONFIG_CHANGE = 1
    # Witness replicas receive metadata-only entries (cf. raft.go:742-756).
    METADATA = 2
    # Payload carries the v0 compression header (cf. rsm/encoded.go:47-176).
    ENCODED = 3


class ConfigChangeType(enum.IntEnum):
    ADD_NODE = 0
    REMOVE_NODE = 1
    ADD_OBSERVER = 2
    ADD_WITNESS = 3


class CompressionType(enum.IntEnum):
    NO_COMPRESSION = 0
    SNAPPY = 1


@dataclass(slots=True)
class Entry:
    """A Raft log entry (cf. raftpb raft.pb.go:589 Entry fields)."""

    type: EntryType = EntryType.APPLICATION
    term: int = 0
    index: int = 0
    key: int = 0
    client_id: int = 0
    series_id: int = 0
    responded_to: int = 0
    cmd: bytes = b""
    # causal trace id (trace.mint_trace_id), nonzero on the 1-in-N sampled
    # proposals only; unlike `lat` it IS serialized, so replicas across the
    # wire can stamp the same id into their flight-recorder events and a
    # merged multi-node dump reconstructs one proposal's causal chain
    trace_id: int = 0
    # sampled latency trace (trace.LatencyTrace), attached at propose time
    # to 1-in-N proposals on the PROPOSING node only; never serialized (the
    # codec copies explicit fields), None everywhere else
    lat: Optional[object] = None

    def is_config_change(self) -> bool:
        return self.type == EntryType.CONFIG_CHANGE

    def is_noop_session(self) -> bool:
        return self.client_id == NOOP_CLIENT_ID

    def is_new_session_request(self) -> bool:
        return (
            self.type != EntryType.CONFIG_CHANGE
            and self.client_id != NOOP_CLIENT_ID
            and self.series_id == SERIES_ID_FOR_REGISTER
        )

    def is_end_of_session_request(self) -> bool:
        return (
            self.type != EntryType.CONFIG_CHANGE
            and self.client_id != NOOP_CLIENT_ID
            and self.series_id == SERIES_ID_FOR_UNREGISTER
        )

    def is_session_managed(self) -> bool:
        return not (
            self.type == EntryType.CONFIG_CHANGE or self.client_id == NOOP_CLIENT_ID
        )

    def is_update(self) -> bool:
        """A regular session-managed update proposal."""
        return (
            self.type != EntryType.CONFIG_CHANGE
            and self.client_id != NOOP_CLIENT_ID
            and self.series_id != SERIES_ID_FOR_REGISTER
            and self.series_id != SERIES_ID_FOR_UNREGISTER
        )

    def is_empty(self) -> bool:
        # config-change and session-managed entries are never "empty"
        # (cf. raftpb/raft.go:152-160)
        if self.type == EntryType.CONFIG_CHANGE or self.is_session_managed():
            return False
        return len(self.cmd) == 0


# Special client session series ids (cf. client/session.go:29-43:
# register = MaxUint64-1, unregister = MaxUint64).
NOOP_CLIENT_ID = 0
NOOP_SERIES_ID = 0
SERIES_ID_FOR_REGISTER = 2**64 - 2
SERIES_ID_FOR_UNREGISTER = 2**64 - 1
SERIES_ID_FIRST_PROPOSAL = 1


@dataclass(slots=True)
class ConfigChange:
    config_change_id: int = 0
    type: ConfigChangeType = ConfigChangeType.ADD_NODE
    node_id: int = 0
    address: str = ""
    initialize: bool = False


@dataclass(slots=True)
class SnapshotFile:
    filepath: str = ""
    file_size: int = 0
    file_id: int = 0
    metadata: bytes = b""


@dataclass(slots=True)
class Membership:
    config_change_id: int = 0
    addresses: dict = field(default_factory=dict)  # node_id -> address
    removed: dict = field(default_factory=dict)  # node_id -> True
    observers: dict = field(default_factory=dict)
    witnesses: dict = field(default_factory=dict)

    def copy(self) -> "Membership":
        return Membership(
            config_change_id=self.config_change_id,
            addresses=dict(self.addresses),
            removed=dict(self.removed),
            observers=dict(self.observers),
            witnesses=dict(self.witnesses),
        )


@dataclass(slots=True)
class Snapshot:
    """Snapshot metadata (cf. raftpb raft.pb.go:879)."""

    filepath: str = ""
    file_size: int = 0
    index: int = 0
    term: int = 0
    membership: Optional[Membership] = None
    files: List[SnapshotFile] = field(default_factory=list)
    checksum: bytes = b""
    dummy: bool = False
    cluster_id: int = 0
    type: int = 0
    imported: bool = False
    on_disk_index: int = 0
    witness: bool = False

    def is_empty(self) -> bool:
        return self.index == 0


@dataclass(slots=True)
class State:
    """Persistent Raft state (term/vote/commit), cf. raftpb raft.pb.go:529."""

    term: int = 0
    vote: int = 0
    commit: int = 0

    def is_empty(self) -> bool:
        return self.term == 0 and self.vote == 0 and self.commit == 0


EMPTY_STATE = State()


@dataclass(slots=True)
class SystemCtx:
    """Opaque 128-bit context id used by the ReadIndex protocol
    (cf. raftpb/raft.go SystemCtx)."""

    low: int = 0
    high: int = 0

    def __hash__(self):
        return hash((self.low, self.high))

    def is_zero(self) -> bool:
        return self.low == 0 and self.high == 0


@dataclass(slots=True)
class ReadyToRead:
    index: int = 0
    system_ctx: SystemCtx = field(default_factory=SystemCtx)


@dataclass(slots=True)
class Message:
    """Raft protocol message (cf. raftpb raft.pb.go:1019-1033)."""

    type: MessageType = MessageType.NOOP
    to: int = 0
    from_: int = 0
    cluster_id: int = 0
    term: int = 0
    log_term: int = 0
    log_index: int = 0
    commit: int = 0
    reject: bool = False
    hint: int = 0
    hint_high: int = 0
    # causal trace id carried across the wire AND the co-hosted delivery
    # seam: stamped on Replicate/ReplicateResp hops that touch a sampled
    # entry (0 everywhere else — the unsampled path pays nothing)
    trace_id: int = 0
    entries: List[Entry] = field(default_factory=list)
    snapshot: Optional[Snapshot] = None


@dataclass(slots=True)
class MessageBatch:
    requests: List[Message] = field(default_factory=list)
    deployment_id: int = 0
    source_address: str = ""
    bin_ver: int = 0


@dataclass(slots=True)
class SnapshotChunk:
    cluster_id: int = 0
    node_id: int = 0
    from_: int = 0
    chunk_id: int = 0
    chunk_size: int = 0
    chunk_count: int = 0
    data: bytes = b""
    index: int = 0
    term: int = 0
    filepath: str = ""
    file_size: int = 0
    deployment_id: int = 0
    file_chunk_id: int = 0
    file_chunk_count: int = 0
    has_file_info: bool = False
    file_info: Optional[SnapshotFile] = None
    membership: Optional[Membership] = None
    bin_ver: int = 0
    on_disk_index: int = 0
    witness: bool = False


@dataclass(slots=True)
class UpdateCommit:
    """Cursors confirming how much of an Update was processed
    (cf. raftpb/raft.go UpdateCommit and peer.go getUpdateCommit)."""

    processed: int = 0
    last_applied: int = 0
    stable_log_to: int = 0
    stable_log_term: int = 0
    stable_snapshot_to: int = 0
    ready_to_read: int = 0


@dataclass(slots=True)
class Update:
    """The per-step output of a Raft node: what to persist, send, and apply
    (cf. raftpb/raft.go Update)."""

    cluster_id: int = 0
    node_id: int = 0
    state: State = field(default_factory=State)
    entries_to_save: List[Entry] = field(default_factory=list)
    committed_entries: List[Entry] = field(default_factory=list)
    more_committed_entries: bool = False
    snapshot: Optional[Snapshot] = None
    ready_to_reads: List[ReadyToRead] = field(default_factory=list)
    messages: List[Message] = field(default_factory=list)
    last_applied: int = 0
    update_commit: UpdateCommit = field(default_factory=UpdateCommit)
    fast_apply: bool = True
    dropped_entries: List[Entry] = field(default_factory=list)
    dropped_read_indexes: List[SystemCtx] = field(default_factory=list)

    def has_update(self) -> bool:
        return bool(
            not self.state.is_empty()
            or self.entries_to_save
            or self.committed_entries
            or self.messages
            or self.ready_to_reads
            or (self.snapshot is not None and not self.snapshot.is_empty())
        )


@dataclass(slots=True)
class Bootstrap:
    """Bootstrap record persisted on first start (cf. raftpb Bootstrap)."""

    addresses: dict = field(default_factory=dict)  # node_id -> address
    join: bool = False
    type: int = 0

    def validate(self, nodes: dict, join: bool, smtype: int) -> bool:
        # cf. raftpb/raft.go:221-258 Bootstrap.Validate. Restarting with an
        # empty member list is the normal path once a bootstrap record
        # exists; a non-empty list must match the original exactly.
        if not self.join and len(self.addresses) == 0:
            return False
        if self.join and len(nodes) > 0:
            return False
        if join and len(self.addresses) > 0:
            return False
        if self.type != 0 and smtype != 0 and self.type != smtype:
            return False
        if nodes and not self.join:
            if len(nodes) != len(self.addresses):
                return False
            for nid, addr in nodes.items():
                if self.addresses.get(nid) != addr:
                    return False
        return True


def entries_size(entries: Sequence[Entry]) -> int:
    """Approximate in-memory footprint used for flow control accounting."""
    return sum(len(e.cmd) + 48 for e in entries)


def limit_entry_size(entries: List[Entry], max_size: int) -> List[Entry]:
    """Cap the slice at max_size bytes but always keep >=1 entry
    (cf. internal/raft/entryutils.go limitSize)."""
    if not entries:
        return entries
    total = 0
    for i, e in enumerate(entries):
        total += len(e.cmd) + 48
        if total > max_size and i > 0:
            return entries[:i]
    return entries


def assert_contiguous(entries: Sequence[Entry]) -> None:
    """Panic on holes in an entry slice (cf. entryutils.go:36-48)."""
    for i in range(1, len(entries)):
        if entries[i].index != entries[i - 1].index + 1:
            raise RuntimeError(
                f"log hole found between {entries[i-1].index} and {entries[i].index}"
            )


__all__ = [
    "NO_LEADER",
    "NO_NODE",
    "NO_LIMIT",
    "MessageType",
    "EntryType",
    "ConfigChangeType",
    "CompressionType",
    "Entry",
    "ConfigChange",
    "Membership",
    "Snapshot",
    "SnapshotFile",
    "SnapshotChunk",
    "State",
    "EMPTY_STATE",
    "SystemCtx",
    "ReadyToRead",
    "Message",
    "MessageBatch",
    "Update",
    "UpdateCommit",
    "Bootstrap",
    "NOOP_CLIENT_ID",
    "NOOP_SERIES_ID",
    "SERIES_ID_FOR_REGISTER",
    "SERIES_ID_FOR_UNREGISTER",
    "SERIES_ID_FIRST_PROPOSAL",
    "is_local_message",
    "is_response_message",
    "is_request_message",
    "is_leader_message",
    "entries_size",
    "limit_entry_size",
    "assert_contiguous",
    "replace",
]
