"""Pluggable infrastructure seams: log storage, transport, event listeners.

The equivalent of the reference's `raftio/` package: ILogDB is the stable
log storage contract (cf. raftio/logdb.go:99-147), IRaftRPC the transport
contract (cf. raftio/rpc.go:90-105), and the listener interfaces mirror
raftio/listener.go. Implementations live in storage/ and transport/; users
can supply their own through NodeHostConfig factories.
"""
from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Tuple

from .types import Entry, Membership, Message, MessageBatch, Snapshot, SnapshotChunk, State, Update


class ErrNoSavedLog(Exception):
    """No saved state found for the node (cf. raftio/logdb.go ErrNoSavedLog)."""


class ErrNoBootstrapInfo(Exception):
    """No bootstrap record found (cf. raftio/logdb.go ErrNoBootstrapInfo)."""


@dataclass(slots=True)
class NodeInfo:
    cluster_id: int = 0
    node_id: int = 0


@dataclass(slots=True)
class RaftState:
    """State + log range returned by ReadRaftState
    (cf. raftio/logdb.go RaftState)."""

    state: State = None
    first_index: int = 0
    entry_count: int = 0


class ILogDB(abc.ABC):
    """Stable storage of Raft states, entries, snapshots and bootstrap
    records for all groups in a NodeHost (cf. raftio/logdb.go:99-147).

    save_raft_state persists a batch of Updates from many groups in ONE
    atomic+fsynced write — the engine's whole-worker batching depends on it
    (cf. internal/logdb/sharded_rdb.go:149-156)."""

    @abc.abstractmethod
    def name(self) -> str: ...

    @abc.abstractmethod
    def close(self) -> None: ...

    @abc.abstractmethod
    def list_node_info(self) -> List[NodeInfo]: ...

    @abc.abstractmethod
    def save_bootstrap_info(
        self, cluster_id: int, node_id: int, bootstrap
    ) -> None: ...

    def save_bootstrap_infos(self, items) -> None:
        """Bulk bootstrap persistence for fleet bring-up; items are
        (cluster_id, node_id, Bootstrap) tuples. Backends should override
        with one atomic batch per shard — the default falls back to
        per-item writes."""
        for cid, nid, b in items:
            self.save_bootstrap_info(cid, nid, b)

    @abc.abstractmethod
    def get_bootstrap_info(self, cluster_id: int, node_id: int): ...

    @abc.abstractmethod
    def save_raft_state(self, updates: Sequence[Update], shard_id: int = 0) -> None: ...

    @abc.abstractmethod
    def read_raft_state(
        self, cluster_id: int, node_id: int, last_index: int
    ) -> RaftState: ...

    @abc.abstractmethod
    def iterate_entries(
        self,
        cluster_id: int,
        node_id: int,
        low: int,
        high: int,
        max_size: int,
    ) -> Tuple[List[Entry], int]:
        """Entries in [low, high) up to max_size bytes; returns (entries,
        total_size)."""

    @abc.abstractmethod
    def remove_entries_to(self, cluster_id: int, node_id: int, index: int) -> None: ...

    @abc.abstractmethod
    def compact_entries_to(self, cluster_id: int, node_id: int, index: int) -> None: ...

    @abc.abstractmethod
    def save_snapshots(self, updates: Sequence[Update]) -> None: ...

    @abc.abstractmethod
    def delete_snapshot(self, cluster_id: int, node_id: int, index: int) -> None: ...

    @abc.abstractmethod
    def list_snapshots(
        self, cluster_id: int, node_id: int, index: int
    ) -> List[Snapshot]: ...

    @abc.abstractmethod
    def remove_node_data(self, cluster_id: int, node_id: int) -> None: ...

    @abc.abstractmethod
    def import_snapshot(self, ss: Snapshot, node_id: int) -> None: ...


class IConnection(abc.ABC):
    """An established transport connection to a remote NodeHost
    (cf. raftio/rpc.go:30-45)."""

    @abc.abstractmethod
    def close(self) -> None: ...

    @abc.abstractmethod
    def send_message_batch(self, batch: MessageBatch) -> None: ...


class ISnapshotConnection(abc.ABC):
    """Connection used to stream snapshot chunks (cf. raftio/rpc.go:47-62)."""

    @abc.abstractmethod
    def close(self) -> None: ...

    @abc.abstractmethod
    def send_chunk(self, chunk: SnapshotChunk) -> None: ...


class IRaftRPC(abc.ABC):
    """The pluggable transport module (cf. raftio/rpc.go:90-105)."""

    @abc.abstractmethod
    def name(self) -> str: ...

    @abc.abstractmethod
    def start(self) -> None: ...

    @abc.abstractmethod
    def stop(self) -> None: ...

    @abc.abstractmethod
    def get_connection(self, target: str) -> IConnection: ...

    @abc.abstractmethod
    def get_snapshot_connection(self, target: str) -> ISnapshotConnection: ...


# Handler callbacks installed by the NodeHost into the RPC module
# (cf. raftio/rpc.go RequestHandler / ChunkSinkFactory).
RequestHandler = Callable[[MessageBatch], None]
ChunkHandler = Callable[[SnapshotChunk], bool]


@dataclass(slots=True)
class LeaderInfo:
    cluster_id: int = 0
    node_id: int = 0
    term: int = 0
    leader_id: int = 0


class IRaftEventListener(abc.ABC):
    """User callback for leadership events (cf. raftio/listener.go:31-35)."""

    @abc.abstractmethod
    def leader_updated(self, info: LeaderInfo) -> None: ...


@dataclass(slots=True)
class EntryInfo:
    cluster_id: int = 0
    node_id: int = 0
    index: int = 0


@dataclass(slots=True)
class SnapshotInfo:
    cluster_id: int = 0
    node_id: int = 0
    from_: int = 0
    index: int = 0


@dataclass(slots=True)
class ConnectionInfo:
    address: str = ""
    snapshot_connection: bool = False


class ISystemEventListener(abc.ABC):
    """Optional process-level event callbacks (cf. config.SystemEventListener
    in the v3.3 line of the reference; subset relevant here)."""

    def node_ready(self, info: NodeInfo) -> None: ...

    def node_unloaded(self, info: NodeInfo) -> None: ...

    def membership_changed(self, info: NodeInfo) -> None: ...

    def connection_established(self, info: ConnectionInfo) -> None: ...

    def connection_failed(self, info: ConnectionInfo) -> None: ...

    def send_snapshot_started(self, info: SnapshotInfo) -> None: ...

    def send_snapshot_completed(self, info: SnapshotInfo) -> None: ...

    def send_snapshot_aborted(self, info: SnapshotInfo) -> None: ...

    def snapshot_received(self, info: SnapshotInfo) -> None: ...

    def snapshot_recovered(self, info: SnapshotInfo) -> None: ...

    def snapshot_created(self, info: SnapshotInfo) -> None: ...

    def snapshot_compacted(self, info: SnapshotInfo) -> None: ...

    def log_compacted(self, info: EntryInfo) -> None: ...

    def log_db_compacted(self, info: EntryInfo) -> None: ...


class IMessageHandler(abc.ABC):
    """Installed by NodeHost to receive inbound traffic
    (cf. internal/transport/transport.go:100-105)."""

    @abc.abstractmethod
    def handle_message_batch(self, batch: MessageBatch) -> Tuple[int, int]:
        """Returns (snapshot_count, msg_count) accepted."""

    @abc.abstractmethod
    def handle_unreachable(self, cluster_id: int, node_id: int) -> None: ...

    @abc.abstractmethod
    def handle_snapshot_status(
        self, cluster_id: int, node_id: int, failed: bool
    ) -> None: ...

    @abc.abstractmethod
    def handle_snapshot(self, cluster_id: int, node_id: int, from_: int) -> None: ...


__all__ = [
    "ErrNoSavedLog",
    "ErrNoBootstrapInfo",
    "NodeInfo",
    "RaftState",
    "ILogDB",
    "IConnection",
    "ISnapshotConnection",
    "IRaftRPC",
    "RequestHandler",
    "ChunkHandler",
    "LeaderInfo",
    "EntryInfo",
    "SnapshotInfo",
    "ConnectionInfo",
    "IRaftEventListener",
    "ISystemEventListener",
    "IMessageHandler",
]
