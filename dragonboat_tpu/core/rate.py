"""In-memory log-size rate limiting.

Counterpart of the reference's RateLimiter (internal/server/rate.go:32-137):
when Config.max_in_mem_log_size is set, each replica tracks the byte size
of its not-yet-applied in-memory log; followers report their size to the
leader on a logical-clock cadence (one limiter tick per election timeout,
rate.go HeartbeatTick + raft.go:543-545 timeForRateLimitCheck), and the
leader refuses new proposals while ANY fresh replica — itself included —
is over the configured bound. Follower reports older than GC_TICK limiter
ticks are discarded, so a partitioned follower cannot wedge the leader in
the limited state forever (rate.go:102-127).

The scalar core wires this through RATE_LIMIT messages (core/raft.py);
the vector engine applies the same bound per lane host-side from its
arena byte accounting (engine/vector.py) — device lanes never carry
payload bytes, so the host is the only place the size is known.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

# Fixed per-entry overhead charged on top of the payload: index/term/type
# bookkeeping that exists whether or not the command is empty (the
# reference charges the marshalled entry struct size).
ENTRY_OVERHEAD_BYTES = 48


def entry_mem_size(entry) -> int:
    return ENTRY_OVERHEAD_BYTES + len(entry.cmd)


def entries_mem_size(entries: List) -> int:
    return sum(ENTRY_OVERHEAD_BYTES + len(e.cmd) for e in entries)


class RateLimiter:
    """Tracks local + reported follower in-memory log sizes against one
    byte bound. Not thread-safe by itself: the scalar core mutates it from
    the step worker only; the vector engine keeps one per lane under the
    engine lock."""

    GC_TICK = 2  # follower reports older than this many ticks are stale

    __slots__ = ("max_bytes", "_bytes", "tick_count", "_followers")

    def __init__(self, max_bytes: int) -> None:
        self.max_bytes = max_bytes
        self._bytes = 0
        self.tick_count = 0
        self._followers: Dict[int, Tuple[int, int]] = {}

    @property
    def enabled(self) -> bool:
        return self.max_bytes > 0

    # ---------------------------------------------------- logical clock
    def tick(self) -> None:
        self.tick_count += 1

    # ----------------------------------------------------- size tracking
    def increase(self, n: int) -> None:
        self._bytes += n

    def decrease(self, n: int) -> None:
        self._bytes = max(0, self._bytes - n)

    def set(self, n: int) -> None:
        self._bytes = n

    def get(self) -> int:
        return self._bytes

    # -------------------------------------------------- follower reports
    def set_follower_state(self, node_id: int, n: int) -> None:
        self._followers[node_id] = (self.tick_count, n)

    def reset_follower_state(self) -> None:
        self._followers.clear()

    # ------------------------------------------------------------ verdict
    def rate_limited(self) -> bool:
        """True when the largest FRESH size on record exceeds the bound;
        stale follower reports are dropped as a side effect."""
        if not self.enabled:
            return False
        worst = self._bytes
        stale = [
            nid
            for nid, (t, _) in self._followers.items()
            if self.tick_count - t > self.GC_TICK
        ]
        for nid in stale:
            del self._followers[nid]
        for t, n in self._followers.values():
            worst = max(worst, n)
        return worst > self.max_bytes


__all__ = [
    "RateLimiter",
    "entry_mem_size",
    "entries_mem_size",
    "ENTRY_OVERHEAD_BYTES",
]
