"""Per-follower replication progress and flow-control FSM.

Semantics follow the reference's remote states Retry/Wait/Replicate/Snapshot
(cf. internal/raft/remote.go:44-198). The vectorized kernel keeps the same FSM
as an int8 tensor lane per (group, peer).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass


class RemoteState(enum.IntEnum):
    RETRY = 0
    WAIT = 1
    REPLICATE = 2
    SNAPSHOT = 3


@dataclass(slots=True)
class Remote:
    match: int = 0
    next: int = 0
    snapshot_index: int = 0
    state: RemoteState = RemoteState.RETRY
    active: bool = False

    def become_retry(self) -> None:
        if self.state == RemoteState.SNAPSHOT:
            self.next = max(self.match + 1, self.snapshot_index + 1)
        else:
            self.next = self.match + 1
        self.snapshot_index = 0
        self.state = RemoteState.RETRY

    def retry_to_wait(self) -> None:
        if self.state == RemoteState.RETRY:
            self.state = RemoteState.WAIT

    def wait_to_retry(self) -> None:
        if self.state == RemoteState.WAIT:
            self.state = RemoteState.RETRY

    def become_wait(self) -> None:
        self.become_retry()
        self.retry_to_wait()

    def become_replicate(self) -> None:
        self.next = self.match + 1
        self.snapshot_index = 0
        self.state = RemoteState.REPLICATE

    def become_snapshot(self, index: int) -> None:
        self.snapshot_index = index
        self.state = RemoteState.SNAPSHOT

    def clear_pending_snapshot(self) -> None:
        self.snapshot_index = 0

    def try_update(self, index: int) -> bool:
        """Advance match/next on a successful ReplicateResp; returns True when
        match actually moved forward (stale responses return False)."""
        if self.next < index + 1:
            self.next = index + 1
        if self.match < index:
            self.wait_to_retry()
            self.match = index
            return True
        return False

    def progress(self, last_index: int) -> None:
        """Optimistically bump next after sending entries (pipelining)."""
        if self.state == RemoteState.REPLICATE:
            self.next = last_index + 1
        elif self.state == RemoteState.RETRY:
            self.retry_to_wait()
        else:
            raise RuntimeError(f"unexpected remote state {self.state}")

    def responded_to(self) -> None:
        if self.state == RemoteState.RETRY:
            self.become_replicate()
        elif self.state == RemoteState.SNAPSHOT:
            if self.match >= self.snapshot_index:
                self.become_retry()

    def decrease_to(self, rejected: int, last: int) -> bool:
        """Handle a rejected ReplicateResp; conservative reset of next
        (cf. remote.go:155-171). Returns False for stale rejections."""
        if self.state == RemoteState.REPLICATE:
            if rejected <= self.match:
                return False
            self.next = self.match + 1
            return True
        if self.next - 1 != rejected:
            return False
        self.wait_to_retry()
        self.next = max(1, min(rejected, last + 1))
        return True

    def is_paused(self) -> bool:
        return self.state in (RemoteState.WAIT, RemoteState.SNAPSHOT)

    def is_active(self) -> bool:
        return self.active

    def set_active(self) -> None:
        self.active = True

    def set_not_active(self) -> None:
        self.active = False
