"""Scalar (per-group) Raft protocol core.

This package is the pure-Python reference implementation of the Raft protocol
with Dragonboat's exact semantics (cf. /root/reference/internal/raft/). It has
two jobs:

1. It is the *oracle* for differential testing of the vectorized TPU kernel in
   dragonboat_tpu.ops: same message trace in => same Updates out.
2. It is the fallback slow path for protocol events the batched kernel defers
   to the host (snapshot restore, membership reconfiguration).
"""
from .peer import Peer, PeerAddress, launch_peer
from .raft import Raft, RaftNodeState
from .logentry import EntryLog, ILogDB, InMemLogDB
from .remote import Remote, RemoteState
from .readindex import ReadIndexTracker

__all__ = [
    "Peer",
    "PeerAddress",
    "launch_peer",
    "Raft",
    "RaftNodeState",
    "EntryLog",
    "ILogDB",
    "InMemLogDB",
    "Remote",
    "RemoteState",
    "ReadIndexTracker",
]
