"""Peer: the message-passing facade over the scalar Raft state machine.

Everything — ticks, proposals, config changes, leadership transfer — enters
the protocol as a Message; results leave as an Update via the etcd-style
GetUpdate/Commit two-phase contract (cf. internal/raft/peer.go:58-427).
The engine must obey the Update ordering invariants: entries_to_save must be
fsynced before committed_entries beyond them are applied (unless fast_apply),
and Commit(update) must be called to advance the stable/applied cursors.
"""
from __future__ import annotations

import random
from dataclasses import dataclass
from typing import List, Optional

from ..config import Config
from ..types import (
    EMPTY_STATE,
    ConfigChange,
    ConfigChangeType,
    Entry,
    EntryType,
    Message,
    MessageType,
    Snapshot,
    State,
    SystemCtx,
    Update,
    UpdateCommit,
    is_local_message,
    is_response_message,
)
from .logentry import ILogDB
from .raft import Raft

MT = MessageType


@dataclass
class PeerAddress:
    node_id: int
    address: str


def encode_config_change(cc: ConfigChange) -> bytes:
    """Compact fixed codec for config change commands (the reference uses
    protobuf; the payload is opaque to the protocol)."""
    addr = cc.address.encode()
    return b"%d|%d|%d|%d|%s" % (
        cc.config_change_id,
        int(cc.type),
        cc.node_id,
        1 if cc.initialize else 0,
        addr,
    )


def decode_config_change(data: bytes) -> ConfigChange:
    ccid, cctype, node_id, init, addr = data.split(b"|", 4)
    return ConfigChange(
        config_change_id=int(ccid),
        type=ConfigChangeType(int(cctype)),
        node_id=int(node_id),
        initialize=init == b"1",
        address=addr.decode(),
    )


class Peer:
    def __init__(self, raft: Raft, prev_state: State) -> None:
        self.raft = raft
        self.prev_state = prev_state

    # ------------------------------------------------------------- lifecycle
    @staticmethod
    def launch(
        cfg: Config,
        logdb: ILogDB,
        events=None,
        addresses: Optional[List[PeerAddress]] = None,
        initial: bool = False,
        new_node: bool = False,
        rng: Optional[random.Random] = None,
    ) -> "Peer":
        addresses = addresses or []
        _check_launch_request(cfg, addresses, initial, new_node)
        r = Raft(cfg, logdb, events=events, rng=rng)
        _, last_index = logdb.get_range()
        if new_node and not cfg.is_observer and not cfg.is_witness:
            r.become_follower(1, 0)
        if initial and new_node:
            _bootstrap(r, addresses)
        prev_state = EMPTY_STATE if last_index == 0 else r.raft_state()
        return Peer(r, prev_state)

    # ------------------------------------------------------------ local ops
    def tick(self) -> None:
        self.raft.handle(Message(type=MT.LOCAL_TICK, reject=False))

    def quiesced_tick(self) -> None:
        self.raft.handle(Message(type=MT.LOCAL_TICK, reject=True))

    def request_leader_transfer(self, target: int) -> None:
        self.raft.handle(
            Message(
                type=MT.LEADER_TRANSFER,
                to=self.raft.node_id,
                from_=target,
                hint=target,
            )
        )

    def propose_entries(self, entries: List[Entry]) -> None:
        self.raft.handle(
            Message(type=MT.PROPOSE, from_=self.raft.node_id, entries=entries)
        )

    def propose_config_change(self, cc: ConfigChange, key: int) -> None:
        data = encode_config_change(cc)
        self.raft.handle(
            Message(
                type=MT.PROPOSE,
                entries=[Entry(type=EntryType.CONFIG_CHANGE, cmd=data, key=key)],
            )
        )

    def apply_config_change(self, cc: ConfigChange) -> None:
        if cc.node_id == 0:
            self.raft.pending_config_change = False
            return
        self.raft.handle(
            Message(
                type=MT.CONFIG_CHANGE_EVENT,
                reject=False,
                hint=cc.node_id,
                hint_high=int(cc.type),
            )
        )

    def reject_config_change(self) -> None:
        self.raft.handle(Message(type=MT.CONFIG_CHANGE_EVENT, reject=True))

    def restore_remotes(self, ss: Snapshot) -> None:
        self.raft.handle(Message(type=MT.SNAPSHOT_RECEIVED, snapshot=ss))

    def report_unreachable_node(self, node_id: int) -> None:
        self.raft.handle(Message(type=MT.UNREACHABLE, from_=node_id))

    def report_snapshot_status(self, node_id: int, reject: bool) -> None:
        self.raft.handle(
            Message(type=MT.SNAPSHOT_STATUS, from_=node_id, reject=reject)
        )

    def read_index(self, ctx: SystemCtx) -> None:
        self.raft.handle(
            Message(type=MT.READ_INDEX, hint=ctx.low, hint_high=ctx.high)
        )

    def notify_raft_last_applied(self, last_applied: int) -> None:
        self.raft.applied = last_applied

    # -------------------------------------------------------------- messages
    def handle(self, m: Message) -> None:
        if is_local_message(m.type):
            raise RuntimeError("local message sent to Handle")
        known = (
            m.from_ in self.raft.remotes
            or m.from_ in self.raft.observers
            or m.from_ in self.raft.witnesses
        )
        if known or not is_response_message(m.type):
            self.raft.handle(m)

    # ------------------------------------------------------- update contract
    def has_update(self, more_entries_to_apply: bool) -> bool:
        r = self.raft
        pst = r.raft_state()
        if not pst.is_empty() and pst != self.prev_state:
            return True
        if r.log.inmem.snapshot is not None and not r.log.inmem.snapshot.is_empty():
            return True
        if r.msgs:
            return True
        if r.log.entries_to_save():
            return True
        if more_entries_to_apply and r.log.has_entries_to_apply():
            return True
        if r.ready_to_read:
            return True
        if r.dropped_entries or r.dropped_read_indexes:
            return True
        return False

    def has_entry_to_apply(self) -> bool:
        return self.raft.log.has_entries_to_apply()

    def get_update(self, more_entries_to_apply: bool, last_applied: int) -> Update:
        r = self.raft
        ud = Update(
            cluster_id=r.cluster_id,
            node_id=r.node_id,
            entries_to_save=r.log.entries_to_save(),
            messages=r.msgs,
            last_applied=last_applied,
            fast_apply=True,
        )
        if more_entries_to_apply:
            ud.committed_entries = r.log.entries_to_apply()
        if ud.committed_entries:
            ud.more_committed_entries = r.log.has_more_entries_to_apply(
                ud.committed_entries[-1].index
            )
        pst = r.raft_state()
        if pst != self.prev_state:
            ud.state = pst
        if r.log.inmem.snapshot is not None:
            ud.snapshot = r.log.inmem.snapshot
        if r.ready_to_read:
            ud.ready_to_reads = r.ready_to_read
        if r.dropped_entries:
            ud.dropped_entries = r.dropped_entries
        if r.dropped_read_indexes:
            ud.dropped_read_indexes = r.dropped_read_indexes
        _validate_update(ud)
        ud = _set_fast_apply(ud)
        ud.update_commit = get_update_commit(ud)
        return ud

    def commit(self, ud: Update) -> None:
        r = self.raft
        r.msgs = []
        r.dropped_entries = []
        r.dropped_read_indexes = []
        if not ud.state.is_empty():
            self.prev_state = ud.state
        if ud.update_commit.ready_to_read > 0:
            r.ready_to_read = []
        r.log.commit_update(ud.update_commit)

    def rate_limited(self) -> bool:
        """Whether new proposals should be refused because some replica's
        in-memory log exceeds Config.max_in_mem_log_size (cf.
        node.go:1095 handleProposals -> RateLimited)."""
        r = self.raft
        return r.rl.enabled and r.rl.rate_limited()

    def local_status(self):
        r = self.raft
        return {
            "cluster_id": r.cluster_id,
            "node_id": r.node_id,
            "applied": r.applied,
            "leader_id": r.leader_id,
            "state": r.state,
            "term": r.term,
            "vote": r.vote,
            "commit": r.log.committed,
            "last_index": r.log.last_index(),
        }


def launch_peer(*args, **kwargs) -> Peer:
    return Peer.launch(*args, **kwargs)


def _check_launch_request(
    cfg: Config, addresses: List[PeerAddress], initial: bool, new_node: bool
) -> None:
    if cfg.node_id == 0:
        raise ValueError("config.node_id must not be zero")
    if initial and new_node and not addresses:
        raise ValueError("addresses must be specified")
    unique = {a.address for a in addresses}
    if len(unique) != len(addresses):
        raise ValueError(f"duplicated address found {addresses}")


def _bootstrap(r: Raft, addresses: List[PeerAddress]) -> None:
    addresses = sorted(addresses, key=lambda a: a.node_id)
    ents = []
    for i, peer in enumerate(addresses):
        cc = ConfigChange(
            type=ConfigChangeType.ADD_NODE,
            node_id=peer.node_id,
            initialize=True,
            address=peer.address,
        )
        ents.append(
            Entry(
                type=EntryType.CONFIG_CHANGE,
                term=1,
                index=i + 1,
                cmd=encode_config_change(cc),
            )
        )
    r.log.append(ents)
    r.log.committed = len(ents)
    for peer in addresses:
        r.add_node(peer.node_id)


def _set_fast_apply(ud: Update) -> Update:
    ud.fast_apply = True
    if ud.snapshot is not None and not ud.snapshot.is_empty():
        ud.fast_apply = False
    if ud.fast_apply and ud.committed_entries and ud.entries_to_save:
        last_apply = ud.committed_entries[-1].index
        last_save = ud.entries_to_save[-1].index
        first_save = ud.entries_to_save[0].index
        if first_save <= last_apply <= last_save:
            ud.fast_apply = False
    return ud


def _validate_update(ud: Update) -> None:
    if ud.state.commit > 0 and ud.committed_entries:
        if ud.committed_entries[-1].index > ud.state.commit:
            raise RuntimeError("trying to apply not committed entry")
    if ud.committed_entries and ud.entries_to_save:
        if ud.committed_entries[-1].index > ud.entries_to_save[-1].index:
            raise RuntimeError("trying to apply not saved entry")


def get_update_commit(ud: Update) -> UpdateCommit:
    uc = UpdateCommit(
        ready_to_read=len(ud.ready_to_reads), last_applied=ud.last_applied
    )
    if ud.committed_entries:
        uc.processed = ud.committed_entries[-1].index
    if ud.entries_to_save:
        last = ud.entries_to_save[-1]
        uc.stable_log_to, uc.stable_log_term = last.index, last.term
    if ud.snapshot is not None and not ud.snapshot.is_empty():
        uc.stable_snapshot_to = ud.snapshot.index
        uc.processed = max(uc.processed, uc.stable_snapshot_to)
    return uc
