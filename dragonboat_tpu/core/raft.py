"""The scalar Raft state machine: full protocol semantics for one replica.

Behavior matches the reference implementation (cf. internal/raft/raft.go):
election with randomized timeouts and disruption defense, log replication
with per-follower flow control, quorum commit restricted to current-term
entries (Raft paper section 5.4.2), ReadIndex (thesis section 6.4), single
pending membership change, leadership transfer (thesis p29), check-quorum
leader step-down (thesis p69), observers (thesis section 4.2.1) and witnesses
(thesis section 11.7.2).

This scalar form is the semantic oracle for the vectorized kernel in
dragonboat_tpu.ops.kernel; structure here favors clarity over speed.
"""
from __future__ import annotations

import enum
import random
from typing import Callable, Dict, List, Optional, Tuple

from ..types import (
    NO_LEADER,
    NO_NODE,
    ConfigChangeType,
    Entry,
    EntryType,
    Message,
    MessageType,
    ReadyToRead,
    Snapshot,
    State,
    SystemCtx,
    is_leader_message,
)
from ..config import Config
from .. import settings
from .logentry import EntryLog, ErrCompacted, ILogDB
from .rate import RateLimiter, entries_mem_size
from .readindex import ReadIndexTracker
from .remote import Remote, RemoteState

MT = MessageType


class RaftNodeState(enum.IntEnum):
    """Replica roles; numbering matches reference raft.go:63-70.
    PRE_CANDIDATE extends the table for the pre-vote phase (thesis 9.6;
    the reference has no pre-vote)."""

    FOLLOWER = 0
    CANDIDATE = 1
    LEADER = 2
    OBSERVER = 3
    WITNESS = 4
    PRE_CANDIDATE = 5


class Raft:
    def __init__(
        self,
        cfg: Config,
        logdb: ILogDB,
        events=None,
        rng: Optional[random.Random] = None,
    ) -> None:
        cfg.validate()
        self.cluster_id = cfg.cluster_id
        self.node_id = cfg.node_id
        self.leader_id = NO_LEADER
        self.term = 0
        self.vote = NO_NODE
        self.applied = 0
        self.log = EntryLog(logdb)
        self.remotes: Dict[int, Remote] = {}
        self.observers: Dict[int, Remote] = {}
        self.witnesses: Dict[int, Remote] = {}
        self.state = RaftNodeState.FOLLOWER
        self.votes: Dict[int, bool] = {}
        self.msgs: List[Message] = []
        self.leader_transfer_target = NO_NODE
        self.is_leader_transfer_target = False
        self.pending_config_change = False
        self.read_index = ReadIndexTracker()
        self.ready_to_read: List[ReadyToRead] = []
        self.dropped_entries: List[Entry] = []
        self.dropped_read_indexes: List[SystemCtx] = []
        self.quiesced = False
        self.check_quorum = cfg.check_quorum
        self.pre_vote = cfg.pre_vote
        # Leader lease (Config.lease_read): a quorum of heartbeat acks
        # tagged with the current lease round's start tick grants a lease
        # of (election_rtt - margin) ticks FROM THE ROUND START — strictly
        # below the minimum randomized election timeout, so no rival can
        # be elected while a live lease serves local reads, as long as
        # clocks drift less than the margin per election window.
        self.lease_read = cfg.lease_read
        self.lease_margin = cfg.lease_margin_ticks() if cfg.lease_read else 0
        self.lease_until = 0  # tick_count bound (exclusive)
        self.lease_round_tick = 0  # current heartbeat round's start tick
        self.lease_acks: set = set()  # voting peers that acked this round
        self.clock_suspect_until = 0  # no grants/serves before this tick
        self.lease_served = 0  # reads served locally off the lease
        self.lease_fallback = 0  # lease-mode reads that fell back to quorum
        # protocol-event counters, the scalar twin of the kernel's
        # on-device counter plane (ops/state.CTR): incremented at the
        # point the event fires — a campaign launched, a leadership won,
        # a heartbeat message handed to the outbox per target, a
        # Replicate answered with reject — so the vector kernel's
        # per-lane counters and these stay differential-comparable.
        # Plain int reads on export paths (ExecEngine.counter_stats);
        # commit_advances is DERIVED (log.committed - _commit_origin,
        # index units) because committed moves at several sites but the
        # units advanced are what the kernel counts.
        self.elections_started = 0
        self.elections_won = 0
        self.heartbeats_sent = 0
        self.replicate_rejects = 0
        self.read_confirmations = 0
        self._commit_origin = 0
        self.tick_count = 0
        self.election_tick = 0
        self.heartbeat_tick = 0
        self.election_timeout = cfg.election_rtt
        self.heartbeat_timeout = cfg.heartbeat_rtt
        self.randomized_election_timeout = 0
        self.max_entry_size = settings.soft.max_entry_size
        # in-memory log size limiter (cf. raft.go:241 NewRateLimiter);
        # replicas over Config.max_in_mem_log_size report to the leader,
        # which then refuses proposals until the fleet drains
        self.rl = RateLimiter(cfg.max_in_mem_log_size)
        if self.rl.enabled:
            self.log.inmem.set_rate_limiter(self.rl)
        self.events = events
        self.rng = rng if rng is not None else random.Random()
        # test-only hook mirroring reference raft.go:1460-1472
        self.has_not_applied_config_change: Optional[Callable[[], bool]] = None

        st, members = logdb.node_state()
        for p in members.addresses:
            self.remotes[p] = Remote(next=1)
        for p in members.observers:
            self.observers[p] = Remote(next=1)
        for p in members.witnesses:
            self.witnesses[p] = Remote(next=1)
        if not st.is_empty():
            self._load_state(st)
        # recovered commit progress is not an "advance" this core made
        self._commit_origin = self.log.committed
        if cfg.is_observer:
            self.state = RaftNodeState.OBSERVER
            self.become_observer(self.term, NO_LEADER)
        elif cfg.is_witness:
            self.state = RaftNodeState.WITNESS
            self.become_witness(self.term, NO_LEADER)
        else:
            self.become_follower(self.term, NO_LEADER)

    # ------------------------------------------------------------------ util
    @property
    def commit_advances(self) -> int:
        # index units advanced since this core instantiated (kernel commits
        # once per step at the quorum fold, the scalar core per message —
        # events diverge but index units stay lockstep-identical)
        return self.log.committed - self._commit_origin

    def is_leader(self) -> bool:
        return self.state == RaftNodeState.LEADER

    def is_candidate(self) -> bool:
        return self.state == RaftNodeState.CANDIDATE

    def is_pre_candidate(self) -> bool:
        return self.state == RaftNodeState.PRE_CANDIDATE

    def is_follower(self) -> bool:
        return self.state == RaftNodeState.FOLLOWER

    def is_observer(self) -> bool:
        return self.state == RaftNodeState.OBSERVER

    def is_witness(self) -> bool:
        return self.state == RaftNodeState.WITNESS

    def _must_be_leader(self) -> None:
        if not self.is_leader():
            raise RuntimeError(f"{self._describe()} is not a leader")

    def _describe(self) -> str:
        return (
            f"[c{self.cluster_id},n{self.node_id}] t{self.term} "
            f"{self.state.name.lower()}"
        )

    def set_leader_id(self, leader_id: int) -> None:
        self.leader_id = leader_id
        if self.events is not None:
            self.events.leader_updated(
                self.cluster_id, self.node_id, leader_id, self.term
            )

    def num_voting_members(self) -> int:
        return len(self.remotes) + len(self.witnesses)

    def quorum(self) -> int:
        return self.num_voting_members() // 2 + 1

    def is_single_node_quorum(self) -> bool:
        return self.quorum() == 1

    def voting_members(self) -> Dict[int, Remote]:
        members = dict(self.remotes)
        members.update(self.witnesses)
        return members

    def nodes(self) -> List[int]:
        return (
            list(self.remotes) + list(self.observers) + list(self.witnesses)
        )

    def leader_transfering(self) -> bool:
        return self.leader_transfer_target != NO_NODE and self.is_leader()

    def abort_leader_transfer(self) -> None:
        self.leader_transfer_target = NO_NODE

    def self_removed(self) -> bool:
        if self.is_observer():
            return self.node_id not in self.observers
        if self.is_witness():
            return self.node_id not in self.witnesses
        return self.node_id not in self.remotes

    def raft_state(self) -> State:
        return State(term=self.term, vote=self.vote, commit=self.log.committed)

    def _load_state(self, st: State) -> None:
        if st.commit < self.log.committed or st.commit > self.log.last_index():
            raise RuntimeError(
                f"out of range state, commit {st.commit}, "
                f"range [{self.log.committed},{self.log.last_index()}]"
            )
        self.log.committed = st.commit
        self.term = st.term
        self.vote = st.vote

    def leader_has_quorum(self) -> bool:
        count = 0
        for nid, member in self.voting_members().items():
            if nid == self.node_id or member.is_active():
                count += 1
                member.set_not_active()
        return count >= self.quorum()

    # ------------------------------------------------------------------ tick
    def time_for_election(self) -> bool:
        return self.election_tick >= self.randomized_election_timeout

    def time_for_heartbeat(self) -> bool:
        return self.heartbeat_tick >= self.heartbeat_timeout

    def time_for_check_quorum(self) -> bool:
        return self.election_tick >= self.election_timeout

    def time_to_abort_leader_transfer(self) -> bool:
        return self.leader_transfering() and self.election_tick >= self.election_timeout

    def tick(self) -> None:
        self.quiesced = False
        self.tick_count += 1
        if self.is_leader():
            self._leader_tick()
        else:
            self._non_leader_tick()

    def _time_for_rate_limit_check(self) -> bool:
        # one limiter tick per election timeout (cf. raft.go:543-545)
        return self.tick_count % self.election_timeout == 0

    def _non_leader_tick(self) -> None:
        self.election_tick += 1
        if self._time_for_rate_limit_check() and self.rl.enabled:
            self.rl.tick()
            self._send_rate_limit_message()
        # non-voting members and witnesses never campaign (thesis 4.2.1)
        if self.is_observer() or self.is_witness():
            return
        if not self.self_removed() and self.time_for_election():
            self.election_tick = 0
            self.handle(Message(type=MT.ELECTION, from_=self.node_id))

    def _leader_tick(self) -> None:
        self._must_be_leader()
        self.election_tick += 1
        if self._time_for_rate_limit_check() and self.rl.enabled:
            # advance the limiter clock so stale follower reports age out
            self.rl.tick()
        abort_transfer = self.time_to_abort_leader_transfer()
        if self.time_for_check_quorum():
            self.election_tick = 0
            if self.check_quorum:
                self.handle(Message(type=MT.CHECK_QUORUM, from_=self.node_id))
        if abort_transfer:
            self.abort_leader_transfer()
        self.heartbeat_tick += 1
        if self.time_for_heartbeat():
            self.heartbeat_tick = 0
            self.handle(Message(type=MT.LEADER_HEARTBEAT, from_=self.node_id))

    def quiesced_tick(self) -> None:
        self.quiesced = True
        self.election_tick += 1

    def set_randomized_election_timeout(self) -> None:
        self.randomized_election_timeout = (
            self.election_timeout + self.rng.randrange(self.election_timeout)
        )

    # ------------------------------------------------------------------ send
    def _send(self, m: Message) -> None:
        m.from_ = self.node_id
        m.cluster_id = self.cluster_id
        # Request-routed messages (Propose/ReadIndex) and RequestVote carry
        # their own term; everything else is stamped with the current term
        # (cf. raft.go finalizeMessageTerm).
        if m.type not in (MT.PROPOSE, MT.READ_INDEX) and m.term == 0:
            if m.type != MT.REQUEST_VOTE:
                m.term = self.term
        self.msgs.append(m)

    def _make_replicate_message(
        self, to: int, next_idx: int, max_size: int
    ) -> Message:
        # Both lookups raise ErrCompacted when the follower's window has been
        # compacted away, triggering the snapshot fallback in the caller.
        term = self.log.term(next_idx - 1)
        entries = self.log.entries(next_idx, max_size)
        if entries:
            expected = next_idx - 1 + len(entries)
            if entries[-1].index != expected:
                raise RuntimeError(
                    f"expected last index {expected}, got {entries[-1].index}"
                )
        if to in self.witnesses:
            entries = _make_metadata_entries(entries)
        return Message(
            to=to,
            type=MT.REPLICATE,
            log_index=next_idx - 1,
            log_term=term,
            entries=entries,
            commit=self.log.committed,
        )

    def make_install_snapshot_message(self, to: int) -> Tuple[Message, int]:
        ss = self.log.get_snapshot()
        if ss.is_empty():
            raise RuntimeError(f"{self._describe()} got an empty snapshot")
        if to in self.witnesses:
            ss = _make_witness_snapshot(ss)
        m = Message(to=to, type=MT.INSTALL_SNAPSHOT, snapshot=ss)
        return m, ss.index

    def send_replicate_message(self, to: int) -> None:
        rp = (
            self.remotes.get(to)
            or self.observers.get(to)
            or self.witnesses.get(to)
        )
        if rp is None:
            raise RuntimeError(f"{self._describe()} no remote for {to}")
        if rp.is_paused():
            return
        try:
            m = self._make_replicate_message(to, rp.next, self.max_entry_size)
        except ErrCompacted:
            # log compacted away: fall back to snapshot (cf. raft.go:774-785)
            if not rp.is_active():
                return
            m, index = self.make_install_snapshot_message(to)
            rp.become_snapshot(index)
            self._send(m)
            return
        if m.entries:
            rp.progress(m.entries[-1].index)
        self._send(m)

    def broadcast_replicate_message(self) -> None:
        self._must_be_leader()
        for nid in self.nodes():
            if nid != self.node_id:
                self.send_replicate_message(nid)

    def send_heartbeat_message(
        self, to: int, hint: SystemCtx, match: int, lease_tick: int = 0
    ) -> None:
        self._send(
            Message(
                to=to,
                type=MT.HEARTBEAT,
                # the lease round tag rides the otherwise-unused heartbeat
                # log_index field (0 when leases are off — bit-identical);
                # followers echo it back verbatim in HEARTBEAT_RESP
                log_index=lease_tick,
                commit=min(match, self.log.committed),
                hint=hint.low,
                hint_high=hint.high,
            )
        )

    def broadcast_heartbeat_message(
        self, ctx: Optional[SystemCtx] = None, new_lease_round: bool = False
    ) -> None:
        self._must_be_leader()
        if ctx is None:
            if self.read_index.has_pending_request():
                ctx = self.read_index.peep_ctx()
            else:
                ctx = SystemCtx()
        tag = 0
        if self.lease_read:
            if new_lease_round:
                # a fresh quorum round opens ONLY on the periodic
                # heartbeat (hb_due): ctx-carrying ReadIndex broadcasts
                # stamp the CURRENT round without resetting its acks, or
                # read traffic would starve the lease of full rounds
                self.lease_round_tick = self.tick_count
                self.lease_acks = set()
            tag = self.lease_round_tick
        for nid, rm in self.voting_members().items():
            if nid != self.node_id:
                # counted per target at the send decision (the kernel's
                # counter increments at its broadcast sites the same way)
                self.heartbeats_sent += 1
                self.send_heartbeat_message(nid, ctx, rm.match, tag)
        if ctx.is_zero():
            for nid, rm in self.observers.items():
                self.heartbeats_sent += 1
                self.send_heartbeat_message(nid, ctx, rm.match, tag)

    def send_timeout_now_message(self, node_id: int) -> None:
        self._send(Message(type=MT.TIMEOUT_NOW, to=node_id))

    # ---------------------------------------------------------- commit/append
    def try_commit(self) -> bool:
        self._must_be_leader()
        matched = sorted(
            [v.match for v in self.remotes.values()]
            + [v.match for v in self.witnesses.values()]
        )
        q = matched[self.num_voting_members() - self.quorum()]
        # only current-term entries commit by counting (paper section 5.4.2)
        return self.log.try_commit(q, self.term)

    def append_entries(self, entries: List[Entry]) -> None:
        last_index = self.log.last_index()
        for i, e in enumerate(entries):
            e.term = self.term
            e.index = last_index + 1 + i
        self.log.append(entries)
        self.remotes[self.node_id].try_update(self.log.last_index())
        if self.is_single_node_quorum():
            self.try_commit()

    # ------------------------------------------------------ state transitions
    def _reset(self, term: int) -> None:
        if self.term != term:
            self.term = term
            self.vote = NO_LEADER
        self.votes = {}
        self.election_tick = 0
        self.heartbeat_tick = 0
        self.set_randomized_election_timeout()
        self.read_index = ReadIndexTracker()
        self.pending_config_change = False
        self.abort_leader_transfer()
        # any role transition revokes the lease outright — new leadership
        # must re-earn it via a fresh quorum heartbeat round
        self.lease_until = 0
        self.lease_round_tick = 0
        self.lease_acks = set()
        self._reset_remotes()

    def _reset_remotes(self) -> None:
        # (cf. raft.go resetRemotes: nextIndex = last log index + 1, own match)
        for group in (self.remotes, self.observers, self.witnesses):
            for nid in group:
                group[nid] = Remote(next=self.log.last_index() + 1)
                if nid == self.node_id:
                    group[nid].match = self.log.last_index()

    def become_observer(self, term: int, leader_id: int) -> None:
        if not self.is_observer():
            raise RuntimeError("transitioning to observer from non-observer")
        self._reset(term)
        self.set_leader_id(leader_id)

    def become_witness(self, term: int, leader_id: int) -> None:
        if not self.is_witness():
            raise RuntimeError("transitioning to witness from non-witness")
        self._reset(term)
        self.set_leader_id(leader_id)

    def become_follower(self, term: int, leader_id: int) -> None:
        if self.is_witness():
            raise RuntimeError("transitioning to follower from witness")
        self.state = RaftNodeState.FOLLOWER
        self._reset(term)
        self.set_leader_id(leader_id)

    def become_pre_candidate(self) -> None:
        """Enter the pre-vote poll (thesis 9.6): role and vote tallies
        change, but term, vote and timers stay untouched — the poll must
        be invisible to the rest of the group unless it wins."""
        if self.is_leader():
            raise RuntimeError("transitioning to pre-candidate from leader")
        if self.is_observer() or self.is_witness():
            raise RuntimeError("observer/witness cannot campaign")
        self.state = RaftNodeState.PRE_CANDIDATE
        self.votes = {}
        self.set_leader_id(NO_LEADER)

    def become_candidate(self) -> None:
        if self.is_leader():
            raise RuntimeError("transitioning to candidate from leader")
        if self.is_observer() or self.is_witness():
            raise RuntimeError("observer/witness cannot campaign")
        self.state = RaftNodeState.CANDIDATE
        # paper section 5.2: increment term, vote for self
        self._reset(self.term + 1)
        self.set_leader_id(NO_LEADER)
        self.vote = self.node_id

    def become_leader(self) -> None:
        if not (self.is_leader() or self.is_candidate()):
            raise RuntimeError(f"transitioning to leader from {self.state}")
        self.elections_won += 1
        self.state = RaftNodeState.LEADER
        self._reset(self.term)
        self.set_leader_id(self.node_id)
        # follower reports from a previous leadership stint are meaningless
        self.rl.reset_follower_state()
        self._pre_leader_promotion_handle_config_change()
        # commit a noop entry of the new term ASAP (thesis p72)
        self.append_entries([Entry(type=EntryType.APPLICATION)])

    def _pre_leader_promotion_handle_config_change(self) -> None:
        n = self._get_pending_config_change_count()
        if n > 1:
            raise RuntimeError("multiple uncommitted config change entries")
        if n == 1:
            self.pending_config_change = True

    def _get_pending_config_change_count(self) -> int:
        idx = self.log.committed + 1
        count = 0
        while True:
            ents = self.log.entries(idx, settings.soft.max_entries_to_apply_size)
            if not ents:
                return count
            count += sum(1 for e in ents if e.is_config_change())
            idx = ents[-1].index + 1

    # ------------------------------------------------------------- elections
    def _handle_vote_resp(self, from_: int, rejected: bool) -> int:
        if from_ not in self.votes:
            self.votes[from_] = not rejected
        return sum(1 for v in self.votes.values() if v)

    def pre_campaign(self) -> None:
        """Run the non-disruptive pre-vote poll at term+1. Nothing about
        this replica's durable state changes; a quorum of grants triggers
        the real campaign()."""
        self.become_pre_candidate()
        prospective = self.term + 1
        self._handle_vote_resp(self.node_id, False)
        if self.is_single_node_quorum():
            self.campaign()
            return
        for k in self.voting_members():
            if k == self.node_id:
                continue
            self._send(
                Message(
                    term=prospective,
                    to=k,
                    type=MT.REQUEST_PREVOTE,
                    log_index=self.log.last_index(),
                    log_term=self.log.last_term(),
                )
            )

    def campaign(self) -> None:
        # a REAL campaign (term bump + vote solicitation); pre-vote polls
        # are not counted — same rule as the kernel's _campaign counter
        self.elections_started += 1
        self.become_candidate()
        term = self.term
        if self.events is not None:
            self.events.campaign_launched(self.cluster_id, self.node_id, term)
        self._handle_vote_resp(self.node_id, False)
        if self.is_single_node_quorum():
            self.become_leader()
            return
        hint = 0
        if self.is_leader_transfer_target:
            hint = self.node_id
            self.is_leader_transfer_target = False
        for k in self.voting_members():
            if k == self.node_id:
                continue
            self._send(
                Message(
                    term=term,
                    to=k,
                    type=MT.REQUEST_VOTE,
                    log_index=self.log.last_index(),
                    log_term=self.log.last_term(),
                    hint=hint,
                )
            )

    # ------------------------------------------------------------ membership
    def add_node(self, node_id: int) -> None:
        self.pending_config_change = False
        if node_id == self.node_id and self.is_witness():
            raise RuntimeError("adding self while witness")
        if node_id in self.remotes:
            return
        if node_id in self.observers:
            # promote observer, inheriting progress
            rp = self.observers.pop(node_id)
            self.remotes[node_id] = rp
            if node_id == self.node_id:
                self.become_follower(self.term, self.leader_id)
        elif node_id in self.witnesses:
            raise RuntimeError("cannot promote witness to full member")
        else:
            self.remotes[node_id] = Remote(next=self.log.last_index() + 1)

    def add_observer(self, node_id: int) -> None:
        self.pending_config_change = False
        if node_id == self.node_id and not self.is_observer():
            raise RuntimeError("adding self as observer while not observer")
        if node_id in self.observers:
            return
        self.observers[node_id] = Remote(next=self.log.last_index() + 1)

    def add_witness(self, node_id: int) -> None:
        self.pending_config_change = False
        if node_id == self.node_id and not self.is_witness():
            raise RuntimeError("adding self as witness while not witness")
        if node_id in self.witnesses:
            return
        self.witnesses[node_id] = Remote(next=self.log.last_index() + 1)

    def remove_node(self, node_id: int) -> None:
        self.remotes.pop(node_id, None)
        self.observers.pop(node_id, None)
        self.witnesses.pop(node_id, None)
        self.pending_config_change = False
        if self.node_id == node_id and self.is_leader():
            self.become_follower(self.term, NO_LEADER)
        if self.leader_transfering() and self.leader_transfer_target == node_id:
            self.abort_leader_transfer()
        if self.is_leader() and self.num_voting_members() > 0:
            if self.try_commit():
                self.broadcast_replicate_message()

    # ------------------------------------------------------------- snapshots
    def restore(self, ss: Snapshot) -> bool:
        if ss.index <= self.log.committed:
            return False
        if not self.is_observer():
            for nid in ss.membership.observers:
                if nid == self.node_id:
                    raise RuntimeError("converting non-observer to observer")
        if not self.is_witness():
            for nid in ss.membership.witnesses:
                if nid == self.node_id:
                    raise RuntimeError("converting non-witness to witness")
        # snapshot at index X implies X committed (thesis p52)
        if self.log.match_term(ss.index, ss.term):
            self.log.commit_to(ss.index)
            return False
        self.log.restore(ss)
        return True

    def restore_remotes(self, ss: Snapshot) -> None:
        self.remotes = {}
        for nid in ss.membership.addresses:
            if nid == self.node_id and self.is_observer():
                self.become_follower(self.term, self.leader_id)
            if nid in self.witnesses:
                raise RuntimeError("witness cannot be promoted to full member")
            next_idx = self.log.last_index() + 1
            match = next_idx - 1 if nid == self.node_id else 0
            self.remotes[nid] = Remote(match=match, next=next_idx)
        if self.self_removed() and self.is_leader():
            self.become_follower(self.term, NO_LEADER)
        self.observers = {}
        for nid in ss.membership.observers:
            next_idx = self.log.last_index() + 1
            match = next_idx - 1 if nid == self.node_id else 0
            self.observers[nid] = Remote(match=match, next=next_idx)
        self.witnesses = {}
        for nid in ss.membership.witnesses:
            next_idx = self.log.last_index() + 1
            match = next_idx - 1 if nid == self.node_id else 0
            self.witnesses[nid] = Remote(match=match, next=next_idx)

    # -------------------------------------------------------------- dispatch
    def handle(self, m: Message) -> None:
        if not self._on_message_term_not_matched(m):
            if (
                m.term != 0
                and self.term != m.term
                and m.type not in (MT.REQUEST_PREVOTE, MT.REQUEST_PREVOTE_RESP)
            ):
                # pre-vote traffic legitimately carries the PROSPECTIVE
                # term (current+1) without anyone adopting it
                raise RuntimeError("mismatched term found")
            self._dispatch(m)

    def _drop_request_vote_from_high_term_node(self, m: Message) -> bool:
        # disruption defense (paper section 6 last paragraph, thesis p42);
        # applies to pre-vote polls identically — a live leader's lease
        # refuses the poll the same way it refuses the vote
        if (
            m.type not in (MT.REQUEST_VOTE, MT.REQUEST_PREVOTE)
            or not self.check_quorum
            or m.term <= self.term
        ):
            return False
        if m.hint == m.from_:
            # leader-transfer hint: let it through
            return False
        if self.leader_id != NO_LEADER and self.election_tick < self.election_timeout:
            return True
        return False

    def _on_message_term_not_matched(self, m: Message) -> bool:
        if m.term == 0 or m.term == self.term:
            return False
        if self._drop_request_vote_from_high_term_node(m):
            return True
        if m.term > self.term:
            if m.type == MT.REQUEST_PREVOTE:
                # a poll never changes our term; grant/reject at our term
                return False
            if m.type == MT.REQUEST_PREVOTE_RESP and not m.reject:
                # a granted poll echoes OUR prospective term back; the
                # real term bump happens only in campaign()
                return False
            leader_id = m.from_ if is_leader_message(m.type) else NO_LEADER
            if self.is_observer():
                self.become_observer(m.term, leader_id)
            elif self.is_witness():
                self.become_witness(m.term, leader_id)
            else:
                self.become_follower(m.term, leader_id)
            return False
        # m.term < self.term
        if m.type == MT.REQUEST_PREVOTE:
            # answer a stale poll with our (higher) term so the poller
            # abandons it and catches up (etcd MsgPreVote reject path)
            self._send(
                Message(to=m.from_, type=MT.REQUEST_PREVOTE_RESP, reject=True)
            )
            return True
        if is_leader_message(m.type) and self.check_quorum:
            # free a stuck higher-term candidate (etcd's
            # TestFreeStuckCandidateWithCheckQuorum corner case)
            self._send(Message(to=m.from_, type=MT.NOOP))
        return True

    def _dispatch(self, m: Message) -> None:
        handler = _HANDLERS[self.state].get(m.type)
        if handler is not None:
            handler(self, m)

    def _lookup_remote(self, from_: int) -> Optional[Remote]:
        return (
            self.remotes.get(from_)
            or self.observers.get(from_)
            or self.witnesses.get(from_)
        )

    # -------------------------------------------------- handlers (any state)
    def _handle_node_election(self, m: Message) -> None:
        if self.is_leader():
            return
        # don't campaign with a committed-but-unapplied config change
        # (quorum may differ after it applies; cf. raft.go:1484-1508)
        if self._has_config_change_to_apply():
            if self.events is not None:
                self.events.campaign_skipped(
                    self.cluster_id, self.node_id, self.term
                )
            return
        # leadership-transfer targets skip the poll: the transfer IS the
        # quorum's sanction (etcd campaignTransfer)
        if self.pre_vote and not self.is_leader_transfer_target:
            self.pre_campaign()
        else:
            self.campaign()

    def _has_config_change_to_apply(self) -> bool:
        if self.has_not_applied_config_change is not None:
            return self.has_not_applied_config_change()
        # Scan the committed-but-unapplied window for config changes. The
        # reference conservatively refuses to campaign whenever
        # committed > applied and notes the precise scan as a TODO
        # (raft.go:1461-1470); with entries held in memory the scan is
        # cheap. When the scan CANNOT see part of the window (storage
        # truncated a batch to nothing under max_entry_size, or the
        # window raced a compaction), fall back to the reference's
        # conservative answer — an unseen entry might be a config change,
        # and refusing one campaign beats campaigning across a quorum
        # change that hasn't applied yet.
        if self.log.committed <= self.applied:
            return False
        idx = max(self.applied + 1, self.log.first_index())
        while idx <= self.log.committed:
            try:
                ents = self.log.get_entries(
                    idx, self.log.committed + 1, settings.soft.max_entry_size
                )
            except ErrCompacted:
                return True
            if not ents:
                return True
            if any(e.is_config_change() for e in ents):
                return True
            idx = ents[-1].index + 1
        return False

    def _can_grant_vote(self, m: Message) -> bool:
        return self.vote in (NO_NODE, m.from_) or m.term > self.term

    def _handle_node_request_vote(self, m: Message) -> None:
        resp = Message(to=m.from_, type=MT.REQUEST_VOTE_RESP)
        can_grant = self._can_grant_vote(m)
        up_to_date = self.log.up_to_date(m.log_index, m.log_term)
        if can_grant and up_to_date:
            self.election_tick = 0
            self.vote = m.from_
        else:
            resp.reject = True
        self._send(resp)

    def _handle_node_request_prevote(self, m: Message) -> None:
        """Answer a pre-vote poll (thesis 9.6): grant iff the prospective
        term beats ours AND the poller's log is up to date. NOTHING in our
        state changes — no term adoption, no vote, no election-timer
        reset; that is the entire point of the phase."""
        resp = Message(to=m.from_, type=MT.REQUEST_PREVOTE_RESP)
        if m.term > self.term and self.log.up_to_date(m.log_index, m.log_term):
            # grants echo the prospective term so the poller's tally is
            # not dropped as stale
            resp.term = m.term
        else:
            resp.reject = True
        self._send(resp)

    def _handle_precandidate_request_prevote_resp(self, m: Message) -> None:
        if m.from_ in self.observers:
            return
        count = self._handle_vote_resp(m.from_, m.reject)
        if count == self.quorum():
            # the poll says the election is winnable: run the real one
            self.campaign()
        elif len(self.votes) - count == self.quorum():
            self.become_follower(self.term, NO_LEADER)

    def _handle_node_config_change(self, m: Message) -> None:
        if m.reject:
            self.pending_config_change = False
            return
        cctype = ConfigChangeType(m.hint_high)
        node_id = m.hint
        if cctype == ConfigChangeType.ADD_NODE:
            self.add_node(node_id)
        elif cctype == ConfigChangeType.REMOVE_NODE:
            self.remove_node(node_id)
        elif cctype == ConfigChangeType.ADD_OBSERVER:
            self.add_observer(node_id)
        elif cctype == ConfigChangeType.ADD_WITNESS:
            self.add_witness(node_id)
        else:
            raise RuntimeError("unexpected config change type")

    def _handle_local_tick(self, m: Message) -> None:
        if m.reject:
            self.quiesced_tick()
        else:
            self.tick()

    def _handle_restore_remote(self, m: Message) -> None:
        self.restore_remotes(m.snapshot)

    # ------------------------------------------------------- leader handlers
    def _handle_leader_heartbeat(self, m: Message) -> None:
        self.broadcast_heartbeat_message(new_lease_round=True)

    def _handle_leader_check_quorum(self, m: Message) -> None:
        self._must_be_leader()
        if not self.leader_has_quorum():
            self.become_follower(self.term, NO_LEADER)

    def _handle_leader_propose(self, m: Message) -> None:
        self._must_be_leader()
        if self.leader_transfering():
            self._report_dropped_proposal(m)
            return
        for i, e in enumerate(m.entries):
            if e.type == EntryType.CONFIG_CHANGE:
                if self.pending_config_change:
                    self._report_dropped_config_change(m.entries[i])
                    m.entries[i] = Entry(type=EntryType.APPLICATION)
                else:
                    self.pending_config_change = True
        self.append_entries(m.entries)
        self.broadcast_replicate_message()

    def _has_committed_entry_at_current_term(self) -> bool:
        if self.term == 0:
            raise RuntimeError("term is 0")
        try:
            last_committed_term = self.log.term(self.log.committed)
        except ErrCompacted:
            last_committed_term = 0
        return last_committed_term == self.term

    def _add_ready_to_read(self, index: int, ctx: SystemCtx) -> None:
        # one confirmed linearizable read point handed to the engine —
        # lease serves, single-node instant reads, leader quorum
        # confirmations and forwarded-read responses all land here, which
        # is exactly what the kernel's ready-queue pop counter tallies
        self.read_confirmations += 1
        self.ready_to_read.append(ReadyToRead(index=index, system_ctx=ctx))

    def lease_valid(self) -> bool:
        """Whether a live leader lease can serve a linearizable read
        locally RIGHT NOW. Expiry, step-down (any _reset), an in-flight
        leadership transfer and a host-reported clock anomaly all answer
        False — the read then rides the ReadIndex quorum path instead
        (degradation, not danger)."""
        return (
            self.lease_read
            and self.is_leader()
            and not self.leader_transfering()
            and self.tick_count >= self.clock_suspect_until
            and self.tick_count < self.lease_until
        )

    def set_clock_suspect(self, hold_ticks: int) -> None:
        """Host-side clock-anomaly report (the tick worker's backlog /
        backward-jump detector): revoke any live lease and refuse
        re-grants for hold_ticks, forcing reads onto the ReadIndex path
        until the tick plane has proven sane again."""
        self.clock_suspect_until = self.tick_count + max(int(hold_ticks), 0)
        self.lease_until = 0

    def _handle_leader_read_index(self, m: Message) -> None:
        self._must_be_leader()
        ctx = SystemCtx(low=m.hint, high=m.hint_high)
        if not self.is_single_node_quorum():
            if not self._has_committed_entry_at_current_term():
                # thesis section 6.4 step 1: leader must have committed an
                # entry at its current term first
                self._report_dropped_read_index(m)
                return
            if self.lease_valid():
                # lease fast path: the quorum promised not to elect anyone
                # else before lease_until, so the local committed index IS
                # the linearization point — no heartbeat round needed
                self.lease_served += 1
                self._add_ready_to_read(self.log.committed, ctx)
                if m.from_ not in (NO_NODE, self.node_id):
                    self._send(
                        Message(
                            to=m.from_,
                            type=MT.READ_INDEX_RESP,
                            log_index=self.log.committed,
                            hint=m.hint,
                            hint_high=m.hint_high,
                        )
                    )
                return
            if self.lease_read:
                self.lease_fallback += 1
            self.read_index.add_request(self.log.committed, ctx, m.from_)
            self.broadcast_heartbeat_message(ctx)
        else:
            self._add_ready_to_read(self.log.committed, ctx)
            if m.from_ != self.node_id and (
                m.from_ in self.observers or m.from_ in self.witnesses
            ):
                self._send(
                    Message(
                        to=m.from_,
                        type=MT.READ_INDEX_RESP,
                        log_index=self.log.committed,
                        hint=m.hint,
                        hint_high=m.hint_high,
                        commit=m.commit,
                    )
                )

    def _handle_leader_replicate_resp(self, m: Message, rp: Remote) -> None:
        self._must_be_leader()
        rp.set_active()
        if not m.reject:
            paused = rp.is_paused()
            if rp.try_update(m.log_index):
                rp.responded_to()
                if self.try_commit():
                    self.broadcast_replicate_message()
                elif paused:
                    self.send_replicate_message(m.from_)
                # leadership transfer (thesis p29): target caught up => go
                if (
                    self.leader_transfering()
                    and m.from_ == self.leader_transfer_target
                    and self.log.last_index() == rp.match
                ):
                    self.send_timeout_now_message(self.leader_transfer_target)
        else:
            if rp.decrease_to(m.log_index, m.hint):
                if rp.state == RemoteState.REPLICATE:
                    rp.become_retry()
                self.send_replicate_message(m.from_)

    def _handle_leader_heartbeat_resp(self, m: Message, rp: Remote) -> None:
        self._must_be_leader()
        rp.set_active()
        rp.wait_to_retry()
        if (
            self.lease_read
            and m.log_index != 0
            and m.log_index == self.lease_round_tick
            and m.from_ in self.voting_members()
        ):
            # an echo of the CURRENT round's tag from a voting peer;
            # stale-round echoes (tag < current) are ignored — renewals
            # only ever count one coherent quorum round, conservatively
            self.lease_acks.add(m.from_)
            if (
                len(self.lease_acks) + 1 >= self.quorum()
                and self.tick_count >= self.clock_suspect_until
            ):
                self.lease_until = max(
                    self.lease_until,
                    self.lease_round_tick
                    + self.election_timeout
                    - self.lease_margin,
                )
        if rp.match < self.log.last_index():
            self.send_replicate_message(m.from_)
        if m.hint != 0:
            self._handle_read_index_leader_confirmation(m)

    def _handle_read_index_leader_confirmation(self, m: Message) -> None:
        ctx = SystemCtx(low=m.hint, high=m.hint_high)
        ready = self.read_index.confirm(ctx, m.from_, self.quorum())
        for s in ready or []:
            if s.from_ in (NO_NODE, self.node_id):
                self._add_ready_to_read(s.index, s.ctx)
            else:
                self._send(
                    Message(
                        to=s.from_,
                        type=MT.READ_INDEX_RESP,
                        log_index=s.index,
                        hint=m.hint,
                        hint_high=m.hint_high,
                    )
                )

    def _handle_leader_transfer(self, m: Message, rp: Remote) -> None:
        self._must_be_leader()
        target = m.hint
        if target == NO_NODE:
            raise RuntimeError("leader transfer target not set")
        if self.leader_transfering():
            return
        if self.node_id == target:
            return
        self.leader_transfer_target = target
        self.election_tick = 0
        if rp.match == self.log.last_index():
            self.send_timeout_now_message(target)

    def _handle_leader_snapshot_status(self, m: Message, rp: Remote) -> None:
        if rp.state != RemoteState.SNAPSHOT:
            return
        if m.reject:
            rp.clear_pending_snapshot()
        rp.become_wait()

    def _handle_leader_unreachable(self, m: Message, rp: Remote) -> None:
        if rp.state == RemoteState.REPLICATE:
            rp.become_retry()

    def _send_rate_limit_message(self) -> None:
        """Follower -> leader in-mem size report (cf. raft.go:660-683
        sendRateLimitMessage): reports 0 unless this replica is over the
        bound, and discounts not-yet-committed entries the leader itself
        is still responsible for."""
        if self.leader_id == NO_LEADER or not self.rl.enabled:
            return
        reported = 0
        if self.rl.rate_limited():
            inmem = self.log.inmem
            low = max(self.log.committed + 1, inmem.marker_index)
            high = inmem.marker_index + len(inmem.entries)
            uncommitted = (
                entries_mem_size(inmem.get_entries(low, high))
                if low < high
                else 0
            )
            reported = max(self.rl.get() - uncommitted, 0)
        self._send(
            Message(type=MT.RATE_LIMIT, to=self.leader_id, hint=reported)
        )

    def _handle_leader_rate_limit(self, m: Message) -> None:
        """Record a follower's reported in-mem log size
        (cf. raft.go:1779-1785 handleLeaderRateLimit)."""
        if self.rl.enabled:
            self.rl.set_follower_state(m.from_, m.hint)

    # ----------------------------------------------------- follower handlers
    def _handle_follower_propose(self, m: Message) -> None:
        if self.leader_id == NO_LEADER:
            self._report_dropped_proposal(m)
            return
        fwd = Message(
            type=MT.PROPOSE,
            to=self.leader_id,
            entries=list(m.entries),
        )
        self._send(fwd)

    def _leader_is_available(self) -> None:
        self.election_tick = 0

    def _handle_follower_replicate(self, m: Message) -> None:
        self._leader_is_available()
        self.set_leader_id(m.from_)
        self._handle_replicate_message(m)

    def _handle_follower_heartbeat(self, m: Message) -> None:
        self._leader_is_available()
        self.set_leader_id(m.from_)
        self._handle_heartbeat_message(m)

    def _handle_follower_read_index(self, m: Message) -> None:
        if self.leader_id == NO_LEADER:
            self._report_dropped_read_index(m)
            return
        fwd = Message(
            type=MT.READ_INDEX,
            to=self.leader_id,
            hint=m.hint,
            hint_high=m.hint_high,
        )
        self._send(fwd)

    def _handle_follower_leader_transfer(self, m: Message) -> None:
        if self.leader_id == NO_LEADER:
            return
        self._send(
            Message(type=MT.LEADER_TRANSFER, to=self.leader_id, hint=m.hint)
        )

    def _handle_follower_read_index_resp(self, m: Message) -> None:
        self._leader_is_available()
        self.set_leader_id(m.from_)
        self._add_ready_to_read(
            m.log_index, SystemCtx(low=m.hint, high=m.hint_high)
        )

    def _handle_follower_install_snapshot(self, m: Message) -> None:
        self._leader_is_available()
        self.set_leader_id(m.from_)
        self._handle_install_snapshot_message(m)

    def _handle_follower_timeout_now(self, m: Message) -> None:
        # transfer fast path: behave as if the election timer fired (thesis p29)
        self.election_tick = self.randomized_election_timeout
        self.is_leader_transfer_target = True
        self.tick()
        self.is_leader_transfer_target = False

    # ---------------------------------------------------- candidate handlers
    def _handle_candidate_propose(self, m: Message) -> None:
        self._report_dropped_proposal(m)

    def _handle_candidate_read_index(self, m: Message) -> None:
        self._report_dropped_read_index(m)

    def _handle_candidate_replicate(self, m: Message) -> None:
        # a Replicate at our term implies an established leader (paper 5.2)
        self.become_follower(self.term, m.from_)
        self._handle_replicate_message(m)

    def _handle_candidate_install_snapshot(self, m: Message) -> None:
        self.become_follower(self.term, m.from_)
        self._handle_install_snapshot_message(m)

    def _handle_candidate_heartbeat(self, m: Message) -> None:
        self.become_follower(self.term, m.from_)
        self._handle_heartbeat_message(m)

    def _handle_candidate_request_vote_resp(self, m: Message) -> None:
        if m.from_ in self.observers:
            return
        count = self._handle_vote_resp(m.from_, m.reject)
        if count == self.quorum():
            self.become_leader()
            self.broadcast_replicate_message()
        elif len(self.votes) - count == self.quorum():
            # all hope lost for this term (etcd behavior)
            self.become_follower(self.term, NO_LEADER)

    # ----------------------------------------------------- message mechanics
    def _handle_replicate_message(self, m: Message) -> None:
        resp = Message(to=m.from_, type=MT.REPLICATE_RESP)
        if m.log_index < self.log.committed:
            resp.log_index = self.log.committed
            self._send(resp)
            return
        if self.log.match_term(m.log_index, m.log_term):
            self.log.try_append(m.log_index, m.entries)
            last_idx = m.log_index + len(m.entries)
            self.log.commit_to(min(last_idx, m.commit))
            resp.log_index = last_idx
        else:
            resp.reject = True
            self.replicate_rejects += 1
            resp.log_index = m.log_index
            resp.hint = self.log.last_index()
            if self.events is not None:
                self.events.replication_rejected(
                    self.cluster_id, self.node_id, m.log_index, m.log_term, m.from_
                )
        self._send(resp)

    def _handle_heartbeat_message(self, m: Message) -> None:
        self.log.commit_to(m.commit)
        self._send(
            Message(
                to=m.from_,
                type=MT.HEARTBEAT_RESP,
                # echo the leader's lease round tag (0 when leases off)
                log_index=m.log_index,
                hint=m.hint,
                hint_high=m.hint_high,
            )
        )

    def _handle_install_snapshot_message(self, m: Message) -> None:
        resp = Message(to=m.from_, type=MT.REPLICATE_RESP)
        if self.restore(m.snapshot):
            resp.log_index = self.log.last_index()
        else:
            resp.log_index = self.log.committed
            if self.events is not None:
                self.events.snapshot_rejected(
                    self.cluster_id,
                    self.node_id,
                    m.snapshot.index,
                    m.snapshot.term,
                    m.from_,
                )
        self._send(resp)

    # --------------------------------------------------------------- reports
    def _report_dropped_proposal(self, m: Message) -> None:
        self.dropped_entries.extend(m.entries)
        if self.events is not None:
            self.events.proposal_dropped(self.cluster_id, self.node_id, m.entries)

    def _report_dropped_config_change(self, e: Entry) -> None:
        self.dropped_entries.append(e)

    def _report_dropped_read_index(self, m: Message) -> None:
        self.dropped_read_indexes.append(SystemCtx(low=m.hint, high=m.hint_high))
        if self.events is not None:
            self.events.read_index_dropped(self.cluster_id, self.node_id)


def _make_metadata_entries(entries: List[Entry]) -> List[Entry]:
    """Witnesses receive metadata-only entries except config changes
    (cf. raft.go:742-756)."""
    out = []
    for e in entries:
        if e.type != EntryType.CONFIG_CHANGE:
            out.append(Entry(type=EntryType.METADATA, index=e.index, term=e.term))
        else:
            out.append(e)
    return out


def _make_witness_snapshot(ss: Snapshot) -> Snapshot:
    """Witness replicas get a real (non-dummy) snapshot record with the data
    payload stripped (cf. raft.go:699-707)."""
    return Snapshot(
        filepath="",
        file_size=0,
        index=ss.index,
        term=ss.term,
        membership=ss.membership,
        files=[],
        checksum=ss.checksum,
        dummy=False,
        cluster_id=ss.cluster_id,
        witness=True,
    )


def _lw(f):
    """Wrap a leader handler that needs the sender's Remote
    (cf. raft.go lw())."""

    def wrapped(r: Raft, m: Message) -> None:
        rp = r._lookup_remote(m.from_)
        if rp is None:
            return
        f(r, m, rp)

    return wrapped


# Handler table [state][message type] mirroring reference raft.go:2037-2098;
# messages with no handler for the current state are silently dropped.
_HANDLERS: Dict[RaftNodeState, Dict[MessageType, Callable]] = {
    RaftNodeState.CANDIDATE: {
        MT.HEARTBEAT: Raft._handle_candidate_heartbeat,
        MT.PROPOSE: Raft._handle_candidate_propose,
        MT.READ_INDEX: Raft._handle_candidate_read_index,
        MT.REPLICATE: Raft._handle_candidate_replicate,
        MT.INSTALL_SNAPSHOT: Raft._handle_candidate_install_snapshot,
        MT.REQUEST_VOTE_RESP: Raft._handle_candidate_request_vote_resp,
        MT.ELECTION: Raft._handle_node_election,
        MT.REQUEST_VOTE: Raft._handle_node_request_vote,
        MT.REQUEST_PREVOTE: Raft._handle_node_request_prevote,
        MT.CONFIG_CHANGE_EVENT: Raft._handle_node_config_change,
        MT.LOCAL_TICK: Raft._handle_local_tick,
        MT.SNAPSHOT_RECEIVED: Raft._handle_restore_remote,
    },
    RaftNodeState.PRE_CANDIDATE: {
        MT.HEARTBEAT: Raft._handle_candidate_heartbeat,
        MT.PROPOSE: Raft._handle_candidate_propose,
        MT.READ_INDEX: Raft._handle_candidate_read_index,
        MT.REPLICATE: Raft._handle_candidate_replicate,
        MT.INSTALL_SNAPSHOT: Raft._handle_candidate_install_snapshot,
        MT.REQUEST_PREVOTE_RESP: Raft._handle_precandidate_request_prevote_resp,
        MT.ELECTION: Raft._handle_node_election,
        MT.REQUEST_VOTE: Raft._handle_node_request_vote,
        MT.REQUEST_PREVOTE: Raft._handle_node_request_prevote,
        MT.CONFIG_CHANGE_EVENT: Raft._handle_node_config_change,
        MT.LOCAL_TICK: Raft._handle_local_tick,
        MT.SNAPSHOT_RECEIVED: Raft._handle_restore_remote,
    },
    RaftNodeState.FOLLOWER: {
        MT.PROPOSE: Raft._handle_follower_propose,
        MT.REPLICATE: Raft._handle_follower_replicate,
        MT.HEARTBEAT: Raft._handle_follower_heartbeat,
        MT.READ_INDEX: Raft._handle_follower_read_index,
        MT.LEADER_TRANSFER: Raft._handle_follower_leader_transfer,
        MT.READ_INDEX_RESP: Raft._handle_follower_read_index_resp,
        MT.INSTALL_SNAPSHOT: Raft._handle_follower_install_snapshot,
        MT.ELECTION: Raft._handle_node_election,
        MT.REQUEST_VOTE: Raft._handle_node_request_vote,
        MT.REQUEST_PREVOTE: Raft._handle_node_request_prevote,
        MT.TIMEOUT_NOW: Raft._handle_follower_timeout_now,
        MT.CONFIG_CHANGE_EVENT: Raft._handle_node_config_change,
        MT.LOCAL_TICK: Raft._handle_local_tick,
        MT.SNAPSHOT_RECEIVED: Raft._handle_restore_remote,
    },
    RaftNodeState.LEADER: {
        MT.LEADER_HEARTBEAT: Raft._handle_leader_heartbeat,
        MT.CHECK_QUORUM: Raft._handle_leader_check_quorum,
        MT.PROPOSE: Raft._handle_leader_propose,
        MT.READ_INDEX: Raft._handle_leader_read_index,
        MT.REPLICATE_RESP: _lw(Raft._handle_leader_replicate_resp),
        MT.HEARTBEAT_RESP: _lw(Raft._handle_leader_heartbeat_resp),
        MT.SNAPSHOT_STATUS: _lw(Raft._handle_leader_snapshot_status),
        MT.UNREACHABLE: _lw(Raft._handle_leader_unreachable),
        MT.LEADER_TRANSFER: _lw(Raft._handle_leader_transfer),
        MT.ELECTION: Raft._handle_node_election,
        MT.REQUEST_VOTE: Raft._handle_node_request_vote,
        MT.REQUEST_PREVOTE: Raft._handle_node_request_prevote,
        MT.CONFIG_CHANGE_EVENT: Raft._handle_node_config_change,
        MT.LOCAL_TICK: Raft._handle_local_tick,
        MT.SNAPSHOT_RECEIVED: Raft._handle_restore_remote,
        MT.RATE_LIMIT: Raft._handle_leader_rate_limit,
    },
    RaftNodeState.OBSERVER: {
        MT.HEARTBEAT: Raft._handle_follower_heartbeat,
        MT.REPLICATE: Raft._handle_follower_replicate,
        MT.INSTALL_SNAPSHOT: Raft._handle_follower_install_snapshot,
        MT.PROPOSE: Raft._handle_follower_propose,
        MT.READ_INDEX: Raft._handle_follower_read_index,
        MT.READ_INDEX_RESP: Raft._handle_follower_read_index_resp,
        MT.CONFIG_CHANGE_EVENT: Raft._handle_node_config_change,
        MT.LOCAL_TICK: Raft._handle_local_tick,
        MT.SNAPSHOT_RECEIVED: Raft._handle_restore_remote,
    },
    RaftNodeState.WITNESS: {
        MT.HEARTBEAT: Raft._handle_follower_heartbeat,
        MT.REPLICATE: Raft._handle_follower_replicate,
        MT.INSTALL_SNAPSHOT: Raft._handle_follower_install_snapshot,
        MT.REQUEST_VOTE: Raft._handle_node_request_vote,
        MT.REQUEST_PREVOTE: Raft._handle_node_request_prevote,
        MT.CONFIG_CHANGE_EVENT: Raft._handle_node_config_change,
        MT.LOCAL_TICK: Raft._handle_local_tick,
        MT.SNAPSHOT_RECEIVED: Raft._handle_restore_remote,
    },
}
