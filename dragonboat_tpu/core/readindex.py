"""ReadIndex protocol bookkeeping (Raft thesis section 6.4).

Tracks pending read contexts in FIFO order; when the quorum of heartbeat
acknowledgements for a context arrives, that context and everything queued
before it become ready (cf. internal/raft/readindex.go:31-116).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..types import SystemCtx


@dataclass(slots=True)
class ReadStatus:
    index: int
    from_: int
    ctx: SystemCtx
    confirmed: Set[int] = field(default_factory=set)


class ReadIndexTracker:
    def __init__(self) -> None:
        self.pending: Dict[Tuple[int, int], ReadStatus] = {}
        self.queue: List[Tuple[int, int]] = []

    @staticmethod
    def _key(ctx: SystemCtx) -> Tuple[int, int]:
        return (ctx.low, ctx.high)

    def add_request(self, index: int, ctx: SystemCtx, from_: int) -> None:
        key = self._key(ctx)
        if key in self.pending:
            return
        if self.queue:
            last = self.pending[self.queue[-1]]
            if index < last.index:
                raise RuntimeError(
                    f"index moved backward in readIndex, {index}:{last.index}"
                )
        self.queue.append(key)
        self.pending[key] = ReadStatus(index=index, from_=from_, ctx=ctx)

    def has_pending_request(self) -> bool:
        return bool(self.queue)

    def peep_ctx(self) -> SystemCtx:
        return self.pending[self.queue[-1]].ctx

    def confirm(
        self, ctx: SystemCtx, from_: int, quorum: int
    ) -> Optional[List[ReadStatus]]:
        key = self._key(ctx)
        status = self.pending.get(key)
        if status is None:
            return None
        status.confirmed.add(from_)
        # +1 accounts for the leader itself.
        if len(status.confirmed) + 1 < quorum:
            return None
        ready: List[ReadStatus] = []
        for i, pkey in enumerate(self.queue):
            s = self.pending[pkey]
            ready.append(s)
            if pkey == key:
                # Everything queued at or before the confirmed ctx reads at the
                # confirmed index (indexes are monotone along the queue).
                for v in ready:
                    v.index = s.index
                self.queue = self.queue[i + 1 :]
                for v in ready:
                    del self.pending[self._key(v.ctx)]
                return ready
        return None
