"""Two-tier Raft log view: stable storage (ILogDB) + recent in-memory entries.

Semantics follow the reference's entryLog/inMemory pair
(cf. internal/raft/logentry.go:78-401, internal/raft/inmemory.go:36-246):
the in-memory tier holds entries not yet applied, with a saved_to watermark
tracking what has been fsynced; term lookups merge both tiers.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Protocol, Tuple

from ..types import (
    Entry,
    Membership,
    Snapshot,
    State,
    UpdateCommit,
    assert_contiguous,
    limit_entry_size,
)
from .. import settings
from .rate import entries_mem_size


class ErrCompacted(Exception):
    """The requested log range has been compacted away."""


class ErrUnavailable(Exception):
    """The requested log range is beyond the last index."""


class ILogDB(Protocol):
    """Read view over stable log storage used by the Raft core
    (cf. internal/raft/logentry.go:45-73)."""

    def node_state(self) -> Tuple[State, Membership]: ...

    def get_range(self) -> Tuple[int, int]:  # (first_index, last_index)
        ...

    def term(self, index: int) -> int:  # raises ErrCompacted/ErrUnavailable
        ...

    def entries(self, low: int, high: int, max_size: int) -> List[Entry]: ...

    def snapshot(self) -> Snapshot: ...

    def append(self, entries: List[Entry]) -> None: ...

    def apply_snapshot(self, ss: Snapshot) -> None: ...

    def set_state(self, st: State) -> None: ...

    def create_snapshot(self, ss: Snapshot) -> None: ...

    def compact(self, index: int) -> None: ...


class InMemLogDB:
    """In-memory ILogDB used by tests and by the loopback slice; mirrors the
    reference's TestLogDB (internal/raft/logdb_test.go) with LogReader-style
    marker semantics."""

    def __init__(self) -> None:
        self._state = State()
        self._membership = Membership()
        # entries[0] is a marker entry at (marker_index, marker_term).
        self._marker_index = 0
        self._marker_term = 0
        self._entries: List[Entry] = []
        self._snapshot = Snapshot()

    # -- read view -----------------------------------------------------------
    def node_state(self) -> Tuple[State, Membership]:
        return self._state, self._membership

    def get_range(self) -> Tuple[int, int]:
        return self._marker_index + 1, self._marker_index + len(self._entries)

    def term(self, index: int) -> int:
        if index == self._marker_index:
            return self._marker_term
        if index < self._marker_index:
            raise ErrCompacted
        if index > self._marker_index + len(self._entries):
            raise ErrUnavailable
        return self._entries[index - self._marker_index - 1].term

    def entries(self, low: int, high: int, max_size: int) -> List[Entry]:
        if low <= self._marker_index:
            raise ErrCompacted
        if high > self._marker_index + len(self._entries) + 1:
            raise ErrUnavailable
        ents = self._entries[
            low - self._marker_index - 1 : high - self._marker_index - 1
        ]
        return limit_entry_size(list(ents), max_size)

    def snapshot(self) -> Snapshot:
        return self._snapshot

    # -- write path ----------------------------------------------------------
    def append(self, entries: List[Entry]) -> None:
        if not entries:
            return
        assert_contiguous(entries)
        first = entries[0].index
        if first <= self._marker_index:
            raise RuntimeError(
                f"appending at {first} below marker {self._marker_index}"
            )
        if first > self._marker_index + len(self._entries) + 1:
            raise RuntimeError(
                f"log hole: append at {first}, last {self._marker_index + len(self._entries)}"
            )
        keep = first - self._marker_index - 1
        self._entries = self._entries[:keep] + list(entries)

    def set_state(self, st: State) -> None:
        self._state = st

    def set_membership(self, m: Membership) -> None:
        self._membership = m

    def apply_snapshot(self, ss: Snapshot) -> None:
        self._snapshot = ss
        self._marker_index = ss.index
        self._marker_term = ss.term
        self._entries = []

    def create_snapshot(self, ss: Snapshot) -> None:
        self._snapshot = ss

    def compact(self, index: int) -> None:
        if index <= self._marker_index:
            raise ErrCompacted
        last = self._marker_index + len(self._entries)
        if index > last:
            raise ErrUnavailable
        term = self.term(index)
        self._entries = self._entries[index - self._marker_index :]
        self._marker_index = index
        self._marker_term = term


class InMemory:
    """Recent, not-yet-applied log entries with a saved-to watermark
    (cf. internal/raft/inmemory.go). Tracks its own byte size and mirrors
    it into an attached RateLimiter (cf. inmemory.go rl accounting) so the
    replica can report/enforce Config.max_in_mem_log_size."""

    __slots__ = ("entries", "marker_index", "saved_to", "snapshot", "_rl",
                 "_bytes")

    def __init__(self, last_index: int) -> None:
        self.entries: List[Entry] = []
        self.marker_index = last_index + 1
        self.saved_to = last_index
        self.snapshot: Optional[Snapshot] = None
        self._rl = None
        self._bytes = 0

    def set_rate_limiter(self, rl) -> None:
        self._rl = rl
        rl.set(self._bytes)

    def _set_bytes(self, n: int) -> None:
        self._bytes = n
        if self._rl is not None:
            self._rl.set(n)

    def get_entries(self, low: int, high: int) -> List[Entry]:
        upper = self.marker_index + len(self.entries)
        if low > high or low < self.marker_index:
            raise RuntimeError(
                f"invalid range [{low},{high}) marker {self.marker_index}"
            )
        if high > upper:
            raise RuntimeError(f"invalid high {high}, upper {upper}")
        return self.entries[low - self.marker_index : high - self.marker_index]

    def get_snapshot_index(self) -> Optional[int]:
        return self.snapshot.index if self.snapshot is not None else None

    def get_last_index(self) -> Optional[int]:
        if self.entries:
            return self.entries[-1].index
        return self.get_snapshot_index()

    def get_term(self, index: int) -> Optional[int]:
        if index < self.marker_index:
            si = self.get_snapshot_index()
            if si is not None and si == index:
                return self.snapshot.term
            return None
        last = self.get_last_index()
        if last is not None and index <= last:
            return self.entries[index - self.marker_index].term
        return None

    def entries_to_save(self) -> List[Entry]:
        idx = self.saved_to + 1
        if idx - self.marker_index > len(self.entries):
            return []
        return self.entries[idx - self.marker_index :]

    def saved_log_to(self, index: int, term: int) -> None:
        if index < self.marker_index or not self.entries:
            return
        if (
            index > self.entries[-1].index
            or term != self.entries[index - self.marker_index].term
        ):
            return
        self.saved_to = index

    def applied_log_to(self, index: int) -> None:
        if index < self.marker_index or not self.entries:
            return
        if index > self.entries[-1].index:
            return
        dropped = self.entries[: index - self.marker_index]
        self.entries = self.entries[index - self.marker_index :]
        self.marker_index = index
        if dropped:
            self._set_bytes(max(0, self._bytes - entries_mem_size(dropped)))

    def saved_snapshot_to(self, index: int) -> None:
        si = self.get_snapshot_index()
        if si is not None and si == index:
            self.snapshot = None

    def commit_update(self, cu: UpdateCommit) -> None:
        if cu.stable_log_to > 0:
            self.saved_log_to(cu.stable_log_to, cu.stable_log_term)
        if cu.stable_snapshot_to > 0:
            self.saved_snapshot_to(cu.stable_snapshot_to)

    def merge(self, ents: List[Entry]) -> None:
        first_new = ents[0].index
        tail = self.marker_index + len(self.entries)
        if first_new == tail:
            self.entries = self.entries + list(ents)
            self._set_bytes(self._bytes + entries_mem_size(ents))
        elif first_new <= self.marker_index:
            self.marker_index = first_new
            self.entries = list(ents)
            self.saved_to = first_new - 1
            self._set_bytes(entries_mem_size(ents))
        else:
            existing = self.get_entries(self.marker_index, first_new)
            self.entries = list(existing) + list(ents)
            self.saved_to = min(self.saved_to, first_new - 1)
            self._set_bytes(entries_mem_size(self.entries))

    def restore(self, ss: Snapshot) -> None:
        self.snapshot = ss
        self.marker_index = ss.index + 1
        self.entries = []
        self.saved_to = ss.index
        self._set_bytes(0)


class EntryLog:
    """Merged log view over ILogDB + InMemory; tracks committed/processed
    cursors (cf. internal/raft/logentry.go:78-84)."""

    __slots__ = ("logdb", "inmem", "committed", "processed")

    def __init__(self, logdb: ILogDB) -> None:
        first_index, last_index = logdb.get_range()
        self.logdb = logdb
        self.inmem = InMemory(last_index)
        self.committed = first_index - 1
        self.processed = first_index - 1

    # -- index boundaries ----------------------------------------------------
    def first_index(self) -> int:
        si = self.inmem.get_snapshot_index()
        if si is not None:
            return si + 1
        return self.logdb.get_range()[0]

    def last_index(self) -> int:
        li = self.inmem.get_last_index()
        if li is not None:
            return li
        return self.logdb.get_range()[1]

    def _term_entry_range(self) -> Tuple[int, int]:
        return self.first_index() - 1, self.last_index()

    def _entry_range(self) -> Optional[Tuple[int, int]]:
        if self.inmem.snapshot is not None and not self.inmem.entries:
            return None
        return self.first_index(), self.last_index()

    def last_term(self) -> int:
        return self.term(self.last_index())

    def term(self, index: int) -> int:
        """Returns 0 for out-of-window indexes (matching the reference's
        (0, nil) return); raises ErrCompacted/ErrUnavailable when storage
        reports them for in-window indexes."""
        first, last = self._term_entry_range()
        if index < first or index > last:
            return 0
        t = self.inmem.get_term(index)
        if t is not None:
            return t
        return self.logdb.term(index)

    # -- entry access --------------------------------------------------------
    def _check_bound(self, low: int, high: int) -> None:
        if low > high:
            raise RuntimeError(f"input low {low} > high {high}")
        rng = self._entry_range()
        if rng is None:
            raise ErrCompacted
        first, last = rng
        if low < first:
            raise ErrCompacted
        if high > last + 1:
            raise RuntimeError(
                f"requested range [{low},{high}) out of bound [{first},{last}]"
            )

    def get_entries(self, low: int, high: int, max_size: int) -> List[Entry]:
        self._check_bound(low, high)
        if low == high:
            return []
        marker = self.inmem.marker_index
        ents: List[Entry] = []
        if low < marker:
            ents = self.logdb.entries(low, min(high, marker), max_size)
            if len(ents) < min(high, marker) - low:
                # storage truncated by max_size; don't cross into inmem
                return ents
        if high > marker:
            lower = max(low, marker)
            inmem = self.inmem.get_entries(lower, high)
            if inmem:
                ents = list(ents) + list(inmem)
        return limit_entry_size(ents, max_size)

    def entries(self, start: int, max_size: int) -> List[Entry]:
        if start > self.last_index():
            return []
        return self.get_entries(start, self.last_index() + 1, max_size)

    def get_snapshot(self) -> Snapshot:
        if self.inmem.snapshot is not None:
            return self.inmem.snapshot
        return self.logdb.snapshot()

    # -- apply cursors -------------------------------------------------------
    def first_not_applied_index(self) -> int:
        return max(self.processed + 1, self.first_index())

    def to_apply_index_limit(self) -> int:
        return self.committed + 1

    def has_entries_to_apply(self) -> bool:
        return self.to_apply_index_limit() > self.first_not_applied_index()

    def has_more_entries_to_apply(self, applied_to: int) -> bool:
        return self.committed > applied_to

    def entries_to_apply(self, limit: Optional[int] = None) -> List[Entry]:
        if limit is None:
            limit = settings.soft.max_entries_to_apply_size
        if self.has_entries_to_apply():
            return self.get_entries(
                self.first_not_applied_index(), self.to_apply_index_limit(), limit
            )
        return []

    def entries_to_save(self) -> List[Entry]:
        return self.inmem.entries_to_save()

    # -- append/commit -------------------------------------------------------
    def try_append(self, index: int, ents: List[Entry]) -> bool:
        conflict = self.get_conflict_index(ents)
        if conflict != 0:
            if conflict <= self.committed:
                raise RuntimeError(
                    f"entry {conflict} conflicts with committed entry "
                    f"(committed {self.committed})"
                )
            self.append(ents[conflict - index - 1 :])
            return True
        return False

    def append(self, entries: List[Entry]) -> None:
        if not entries:
            return
        if entries[0].index <= self.committed:
            raise RuntimeError(
                f"committed entries being changed, committed {self.committed}, "
                f"first index {entries[0].index}"
            )
        self.inmem.merge(entries)

    def get_conflict_index(self, entries: List[Entry]) -> int:
        for e in entries:
            if not self.match_term(e.index, e.term):
                return e.index
        return 0

    def commit_to(self, index: int) -> None:
        if index <= self.committed:
            return
        if index > self.last_index():
            raise RuntimeError(
                f"invalid commitTo index {index}, lastIndex {self.last_index()}"
            )
        self.committed = index

    def commit_update(self, cu: UpdateCommit) -> None:
        self.inmem.commit_update(cu)
        if cu.processed > 0:
            if cu.processed < self.processed or cu.processed > self.committed:
                raise RuntimeError(
                    f"invalid processed {cu.processed}, "
                    f"current {self.processed}, committed {self.committed}"
                )
            self.processed = cu.processed
        if cu.last_applied > 0:
            if cu.last_applied > self.committed or cu.last_applied > self.processed:
                raise RuntimeError(
                    f"invalid last_applied {cu.last_applied}, "
                    f"committed {self.committed} processed {self.processed}"
                )
            self.inmem.applied_log_to(cu.last_applied)

    def match_term(self, index: int, term: int) -> bool:
        try:
            t = self.term(index)
        except (ErrCompacted, ErrUnavailable):
            return False
        return t == term

    def up_to_date(self, index: int, term: int) -> bool:
        last_term = self.term(self.last_index())
        if term > last_term:
            return True
        if term == last_term:
            return index >= self.last_index()
        return False

    def try_commit(self, index: int, term: int) -> bool:
        if index <= self.committed:
            return False
        try:
            lterm = self.term(index)
        except ErrCompacted:
            lterm = 0
        if index > self.committed and lterm == term:
            self.commit_to(index)
            return True
        return False

    def get_uncommitted_entries(self) -> List[Entry]:
        last = self.inmem.marker_index + len(self.inmem.entries)
        if last <= self.committed + 1:
            return []
        low = max(self.committed + 1, self.inmem.marker_index)
        return self.inmem.get_entries(low, last)

    def restore(self, ss: Snapshot) -> None:
        self.inmem.restore(ss)
        self.committed = ss.index
        self.processed = ss.index
