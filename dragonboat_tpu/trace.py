"""Sampled latency profiler for the execution engine hot loop.

cf. reference trace.go:29-162: bounded percentile samples (p50/p99/p999)
per pipeline stage, recorded every `sample_ratio` iterations so the
steady-state cost is one time.monotonic() pair per stage only on sampled
iterations, nothing otherwise. Dumped via logger at engine stop
(cf. execengine.go:197-211).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional


class Sample:
    """Bounded sample with cheap percentiles (cf. trace.go:29-96)."""

    __slots__ = ("name", "_vals", "_cap")

    def __init__(self, name: str, cap: int = 50_000) -> None:
        self.name = name
        self._vals: List[float] = []
        self._cap = cap

    def record(self, v: float) -> None:
        if len(self._vals) < self._cap:
            self._vals.append(v)

    def __len__(self) -> int:
        return len(self._vals)

    def percentile(self, p: float) -> float:
        if not self._vals:
            return 0.0
        s = sorted(self._vals)
        k = min(len(s) - 1, max(0, int(p * len(s))))
        return s[k]

    def mean(self) -> float:
        return sum(self._vals) / len(self._vals) if self._vals else 0.0

    def report(self) -> str:
        return (
            f"{self.name}: n={len(self._vals)} mean={self.mean()*1e6:.1f}us "
            f"p50={self.percentile(0.50)*1e6:.1f}us "
            f"p99={self.percentile(0.99)*1e6:.1f}us "
            f"p999={self.percentile(0.999)*1e6:.1f}us"
        )


STAGES = ("step", "fast_apply", "send", "save", "apply", "exec")


class Profiler:
    """Per-worker stage profiler (cf. trace.go:98-162 profiler; stages match
    the reference's propose/step/save/cs/exec breakdown plus our apply).
    Stage names are open-ended: the vector engine records its own pipeline
    (pack/dev/place/send/save/apply/notify), the scalar engine the classic
    set — samples are created on first use."""

    def __init__(self, sample_ratio: int = 16) -> None:
        self.ratio = max(1, sample_ratio)
        self._iter = 0
        self.sampling = False
        self.samples: Dict[str, Sample] = {s: Sample(s) for s in STAGES}
        self.batched_groups = Sample("batched_groups")
        self._t0: Optional[float] = None

    def new_iteration(self, n_groups: int = 0) -> None:
        self._iter += 1
        self.sampling = self._iter % self.ratio == 0
        if self.sampling and n_groups:
            self.batched_groups.record(float(n_groups))

    def start(self) -> None:
        if self.sampling:
            self._t0 = time.monotonic()

    def end(self, stage: str) -> None:
        if self.sampling and self._t0 is not None:
            s = self.samples.get(stage)
            if s is None:
                s = self.samples[stage] = Sample(stage)
            s.record(time.monotonic() - self._t0)
            self._t0 = None

    def report(self) -> str:
        lines = [s.report() for s in self.samples.values() if len(s)]
        if len(self.batched_groups):
            lines.append(
                f"batched_groups: mean={self.batched_groups.mean():.1f} "
                f"p99={self.batched_groups.percentile(0.99):.0f}"
            )
        return "\n".join(lines)

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Machine-readable stage costs (mean/p99 in seconds + sample n);
        bench.py folds the top stages into its JSON line."""
        out: Dict[str, Dict[str, float]] = {}
        for name, s in self.samples.items():
            if len(s):
                out[name] = {
                    "n": float(len(s)),
                    "mean_s": s.mean(),
                    "p99_s": s.percentile(0.99),
                    "total_s": s.mean() * len(s) * self.ratio,
                }
        return out

    def top_stages(self, k: int = 3) -> List[str]:
        """Stage names by estimated total cost, descending."""
        sm = self.summary()
        return sorted(sm, key=lambda n: -sm[n]["total_s"])[:k]


__all__ = ["Sample", "Profiler", "STAGES"]
